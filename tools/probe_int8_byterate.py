"""Same-session byte-rate shootout for the int8 decode matmul designs.

Round-5 question (VERDICT #1): the round-4 kernel streams ~270-380 GB/s of
int8 bytes where XLA's bf16 pipeline reaches ~670 GB/s at 7B shapes. Root
cause hypothesis: the row-major [K, N] weight layout makes every (bk, bn)
tile DMA read only bn contiguous BYTES per row (256 B at the shipped
panel), below HBM burst efficiency; bf16 rows are 2x longer for the same
panel. Candidates measured here, all on the 7B MLP chain
[1,4096]@[4096,22016] -> [1,22016]@[22016,4096]:

  bf16        — plain XLA bf16 matmuls (the 670 GB/s reference pipeline)
  row-major   — shipping kernel (full-K x 256 panels on a [K, N] weight)
  tiled-256   — tile_rowwise layout, contiguous full-K x 256 tiles
  tiled-512   — same, 512-wide tiles (contiguity may flip the 256-vs-512
                panel answer: fewer, larger linear reads)
  w8a8-xla    — dynamic per-token activation quant + native int8 x int8
                lax.dot_general (no Pallas; XLA streams int8 natively)
  w8a16-xla   — x @ q.astype(bf16): the convert-materializes case the
                kernel exists to beat (sanity lower bound)

Per tpu-tunnel discipline: one process, adjacent runs, element fence via
float(), best-of-3 windows sized >> the ~100 ms tunnel RTT.

Writes tools/probe_int8_byterate.json.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.int8_matmul import int8_matmul, tile_rowwise

D, F2 = 4096, 22016
R = 1024
INT8_BYTES = D * F2 + F2 * D            # per chain iter
BF16_BYTES = 2 * INT8_BYTES


def window(run, x0, reps=3):
    float(jnp.sum(run(x0)))              # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        float(jnp.sum(run(x0)))
        best = min(best, time.time() - t0)
    return best


def main():
    rng = np.random.default_rng(0)
    q1 = jnp.asarray(rng.integers(-127, 128, (D, F2), dtype=np.int8))
    q2 = jnp.asarray(rng.integers(-127, 128, (F2, D), dtype=np.int8))
    # unit-gain scales keep the R-step chain in bf16 range (same trick as
    # the engine's panel autotune)
    s1 = jnp.full((D,), 1.0 / (73.0 * np.sqrt(D)), jnp.float32)
    s2 = jnp.full((F2,), 1.0 / (73.0 * np.sqrt(F2)), jnp.float32)
    w1 = (q1.astype(jnp.float32) * s1[:, None]).astype(jnp.bfloat16)
    w2 = (q2.astype(jnp.float32) * s2[:, None]).astype(jnp.bfloat16)
    x0 = jnp.ones((1, D), jnp.bfloat16)

    results = {}

    def record(name, fn, weight_bytes, ws, *, block=None):
        # weights ride as jit ARGUMENTS (``ws``), not closure constants:
        # baked-in constants ship inside the program to the tunnel's
        # remote-compile endpoint and 360 MB of bf16 trips its request
        # cap (HTTP 413)
        try:
            def loop(x, ws):
                def body(i, x):
                    return fn(fn(x, 0, ws), 1, ws)
                return jax.lax.fori_loop(0, R, body, x)
            jitted = jax.jit(loop)
            t = window(lambda x: jitted(x, ws), x0)
            gbs = weight_bytes * R / t / 1e9
            results[name] = {"window_s": round(t, 4),
                             "weight_GBps": round(gbs, 1)}
            if block:
                results[name]["block"] = block
            print(f"{name:12s} {t*1e3:9.1f} ms  {gbs:7.1f} GB/s weight bytes")
        except Exception as e:                      # noqa: BLE001
            results[name] = {"error": repr(e)[:200]}
            print(f"{name:12s} FAILED: {e!r}")

    # --- bf16 XLA reference pipeline
    record("bf16", lambda x, i, ws: x @ ws[i], BF16_BYTES, (w1, w2))

    # --- shipping row-major kernel
    record("row-major",
           lambda x, i, ws: int8_matmul(x, ws[2 * i], ws[2 * i + 1],
                                        out_dtype=jnp.bfloat16),
           INT8_BYTES, (q1, s1, q2, s2))

    # --- tiled layouts (block_k=None takes the production default per K;
    # smaller explicit block_k trades the full-K accumulator economy for
    # more outstanding DMAs — the pipelining-depth axis)
    # NB: every bn must divide both N=22016 and N=4096 (tile_rowwise
    # asserts); 768 does not — it crashed a round-5 probe run
    for bn, bk in ((256, 2048), (512, 2048), (512, 4096), (512, 1024)):
        t1 = tile_rowwise(q1, s1, block_k=bk, block_n=bn)
        t2 = tile_rowwise(q2, s2, block_k=bk, block_n=bn)
        record(f"tiled-{bn}" + ("" if bk is None else f"x{bk}"),
               lambda x, i, ws: int8_matmul(
                   x, ws[2 * i], ws[2 * i + 1], out_dtype=jnp.bfloat16),
               INT8_BYTES, (t1[0], t1[1], t2[0], t2[1]),
               block=[list(t1[0].shape), list(t2[0].shape)])

    # --- XLA-native int8 x int8 with dynamic activation quant
    def w8a8(x, i, ws):
        q, s = ws[2 * i], ws[2 * i + 1]
        xs = x.astype(jnp.float32) * s[None, :]
        ax = jnp.max(jnp.abs(xs), axis=1, keepdims=True) / 127.0
        ax = jnp.maximum(ax, 1e-30)
        xi = jnp.clip(jnp.round(xs / ax), -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(xi, q, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.int32)
        return (y.astype(jnp.float32) * ax).astype(jnp.bfloat16)
    record("w8a8-xla", w8a8, INT8_BYTES, (q1, s1, q2, s2))

    # --- convert-materializing sanity case
    def w8a16(x, i, ws):
        q, s = ws[2 * i], ws[2 * i + 1]
        xs = (x.astype(jnp.float32) * s[None, :]).astype(jnp.bfloat16)
        return xs @ q.astype(jnp.bfloat16)
    record("w8a16-xla", w8a16, INT8_BYTES, (q1, s1, q2, s2))

    out = {"shapes": {"D": D, "F2": F2, "R": R},
           "backend": jax.default_backend(),
           "results": results}
    with open("tools/probe_int8_byterate.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out["results"], indent=1))


if __name__ == "__main__":
    main()
