"""Compiled evidence for the multi-chip no-remat projection (VERDICT r2 #3).

AOT-compiles the 770M fused train step on virtual CPU meshes at dp=2/4/8
with the remat policies the single chip cannot hold (no-remat, save_mlp)
and reports ``compiled.memory_analysis()`` per-device peaks — turning
docs/PERF_ANALYSIS.md's "multi-chip ZeRO frees the optimizer states"
projection from prose into numbers: does each config fit a 15.75 GB v5e
chip / a 95 GB v5p chip, and what MFU does the step model project?

Run (takes tens of minutes of XLA CPU compile on one core):
    python tools/multichip_memory_analysis.py [--quick]
Writes MULTICHIP_MEM.json at the repo root.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge  # noqa: E402

if xla_bridge._backends:
    xla_bridge._clear_backends()
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
from deepspeed_tpu.utils.jax_compat import set_mesh  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel  # noqa: E402
from deepspeed_tpu.models.llama import loss_fn as lm_loss  # noqa: E402
from deepspeed_tpu.parallel.mesh import make_mesh  # noqa: E402
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig  # noqa: E402
from deepspeed_tpu.runtime.zero.stages import (  # noqa: E402
    opt_state_shardings, plan_zero_shardings,
)

V5E_HBM = 15.75e9
V5P_HBM = 95e9
# measured single-chip facts (docs/PERF_ANALYSIS.md round 2)
MEASURED_MFU_BLOCK_REMAT = 0.4173     # whole-block remat, 16x512
MATMUL_EFF = 0.72                     # fused-loop matmul ceiling on chip
REMAT_RECOMPUTE = {                   # extra executed FLOPs over 6NP model
    "none": 0.0,                      # fwd(2) + bwd(4) only
    "save_mlp": 0.2,                  # re-runs ~60% of the forward (attn path)
    "block_nothing": 1.0 / 3.0,       # re-runs the WHOLE forward: 8NP/6NP
}


def model_cfg(remat_case: str) -> LlamaConfig:
    base = dict(vocab_size=32000, hidden_size=1536, intermediate_size=4096,
                num_layers=24, num_heads=24, num_kv_heads=24,
                max_seq_len=2048, dtype=jnp.bfloat16, scan_layers=True)
    if remat_case == "none":
        return LlamaConfig(**base, remat=False)
    if remat_case == "save_mlp":
        return LlamaConfig(**base, remat=True, remat_scope="block",
                           remat_policy="save_mlp")
    return LlamaConfig(**base, remat=True, remat_scope="block",
                       remat_policy="nothing_saveable")


def analyze(dp: int, remat_case: str, micro_per_chip: int = 16,
            seq: int = 512, zero_stage: int = 1):
    cfg = model_cfg(remat_case)
    model = LlamaModel(cfg)
    devices = np.array(jax.devices()[:dp]).reshape(1, dp, 1, 1, 1, 1)
    mesh = Mesh(devices, ("pipe", "data", "expert", "mics", "sequence",
                          "tensor"))
    zc = DeepSpeedZeroConfig(stage=zero_stage)

    abstract = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, seq), jnp.int32))["params"],
        jax.random.PRNGKey(0))
    plan = plan_zero_shardings(abstract, mesh, zc)
    optimizer = optax.chain(optax.clip_by_global_norm(1.0),
                            optax.adamw(1e-4))
    abs_opt = jax.eval_shape(optimizer.init, abstract)
    opt_sh = opt_state_shardings(abs_opt, abstract, plan, mesh)

    B = micro_per_chip * dp
    bspec = NamedSharding(mesh, PartitionSpec("data"))

    def train_step(params, opt_state, batch):
        def loss(p):
            logits = model.apply({"params": p}, batch["input_ids"])
            return lm_loss(logits, batch["labels"])

        l, grads = jax.value_and_grad(loss)(params)
        grads = jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, grads, plan.grad_specs)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt, l

    def with_sh(tree, sh_tree):
        return jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            tree, sh_tree)

    abs_params = with_sh(abstract, plan.param_shardings)
    abs_opt_sh = jax.tree_util.tree_map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s)
        if hasattr(a, "shape") and s is not None else
        jax.ShapeDtypeStruct(a.shape, a.dtype), abs_opt, opt_sh)
    abs_batch = {
        "input_ids": jax.ShapeDtypeStruct((B, seq), jnp.int32,
                                          sharding=bspec),
        "labels": jax.ShapeDtypeStruct((B, seq), jnp.int32, sharding=bspec),
    }

    t0 = time.time()
    with set_mesh(mesh):
        lowered = jax.jit(train_step, donate_argnums=(0, 1)).lower(
            abs_params, abs_opt_sh, abs_batch)
        compiled = lowered.compile()
    ma = compiled.memory_analysis()
    compile_s = time.time() - t0

    # per-device live peak ≈ resident args (params+opt, donated/aliased) +
    # temporaries (activations, grads, workspaces) + outputs beyond aliases
    args = ma.argument_size_in_bytes
    temp = ma.temp_size_in_bytes
    out = ma.output_size_in_bytes
    alias = ma.alias_size_in_bytes
    peak = args + temp + max(out - alias, 0)

    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(abstract))
    # step model: measured MFU counts MODEL flops (6NP); with whole-block
    # remat the chip executes 8NP. Removing recompute shrinks executed
    # flops while hardware efficiency stays the measured one:
    #   proj = measured * (1 + recompute_block) / (1 + recompute_case)
    extra = REMAT_RECOMPUTE[remat_case]
    proj_mfu = MEASURED_MFU_BLOCK_REMAT \
        * (1 + REMAT_RECOMPUTE["block_nothing"]) / (1 + extra)
    return {
        "dp": dp, "remat": remat_case, "zero_stage": zero_stage,
        "micro_per_chip": micro_per_chip, "seq": seq,
        "per_device": {
            "argument_bytes": int(args), "temp_bytes": int(temp),
            "output_bytes": int(out), "alias_bytes": int(alias),
            "est_peak_bytes": int(peak),
            "est_peak_gb": round(peak / 1e9, 2),
        },
        "fits_v5e": bool(peak < V5E_HBM * 0.92),   # 8% runtime headroom
        "fits_v5p": bool(peak < V5P_HBM * 0.92),
        "projected_mfu": round(proj_mfu, 4),
        "n_params": n_params,
        "compile_s": round(compile_s, 1),
    }


def main():
    quick = "--quick" in sys.argv
    # (dp, remat, micro_per_chip): per-chip activations do NOT shard with
    # dp, so the no-remat/save_mlp rows also probe smaller per-chip micro
    # batches — the real tradeoff surface on HBM-limited chips
    cases = ([(8, "none", 16)] if quick else
             [(2, "none", 16), (4, "none", 16), (8, "none", 16),
              (8, "none", 4), (8, "none", 2),
              (4, "save_mlp", 16), (8, "save_mlp", 16), (8, "save_mlp", 8),
              (8, "save_mlp", 4), (8, "block_nothing", 16)])
    rows = []
    for dp, remat, micro in cases:
        print(f"compiling dp={dp} remat={remat} micro={micro} ...",
              flush=True)
        try:
            row = analyze(dp, remat, micro_per_chip=micro)
        except Exception as e:
            row = {"dp": dp, "remat": remat, "micro_per_chip": micro,
                   "error": str(e)[:500]}
        rows.append(row)
        print(json.dumps(row), flush=True)
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MULTICHIP_MEM.json")
    with open(out_path, "w") as f:
        json.dump({"note": "770M fused train step AOT-compiled on virtual "
                           "CPU meshes; per-device XLA memory analysis",
                   "measured_single_chip_mfu": MEASURED_MFU_BLOCK_REMAT,
                   "rows": rows}, f, indent=2)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
