"""AOT memory probe: does the streamed offload TRAIN STEP fit HBM at 7B?

The 7B capacity attempt OOMed in jit(init_fn) (the full fp32 stacked tree
materializes in HBM before the host copy). Init can be fixed by feeding
host-built params; the open question is the step program: the backward
scan accumulates the stacked fp32 grad tree (27 GB) — does XLA place that
accumulation in host space (out_shardings pinned_host) or in HBM?

Compiles the engine-shaped grads program with abstract inputs and prints
the compiler's memory analysis. No data, no init — just the answer.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.models.llama import (
    LlamaConfig, LlamaModel, StreamedLlamaModel, loss_fn as lm_loss,
)
from deepspeed_tpu.parallel.mesh import make_mesh

H, F, L, HEADS = 4096, 11008, 32, 32
VOCAB, BS, SEQ = 32000, 4, 512


def main():
    cfg = LlamaConfig(
        vocab_size=VOCAB, hidden_size=H, intermediate_size=F, num_layers=L,
        num_heads=HEADS, num_kv_heads=HEADS, max_seq_len=SEQ,
        dtype=jnp.bfloat16, remat=True, remat_policy="nothing_saveable",
        remat_scope="block", scan_layers=True)
    mesh = make_mesh(dims={"pipe": 1, "data": 1, "expert": 1,
                           "sequence": 1, "tensor": 1})
    host = NamedSharding(mesh, P(), memory_kind="pinned_host")
    dev = NamedSharding(mesh, P())

    model = LlamaModel(cfg)
    ids0 = jnp.zeros((BS, SEQ), jnp.int32)
    abstract = jax.eval_shape(
        lambda r: model.init(r, ids0)["params"], jax.random.PRNGKey(0))
    host_sh = jax.tree_util.tree_map(lambda _: host, abstract)
    # streamed twin: device shardings per slice
    stream_sh = jax.tree_util.tree_map(lambda _: dev, abstract)
    streamed = StreamedLlamaModel(cfg, stream_sh)

    def loss(params, batch):
        logits = streamed.apply({"params": params}, batch["input_ids"])
        return lm_loss(logits, batch["labels"])

    def grads_fn(params, batch):
        l, g = jax.value_and_grad(loss)(params, batch)
        return l, g

    batch_abs = {"input_ids": jax.ShapeDtypeStruct((BS, SEQ), jnp.int32),
                 "labels": jax.ShapeDtypeStruct((BS, SEQ), jnp.int32)}
    params_abs = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=host),
        abstract)
    lowered = jax.jit(
        grads_fn,
        in_shardings=(host_sh, {"input_ids": dev, "labels": dev}),
        out_shardings=(dev, host_sh),
    ).lower(params_abs, batch_abs)
    try:
        compiled = lowered.compile()
        ma = compiled.memory_analysis()
        print(json.dumps({
            "fits": True,
            "temp_gb": round(ma.temp_size_in_bytes / 1e9, 2),
            "argument_gb": round(ma.argument_size_in_bytes / 1e9, 2),
            "output_gb": round(ma.output_size_in_bytes / 1e9, 2),
        }))
    except Exception as e:
        msg = str(e)
        import re
        m = re.search(r"Ran out of memory in memory space hbm[^\n]*"
                      r"|Largest program allocations[\s\S]{0,2000}", msg)
        print(json.dumps({"fits": False,
                          "error": m.group(0) if m else msg[-2000:]}))


if __name__ == "__main__":
    main()
