// Host Adam/AdamW over flat float buffers — analogue of the reference's
// AVX-vectorized csrc/adam/cpu_adam.cpp used by ZeRO-Offload. Written as
// simple strided loops that g++ -O3 -march=native auto-vectorizes (the
// image's GCC emits AVX2/AVX-512 where available), parallelized over
// shards by the caller's thread pool (ops/aio.py reuses its workers).
//
// Build: g++ -O3 -march=native -shared -fPIC cpu_adam.cpp -o libdstpu_adam.so

#include <cmath>
#include <cstdint>

extern "C" {

// One fused Adam(W) step over a contiguous fp32 shard.
//   params/grads/exp_avg/exp_avg_sq: length n
//   step: 1-based step count (for bias correction)
//   adamw_mode: 1 → decoupled weight decay (AdamW), 0 → L2 into grads
void dstpu_cpu_adam_step(float* params, const float* grads, float* exp_avg,
                         float* exp_avg_sq, long long n, int step, float lr,
                         float beta1, float beta2, float eps,
                         float weight_decay, int adamw_mode) {
  const float bc1 = 1.0f - std::pow(beta1, (float)step);
  const float bc2 = 1.0f - std::pow(beta2, (float)step);
  const float step_size = lr / bc1;
  const float bc2_sqrt = std::sqrt(bc2);
  const float om_beta1 = 1.0f - beta1;
  const float om_beta2 = 1.0f - beta2;

  if (adamw_mode && weight_decay > 0.0f) {
    const float decay = 1.0f - lr * weight_decay;
    for (long long i = 0; i < n; ++i) params[i] *= decay;
  }

#pragma GCC ivdep
  for (long long i = 0; i < n; ++i) {
    float g = grads[i];
    if (!adamw_mode && weight_decay > 0.0f) g += weight_decay * params[i];
    float m = exp_avg[i] = beta1 * exp_avg[i] + om_beta1 * g;
    float v = exp_avg_sq[i] = beta2 * exp_avg_sq[i] + om_beta2 * g * g;
    params[i] -= step_size * m / (std::sqrt(v) / bc2_sqrt + eps);
  }
}

// Adagrad variant (reference csrc/adagrad/cpu_adagrad.cpp).
void dstpu_cpu_adagrad_step(float* params, const float* grads, float* sq_sum,
                            long long n, float lr, float eps,
                            float weight_decay) {
#pragma GCC ivdep
  for (long long i = 0; i < n; ++i) {
    float g = grads[i];
    if (weight_decay > 0.0f) g += weight_decay * params[i];
    sq_sum[i] += g * g;
    params[i] -= lr * g / (std::sqrt(sq_sum[i]) + eps);
  }
}

}  // extern "C"
