// Async block I/O thread pool — TPU-host analogue of the reference's
// libaio-based csrc/aio (deepspeed_py_aio_handle.cpp): a submission queue of
// pread/pwrite requests served by worker threads, used by the tensor-swap
// layer (ZeRO-Infinity NVMe offload) to overlap disk traffic with device
// compute. Plain C API for ctypes binding (no pybind11 in this image).
//
// Build: g++ -O3 -shared -fPIC -pthread aio.cpp -o libdstpu_aio.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
  int64_t id;
  bool is_write;
  std::string path;
  void* buffer;
  size_t nbytes;
  size_t offset;
};

struct Completion {
  int64_t id;
  int64_t result;  // bytes moved, or -errno
};

class AioHandle {
 public:
  AioHandle(int block_size, int queue_depth, int thread_count)
      : block_size_(block_size <= 0 ? (1 << 20) : block_size),
        queue_depth_(queue_depth <= 0 ? 8 : queue_depth),
        stop_(false),
        next_id_(1),
        inflight_(0) {
    int n = thread_count <= 0 ? 1 : thread_count;
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { this->worker_loop(); });
    }
  }

  ~AioHandle() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int64_t submit(bool is_write, const char* path, void* buffer, size_t nbytes,
                 size_t offset) {
    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_id_++;
    queue_.push_back(Request{id, is_write, path, buffer, nbytes, offset});
    ++inflight_;
    inflight_ids_.insert(id);
    cv_.notify_one();
    return id;
  }

  // Blocks until every submitted request completes; returns number of
  // failures (0 == clean).
  int64_t wait_all() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] { return inflight_ == 0; });
    int64_t failures = 0;
    for (const auto& c : completions_) {
      if (c.result < 0) ++failures;
    }
    completions_.clear();
    return failures;
  }

  // Blocks until every request with id <= max_id completes (ids are
  // submission-ordered, so this drains one caller's earlier batch without
  // serializing unrelated later submissions). Returns failures among the
  // drained completions, which are consumed.
  int64_t wait_upto(int64_t max_id) {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this, max_id] {
      return inflight_ids_.empty() || *inflight_ids_.begin() > max_id;
    });
    int64_t failures = 0;
    auto it = completions_.begin();
    while (it != completions_.end()) {
      if (it->id <= max_id) {
        if (it->result < 0) ++failures;
        it = completions_.erase(it);
      } else {
        ++it;
      }
    }
    return failures;
  }

  int64_t pending() {
    std::unique_lock<std::mutex> lk(mu_);
    return inflight_;
  }

 private:
  void worker_loop() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        req = queue_.front();
        queue_.pop_front();
      }
      int64_t result = execute(req);
      {
        std::unique_lock<std::mutex> lk(mu_);
        completions_.push_back(Completion{req.id, result});
        --inflight_;
        inflight_ids_.erase(req.id);
        done_cv_.notify_all();
      }
    }
  }

  int64_t execute(const Request& req) {
    int flags = req.is_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return -1;
    size_t moved = 0;
    const size_t chunk = static_cast<size_t>(block_size_);
    char* buf = static_cast<char*>(req.buffer);
    while (moved < req.nbytes) {
      size_t len = std::min(chunk, req.nbytes - moved);
      ssize_t rc =
          req.is_write
              ? ::pwrite(fd, buf + moved, len, req.offset + moved)
              : ::pread(fd, buf + moved, len, req.offset + moved);
      if (rc < 0) {
        ::close(fd);
        return -1;
      }
      if (rc == 0) break;  // EOF on read
      moved += static_cast<size_t>(rc);
    }
    ::close(fd);
    return static_cast<int64_t>(moved);
  }

  int block_size_;
  int queue_depth_;
  bool stop_;
  int64_t next_id_;
  int64_t inflight_;
  std::set<int64_t> inflight_ids_;
  std::deque<Request> queue_;
  std::vector<Completion> completions_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
};

}  // namespace

extern "C" {

void* dstpu_aio_create(int block_size, int queue_depth, int thread_count) {
  return new AioHandle(block_size, queue_depth, thread_count);
}

void dstpu_aio_destroy(void* handle) {
  delete static_cast<AioHandle*>(handle);
}

long long dstpu_aio_pwrite(void* handle, const char* path, void* buffer,
                           long long nbytes, long long offset) {
  return static_cast<AioHandle*>(handle)->submit(true, path, buffer,
                                                 (size_t)nbytes, (size_t)offset);
}

long long dstpu_aio_pread(void* handle, const char* path, void* buffer,
                          long long nbytes, long long offset) {
  return static_cast<AioHandle*>(handle)->submit(false, path, buffer,
                                                 (size_t)nbytes, (size_t)offset);
}

long long dstpu_aio_wait(void* handle) {
  return static_cast<AioHandle*>(handle)->wait_all();
}

long long dstpu_aio_wait_upto(void* handle, long long max_id) {
  return static_cast<AioHandle*>(handle)->wait_upto(max_id);
}

long long dstpu_aio_pending(void* handle) {
  return static_cast<AioHandle*>(handle)->pending();
}

}  // extern "C"
