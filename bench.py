"""Benchmark: flagship-model training throughput on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: training tokens/sec/chip on a LLaMA-style decoder sized to fit the
chip, ZeRO/bf16 fused train step (the BASELINE.json "ZeRO-3 tokens/sec/chip"
family — single-chip proxy until multi-chip hardware is attached).
vs_baseline compares achieved model FLOPs/s against the reference's
49 TFLOPs/GPU ZeRO-3 claim (BASELINE.md: 512×V100 ZeRO-3 Offload sustained),
scaled as MFU ratio: (our MFU) / (49/125 V100-peak MFU).
"""

import json
import os
import sys
import time

import numpy as np


def time_best(window_fn, windows: int) -> float:
    """Best-of-N timing windows (the tunnel chip's throughput varies run to
    run; the minimum measures the hardware, not the noise). ``window_fn``
    runs one full window and must block on completion before returning
    (host transfer — block_until_ready alone can lie through the tunnel)."""
    best = float("inf")
    for _ in range(windows):
        t0 = time.time()
        window_fn()
        best = min(best, max(time.time() - t0, 1e-6))
    return best


def inference_main(int8: bool = False, batch_size: int = 1,
                   stream: bool = False, panel=None, kv8: bool = False):
    """--inference [--int8] [--batch N]: fused-generation decode benchmark —
    TTFT (p50) and decode tokens/s on the flagship model (the DS-Inference
    headline family; reference kernels csrc/transformer/inference/).
    ``--batch N`` measures throughput serving: decode is weight-streaming
    bound, so tokens/s scales ~linearly with batch until compute binds."""
    if kv8 and not (int8 and stream):
        # quant.kv_cache only reaches the config on the int8-streaming
        # path; a bf16 run labeled _kv8 would corrupt the A/B records
        sys.exit("--kv8 requires --int8 --stream")
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, scan_layers=True)
        batch, prompt_len, gen_len = batch_size, 512, 128
    else:
        cfg = LlamaConfig.tiny(dtype=jnp.float32)
        if batch_size > 1:
            print(f"# --batch {batch_size} ignored on the off-TPU smoke path",
                  file=sys.stderr)
        batch, prompt_len, gen_len = 1, 16, 8

    model = LlamaModel(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, prompt_len))
    params = jax.jit(
        lambda r: model.init(r, jnp.asarray(ids))["params"])(
        jax.random.PRNGKey(0))
    config = {"dtype": "bfloat16" if on_tpu else "float32",
              "tensor_parallel": {"tp_size": 1}}
    if int8:
        config["quant"] = {"enabled": True, "bits": 8, "group_size": 128,
                           "streaming": stream,
                           **({"kv_cache": True} if kv8 else {}),
                           **({"block_n": panel} if panel else {}),
                           # w8a8 prefill is opt-in since the default
                           # flip (per-token activation rounding is a
                           # numerics change); --no-w8a8 still forces it
                           # off for A/B hygiene
                           **({"w8a8_prefill": True}
                              if "--w8a8" in sys.argv else {}),
                           **({"w8a8_prefill": False}
                              if "--no-w8a8" in sys.argv else {})}
    engine = deepspeed_tpu.init_inference(model=model, config=config,
                                          params=params, model_config=cfg)

    # NOTE: through the axon tunnel block_until_ready can return before
    # execution; an element transfer (int()) is the only honest fence.
    def run_blocking(n):
        toks = engine.generate(ids, max_new_tokens=n)
        return int(toks[0, -1])

    run_blocking(gen_len)   # compile long program
    run_blocking(1)         # compile TTFT program

    # TTFT: prefill + first token (p50 of several runs). Through the axon
    # tunnel every blocking fence pays one client<->chip round trip
    # (~100 ms measured) that is transport, not model latency — measure it
    # with a transfer of an already-materialized scalar and report TTFT
    # net of it (raw + rtt kept in detail).
    ready = jnp.zeros((), jnp.int32) + 1
    int(ready)
    rtts = []
    for _ in range(5):
        t0 = time.time()
        int(ready + 0)          # fresh tiny dispatch + transfer
        rtts.append(time.time() - t0)
    rtt_p50 = sorted(rtts)[len(rtts) // 2]

    ttfts = []
    for _ in range(5):
        engine.reset_cache()
        t0 = time.time()
        run_blocking(1)
        ttfts.append(time.time() - t0)
    ttft_raw_p50 = sorted(ttfts)[len(ttfts) // 2]
    ttft_p50 = max(ttft_raw_p50 - rtt_p50, 1e-4)

    # decode throughput: long generation minus the separately-measured
    # prefill+first-token time, so the metric really is decode tokens/s
    best = 0.0
    for _ in range(3):
        engine.reset_cache()
        t0 = time.time()
        run_blocking(gen_len)
        # subtract the RAW ttft (incl. its round trip) so this window's own
        # round trip cancels and dt is pure decode time
        dt = max(time.time() - t0 - ttft_raw_p50, 1e-6)
        best = max(best, batch * (gen_len - 1) / dt)

    n_params = sum(
        x.size for x in jax.tree_util.tree_leaves(engine.params))
    # decode is weight-streaming-bound PER STEP: one weight pass serves the
    # whole batch, so utilization = (decode steps/s) * weight bytes over
    # the ACHIEVABLE single-row matvec bandwidth. Measured on this chip
    # (docs/PERF_ANALYSIS.md): the full decode program streams ~420 GB/s
    # effective against a ~450 GB/s achievable matvec ceiling — the
    # nominal 819 GB/s HBM figure is not reachable for [1,K]x[K,N] shapes,
    # so utilization against it understates how close decode is to its
    # real ceiling (kept in detail as hbm_util_nominal). Plain int8
    # storage is dequantized ONCE per generation (capacity win), so that
    # decode loop still streams bf16: 2 bytes/param. With quant.streaming
    # the decode matmuls read int8 through the Pallas kernel: 1 byte/param.
    bytes_per_param = 1 if (int8 and stream) else 2
    MATVEC_BW = 450e9
    steps_per_sec = best / batch
    stream_rate = n_params * bytes_per_param * steps_per_sec
    hbm_util = stream_rate / MATVEC_BW if on_tpu else 0.0
    hbm_util_nominal = stream_rate / 819e9 if on_tpu else 0.0
    print(json.dumps({
        "metric": "llama770m_decode_tokens_per_sec"
                  + ("_int8" if int8 else "")
                  + ("_stream" if (int8 and stream) else "")
                  + ("_kv8" if kv8 else "")
                  + (f"_b{batch}" if batch > 1 else ""),
        "value": round(best, 1),
        "unit": "tokens/s",
        "vs_baseline": round(hbm_util, 3),
        "detail": {"ttft_p50_ms": round(ttft_p50 * 1e3, 1),
                   "ttft_raw_p50_ms": round(ttft_raw_p50 * 1e3, 1),
                   "tunnel_rtt_p50_ms": round(rtt_p50 * 1e3, 1),
                   "matvec_bw_utilization": round(hbm_util, 3),
                   "hbm_util_nominal": round(hbm_util_nominal, 3),
                   "batch": batch, "prompt_len": prompt_len,
                   "gen_len": gen_len, "params": int(n_params),
                   "weight_stream_GBps": round(stream_rate / 1e9, 1),
                   "int8": int8, "int8_streaming": bool(int8 and stream),
                   "int8_tiled": bool(int8 and stream
                                      and engine._config.quant.tiled),
                   "int8_panel": getattr(engine._decoder, "int8_block_n",
                                         None) if (int8 and stream) else None,
                   "int8_panel_trace": getattr(engine,
                                               "_int8_panel_detail", None),
                   "backend": jax.default_backend()},
    }))


def pld_main():
    """--inference --pld: prompt-lookup speculative decode on a STRUCTURED
    prompt (a repeated document — the favorable case this feature exists
    for: summarization/code-edit/RAG workloads where generation repeats
    prompt spans). Greedy acceptance keeps outputs exactly equal to plain
    greedy decode; reports both rates, the speedup, and mean accepted
    drafts/round. On incompressible prompts acceptance ~0 and the plain
    path wins — documented, not hidden (PERF_ANALYSIS decode section)."""
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(dtype=jnp.bfloat16, **BASE_770M_KWARGS)
        prompt_len, gen_len, K = 512, 128, 8
    else:
        cfg = LlamaConfig.tiny(dtype=jnp.float32)
        prompt_len, gen_len, K = 32, 16, 6

    model = LlamaModel(cfg)
    rng = np.random.default_rng(0)
    # structured prompt: one 32-token "document" repeated — the greedy
    # continuation reproduces document spans, which is what lookup drafts
    unit = rng.integers(0, cfg.vocab_size, size=(1, 32))
    ids = np.tile(unit, (1, prompt_len // 32))[:, :prompt_len]
    params = jax.jit(
        lambda r: model.init(r, jnp.asarray(ids))["params"])(
        jax.random.PRNGKey(0))
    engine = deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "bfloat16" if on_tpu else "float32"})

    def run(speculative=None):
        kw = {"speculative": speculative, "draft_len": K} if speculative \
            else {}
        toks = engine.generate(ids, max_new_tokens=gen_len, temperature=0.0,
                               **kw)
        return int(toks[0, -1])

    # pld first: its larger KV arena (+draft_len) rebuilds the decoder and
    # clears the gen cache — compiling plain second keeps both programs live
    run("prompt_lookup"); run()
    t_plain = min(time_best(lambda: run(), 1) for _ in range(3))
    t_pld = min(time_best(lambda: run("prompt_lookup"), 1) for _ in range(3))
    plain_tps = (gen_len - 1) / t_plain
    pld_tps = (gen_len - 1) / t_pld
    print(json.dumps({
        "metric": "llama770m_decode_tokens_per_sec_pld_structured",
        "value": round(pld_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(pld_tps / max(plain_tps, 1e-9), 3),
        "detail": {"plain_tokens_per_sec": round(plain_tps, 1),
                   "mean_accepted_per_round": round(
                       getattr(engine, "last_acceptance", 0.0), 2),
                   "draft_len": K, "prompt": "32-token unit repeated",
                   "prompt_len": prompt_len, "gen_len": gen_len,
                   "note": "greedy-exact; structured-prompt workloads only "
                           "(acceptance ~0 on incompressible prompts)",
                   "backend": jax.default_backend()},
    }))


def assert_traces_equal(a, b):
    """A/B hygiene: both arms must replay the IDENTICAL request sequence
    (prompt tokens, generation budgets, arrival offsets) — seeded trace
    regeneration plus this assert makes that a property of the bench,
    not a hope (bench.py --serve --trace-seed N)."""
    assert len(a) == len(b), (len(a), len(b))
    for (pa, ga, oa), (pb, gb, ob) in zip(a, b):
        assert ga == gb and oa == ob and np.array_equal(pa, pb), \
            "trace replay diverged between arms"


def serve_main(num_slots=None, n_requests=None, decode_chunk=None,
               seed=0, out_path="BENCH_SERVE.json", kernels=None,
               trace_seed=None):
    """--serve: continuous batching (paged KV + slot scheduler) vs the
    static whole-batch baseline on a mixed-length Poisson arrival trace,
    PLUS a same-config attention-kernel A/B (jnp reference gather vs the
    Pallas ragged decode kernel, ``serve.attn_kernel``).

    All serve arms run the SAME engine, weights, trace and slot count:
    the baseline groups requests into arrival-order batches of
    ``num_slots`` and runs ``generate()`` — whole-batch prefill, lockstep
    decode to the LONGEST request in the group (head-of-line blocking);
    the serve arms admit requests into freed slots mid-stream
    (``engine.serve``) with ON-DEMAND block allocation, and differ only
    in the paged-attention arm. Reports aggregate generated tokens/s,
    p50/p95 per-request latency and queue-wait p50/p95 for each arm,
    plus the per-step pool-occupancy time series (blocks allocated vs
    the PR-1 upfront-reservation equivalent, live tokens, stalls) — as
    one JSON line and a JSON artifact (default BENCH_SERVE.json).

    Off-TPU the Pallas arm runs in INTERPRET mode — a correctness/
    plumbing arm whose tokens/s is not a kernel measurement (the artifact
    records the backend so readers can tell); on TPU both arms compile
    and the ratio is the kernel win. ``kernels`` restricts the arms
    (``["reference"]`` / ``["pallas"]``; default both).

    Arms are warmed first (compile paths populated), then timed on a
    fresh arrival clock — the comparison measures scheduling, not XLA
    compile time. Baseline caveat: ragged prompts are left-padded with
    token 0 to the group max (generate() has one attn_start per batch,
    not per row), so its OUTPUTS for shorter rows differ from
    per-request generation; its timing — the thing measured — is exactly
    the lockstep cost a static server pays.
    """
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.inference.scheduler import Request
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, scan_layers=True)
        num_slots = num_slots or 8
        n_requests = n_requests or 48
        decode_chunk = decode_chunk or 8
        block_size = 32
        prompt_lens = (32, 64, 96, 128)
        gen_mix = (16, 32, 64, 160)          # mixed: max/mean ~ 2.4
        mean_gap = 0.05
    else:
        # NOT .tiny(): at toy scale the measurement is per-call dispatch
        # overhead, not scheduling — this size keeps a decode step
        # compute-dominated on the CPU mesh so the benchmark measures the
        # thing the scheduler changes (occupancy), in minutes not hours
        cfg = LlamaConfig(
            vocab_size=4096, hidden_size=512, intermediate_size=1024,
            num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=512,
            dtype=jnp.float32)
        num_slots = num_slots or 4
        n_requests = n_requests or 48
        decode_chunk = decode_chunk or 16
        block_size = 8
        prompt_lens = (6, 10, 17, 25)
        # heavy-tailed mix (max/mean ~ 3.6): the static baseline decodes
        # every group to its slowest member, so the occasional 128-token
        # request stalls three short ones — the head-of-line cost
        # continuous batching exists to remove
        gen_mix = (8, 8, 16, 16, 128)
        mean_gap = 0.004

    model = LlamaModel(cfg)
    rng = np.random.default_rng(seed)
    params = jax.jit(
        lambda r: model.init(
            r, jnp.zeros((1, max(prompt_lens)), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    engine = deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "bfloat16" if on_tpu else "float32"})

    def make_trace(offset_rng):
        """(prompt, gen, arrival_offset) triples: Poisson arrivals
        (exponential gaps), mixed prompt/gen lengths."""
        gaps = offset_rng.exponential(mean_gap, n_requests)
        arrivals = np.cumsum(gaps)
        trace = []
        for i in range(n_requests):
            p_len = int(offset_rng.choice(prompt_lens))
            g_len = int(offset_rng.choice(gen_mix))
            prompt = offset_rng.integers(1, cfg.vocab_size, p_len)
            trace.append((prompt, g_len, float(arrivals[i])))
        return trace

    # --trace-seed: every arm REGENERATES its trace from this seed and
    # the replays are asserted identical — an A/B where the arms saw
    # different request sequences measures the traffic, not the arms
    trace_seed = (seed + 1) if trace_seed is None else int(trace_seed)
    trace = make_trace(np.random.default_rng(trace_seed))
    total_gen = sum(g for _, g, _ in trace)
    kernels = list(kernels or ("reference", "pallas"))

    # --- continuous-batching arms (reference / pallas attention) -------------
    def run_serve(timed: bool, attn_kernel: str, with_trace: bool = True):
        arm_trace = make_trace(np.random.default_rng(trace_seed))
        assert_traces_equal(trace, arm_trace)
        if timed:
            # engine-reported percentiles must describe exactly the
            # timed traffic (no warm-up compile spans in the histogram)
            engine.reset_serve_metrics()
        t0 = time.time() + (0.0 if not timed else 0.01)
        reqs = [Request(rid=i, prompt=p, max_new_tokens=g,
                        arrival_time=(t0 + off) if timed else None)
                for i, (p, g, off) in enumerate(arm_trace)]
        comps = engine.serve(reqs, num_slots=num_slots,
                             block_size=block_size,
                             decode_chunk=decode_chunk,
                             attn_kernel=attn_kernel,
                             record_occupancy=timed,
                             trace=with_trace)
        lat = sorted(c.t_finish - c.t_submit for c in comps)
        ttft = sorted(c.t_first_token - c.t_submit for c in comps)
        qwait = sorted(c.queue_delay for c in comps)
        # bench-side TPOT (time per output token over the decode phase)
        tpot = sorted((c.t_finish - c.t_first_token) / (len(c.tokens) - 1)
                      for c in comps if len(c.tokens) > 1)
        wall = max(c.t_finish for c in comps) - t0
        occ = engine.last_serve_occupancy if timed else None
        preempt = engine.last_serve_scheduler.preemptions
        obs = None
        if timed and with_trace:
            obs = {"metrics": engine.serve_metrics(),
                   "chrome": engine.export_trace(), "tpot": tpot,
                   # bench-side completion accounting for the goodput /
                   # burn-rate cross-checks (dstfleet): delivered tokens
                   # counted from the completions the bench HOLDS, not
                   # from engine counters
                   "delivered_tokens": sum(
                       len(c.tokens) for c in comps
                       if c.status == "COMPLETED"),
                   "ttft_by_status": [(c.status,
                                       c.t_first_token - c.t_submit,
                                       len(c.tokens)) for c in comps]}
        return wall, lat, qwait, occ, preempt, ttft, obs

    arm_results = {}
    # compile-window accounting (dstprof): the PR 3 bench-warmup lesson
    # as a PERMANENT guard — after warm-up, the measured window must
    # compile NOTHING (a mid-measurement compile once read as a
    # prefix-cache slowdown). The CompileWatcher's program table
    # survives reset_serve_metrics(), so warm-up vs window splits are
    # exact even though the timed run zeroes the registry.
    compile_windows = {}
    prev_compiles = engine.compile_obs.compiles_total("serve")
    slo_target = None
    for kern in kernels:
        warm = run_serve(timed=False, attn_kernel=kern)  # warm: compile
        if slo_target is None:
            # dstfleet SLO arm: the TTFT objective is the warm-up run's
            # median, so the timed traffic genuinely splits around it —
            # the burn-rate cross-check then verifies real counting
            # instead of a trivial 0 == 0
            slo_target = float(warm[5][len(warm[5]) // 2])
            engine._config.serve.slo = {
                "ttft_p95_s": slo_target,
                "availability": 0.999,
                "windows_s": [3600.0],      # covers the whole timed run
                "min_interval_s": 0.1,
            }
        warmed = engine.compile_obs.compiles_total("serve")
        arm_results[kern] = run_serve(timed=True, attn_kernel=kern)
        after = engine.compile_obs.compiles_total("serve")
        in_window = after - warmed
        assert in_window == 0, (
            f"{in_window} serve-program compile(s) inside the measured "
            f"window (arm {kern}) — warm-up missed a bucket; the timing "
            f"measures XLA, not scheduling: "
            f"{engine.compile_obs.section()}")
        compile_windows[kern] = {
            "warmup_compiles": warmed - prev_compiles,
            "measured_window_compiles": in_window,
        }
        prev_compiles = after
    cb_wall = arm_results[kernels[0]][0]
    # tracing-overhead arm: the same first-kernel config re-timed with
    # the tracer off — the ratio is the artifact's evidence that span
    # emission at chunk boundaries is noise next to the device work
    notrace_wall = run_serve(timed=True, attn_kernel=kernels[0],
                             with_trace=False)[0]

    # --- static whole-batch baseline -----------------------------------------
    def run_baseline(timed: bool):
        t0 = time.time() + (0.0 if not timed else 0.01)
        lat = []
        end = t0
        for g0 in range(0, n_requests, num_slots):
            group = trace[g0:g0 + num_slots]
            group_arrive = t0 + max(off for _, _, off in group)
            if timed:
                now = time.time()
                if group_arrive > now:
                    time.sleep(group_arrive - now)
            max_p = max(len(p) for p, _, _ in group)
            max_g = max(g for _, g, _ in group)
            ids = np.zeros((len(group), max_p), np.int64)
            for r, (p, _, _) in enumerate(group):
                ids[r, max_p - len(p):] = p      # left-pad ragged prompts
            out = engine.generate(jnp.asarray(ids), max_new_tokens=max_g)
            int(out[0, -1])                      # materialize (honest fence)
            end = time.time()
            if timed:
                lat.extend(end - (t0 + off) for _, _, off in group)
        return end - t0, sorted(lat)

    run_baseline(timed=False)                  # warm compile per group shape
    sb_wall, sb_lat = run_baseline(timed=True)

    def pct(xs, q):
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    def arm_stats(kern):
        wall, lat, qwait, occ, preempt, ttft = arm_results[kern][:6]
        d = {"attn_kernel": kern,
             "tokens_per_sec": round(total_gen / wall, 1),
             "wall_s": round(wall, 3),
             "latency_p50_s": round(pct(lat, 0.5), 4),
             "latency_p95_s": round(pct(lat, 0.95), 4),
             "ttft_p50_s": round(pct(ttft, 0.5), 4),
             "ttft_p95_s": round(pct(ttft, 0.95), 4),
             "queue_wait_p50_s": round(pct(qwait, 0.5), 4),
             "queue_wait_p95_s": round(pct(qwait, 0.95), 4),
             "preemptions": preempt}
        if occ:
            alloc = [e["blocks_allocated"] for e in occ]
            resv = [e["blocks_reserved_equiv"] for e in occ]
            t0 = occ[0]["t"]
            stride = max(1, len(occ) // 160)     # bound the artifact size
            d["pool_occupancy"] = {
                "usable_blocks": occ[0]["blocks_allocated"]
                + occ[0]["blocks_free"],
                "steps": len(occ),
                "peak_blocks_allocated": max(alloc),
                "mean_blocks_allocated": round(sum(alloc) / len(alloc), 2),
                # what PR-1's admission-time reservation would have pinned
                # for the same residency — the on-demand win per step
                "peak_blocks_reserved_equiv": max(resv),
                "mean_blocks_reserved_equiv": round(
                    sum(resv) / len(resv), 2),
                "stalled_step_fraction": round(
                    sum(1 for e in occ if e["stalled_slots"]) / len(occ), 4),
                "series": [
                    {"t": round(e["t"] - t0, 3),
                     "blocks_allocated": e["blocks_allocated"],
                     "blocks_reserved_equiv": e["blocks_reserved_equiv"],
                     "blocks_free": e["blocks_free"],
                     "live_tokens": e["live_tokens"],
                     "active_slots": e["active_slots"],
                     "stalled_slots": e["stalled_slots"],
                     "queued": e["queued"]}
                    for e in occ[::stride]],
            }
        return d

    cb_tps = total_gen / cb_wall
    sb_tps = total_gen / sb_wall
    detail = {
        "continuous": arm_stats(kernels[0]),
        "static_batch": {"tokens_per_sec": round(sb_tps, 1),
                         "wall_s": round(sb_wall, 3),
                         "latency_p50_s": round(pct(sb_lat, 0.5), 4),
                         "latency_p95_s": round(pct(sb_lat, 0.95), 4)},
        "speedup_tokens_per_sec": round(cb_tps / max(sb_tps, 1e-9), 3),
        "num_slots": num_slots, "n_requests": n_requests,
        "decode_chunk": decode_chunk, "block_size": block_size,
        "prompt_lens": list(prompt_lens), "gen_mix": list(gen_mix),
        "poisson_mean_gap_s": mean_gap, "trace_seed": trace_seed,
        "total_generated_tokens": int(total_gen),
        "block_allocation": "on_demand",
        "useful_token_fraction_static": round(
            total_gen / sum(max(g for _, g, _ in trace[i:i + num_slots])
                            * len(trace[i:i + num_slots])
                            for i in range(0, n_requests, num_slots)), 3),
        "backend": jax.default_backend(),
    }
    for kern in kernels[1:]:
        detail[f"continuous_{kern}"] = arm_stats(kern)
    if len(kernels) > 1:
        ref_w = arm_results[kernels[0]][0]
        alt_w = arm_results[kernels[1]][0]
        detail["kernel_ab"] = {
            "arms": list(kernels),
            "tokens_per_sec": {k: round(total_gen / arm_results[k][0], 1)
                               for k in kernels},
            f"{kernels[1]}_vs_{kernels[0]}": round(ref_w / alt_w, 3),
            "note": ("off-TPU the pallas arm runs in interpret mode — a "
                     "parity/plumbing arm, not a kernel measurement"
                     if jax.default_backend() != "tpu" else
                     "compiled kernel A/B at equal config"),
        }

    # --- dstrace observability (docs/OBSERVABILITY.md) -----------------------
    # the engine now reports its own latency breakdown; the bench keeps
    # measuring externally and the two are CROSS-CHECKED here so they
    # can never silently diverge (ISSUE 8 acceptance: TTFT p50 within
    # 5%, valid Perfetto trace covering every request's lifecycle)
    from deepspeed_tpu.observability import validate_chrome_trace

    wall0, _, _, _, _, ttft0, obs = arm_results[kernels[0]]
    snap, chrome_trace = obs["metrics"], obs["chrome"]
    schema_problems = validate_chrome_trace(chrome_trace)
    assert not schema_problems, f"invalid trace: {schema_problems[:3]}"
    term_rids = {e["args"]["rid"] for e in chrome_trace["traceEvents"]
                 if e.get("cat") == "terminal"}
    assert term_rids == set(range(n_requests)), \
        "trace missing terminal spans for some requests"
    def nearest_rank(xs, q):
        # the standard nearest-rank percentile (ceil(q*n)-th order
        # statistic) — the SAME rank convention the histogram's
        # cumulative walk lands on, so the cross-check compares
        # accounting paths, not percentile definitions
        import math as _math
        return xs[max(0, _math.ceil(q * len(xs)) - 1)]

    eng_ttft_p50 = snap["histograms"]["serve.ttft_s"]["p50"]
    bench_ttft_p50 = nearest_rank(ttft0, 0.5)
    agreement = abs(eng_ttft_p50 - bench_ttft_p50) / max(bench_ttft_p50,
                                                         1e-9)
    assert agreement <= 0.05, (
        f"engine-reported TTFT p50 {eng_ttft_p50:.4f}s diverges from "
        f"bench-measured {bench_ttft_p50:.4f}s by {agreement:.1%} "
        f"(> 5%) — the two accountings drifted")
    eng_tpot_p50 = snap["histograms"]["serve.tpot_s"]["p50"]
    bench_tpot_p50 = nearest_rank(obs["tpot"], 0.5) if obs["tpot"] else 0.0

    # --- dstfleet SLO/goodput cross-check (ISSUE 13 acceptance) ---------------
    # goodput: the engine's serve.goodput gauge (tokens_delivered /
    # tokens_sampled, both counted at the terminal funnel) against the
    # BENCH's completion accounting — delivered tokens summed from the
    # Completion objects the bench holds, over the engine's sampled-work
    # denominator (work done is only engine-knowable: it includes
    # preemption regeneration the bench cannot see externally)
    eng_goodput = snap["gauges"].get("serve.goodput", 0.0)
    eng_sampled = snap["counters"].get("serve.tokens_sampled", 0)
    bench_goodput = obs["delivered_tokens"] / max(eng_sampled, 1)
    goodput_agree = abs(eng_goodput - bench_goodput) \
        / max(bench_goodput, 1e-9)
    assert goodput_agree <= 0.05, (
        f"engine serve.goodput {eng_goodput:.4f} diverges from bench "
        f"completion accounting {bench_goodput:.4f} by "
        f"{goodput_agree:.1%} (> 5%)")
    # burn rate: the engine's whole-run-window TTFT burn rate times the
    # allowed fraction (0.05) IS its observed bad fraction; the bench
    # recounts ttft > target from its own completions. Agreement is
    # bounded by the histogram's bucket-edge resolution (~4.9% in VALUE
    # around the target), so the pin is 5 percentage points.
    # read the burn rate from the serve.slo COLLECTOR section, not the
    # gauges dict: snapshot() copies gauges BEFORE collectors run, and
    # the section's pull-time tick() is what folds in completions since
    # the scheduler's last rate-limited tick
    eng_burn = snap.get("serve.slo", {}).get(
        "ttft.burn_rate.3600s",
        snap["gauges"].get("serve.slo.ttft.burn_rate.3600s", 0.0))
    eng_bad_frac = eng_burn * 0.05
    n_ttft = sum(1 for _, t, n in obs["ttft_by_status"] if n > 0)
    bench_bad_frac = (sum(1 for _, t, n in obs["ttft_by_status"]
                          if n > 0 and t > slo_target)
                      / max(n_ttft, 1))
    burn_agree = abs(eng_bad_frac - bench_bad_frac)
    assert burn_agree <= 0.05, (
        f"engine TTFT bad-fraction {eng_bad_frac:.4f} (burn {eng_burn:.2f}"
        f" x 0.05) diverges from bench recount {bench_bad_frac:.4f} by "
        f"{burn_agree:.3f} (> 0.05 abs) at target {slo_target:.4f}s")

    # --- dstmem static-vs-measured memory cross-check (ISSUE 14) -------------
    # the static serving-memory prediction (the same eval_shape sizing
    # arithmetic the dstlint memory pass budgets) against dstprof's
    # serve.memory gauges — the memory twin of the comms budgets'
    # static==measured wire-byte pin. Pool AND param device bytes must
    # agree within 10%.
    from deepspeed_tpu.tools.dstlint import mempass

    serve_mem = snap.get("serve.memory", {})
    static_mem = mempass.predict_serve_memory(
        cfg, num_slots=num_slots, block_size=block_size,
        max_context=max(len(p) + g for p, g, _ in trace),
        dtype=cfg.dtype, params=params)
    mem_agree = {}
    for quantity, cmp in mempass.compare_serve_memory(
            static_mem, serve_mem).items():
        assert cmp["agreement"] <= 0.10, (
            f"measured {quantity} {cmp['measured']} diverges from the "
            f"static prediction {cmp['static']} by "
            f"{cmp['agreement']:.1%} (> 10%) — the sizing arithmetic "
            f"and the device drifted apart")
        mem_agree[quantity] = {
            "static": cmp["static"],
            "measured": cmp["measured"],
            "agreement_pct": round(cmp["agreement"] * 100, 2),
        }

    trace_file = "BENCH_TRACE.json"
    with open(trace_file, "w") as f:
        json.dump(chrome_trace, f, default=str)
    n_events = len(chrome_trace["traceEvents"])
    stride = max(1, n_events // 400)    # bounded inline sample
    compile_section = engine.compile_obs.section()
    detail["observability"] = {
        "metrics": snap,
        # per-bucket compile seconds + the zero-compiles-in-window guard
        # (asserted above): the compile-time breakdown the PR 3 warm-up
        # incident needed and didn't have
        "compile": {
            "per_arm_windows": compile_windows,
            "zero_compiles_in_measured_window": True,   # asserted above
            "programs": {cache: progs
                         for cache, progs in compile_section.items()
                         if cache.startswith("serve")},
            "gen_cache_compiles": sum(
                e["compiles"]
                for e in compile_section.get("gen", {}).values()),
        },
        "memory": {
            "static_vs_measured": mem_agree,
            "num_blocks": static_mem["num_blocks"],
            "block_bytes": static_mem["block_bytes"],
            "serve_memory_section": serve_mem,
        },
        "ttft_p50_engine_s": round(eng_ttft_p50, 4),
        "ttft_p50_bench_s": round(bench_ttft_p50, 4),
        "ttft_p50_agreement_pct": round(agreement * 100, 2),
        "tpot_p50_engine_s": round(eng_tpot_p50, 5),
        "tpot_p50_bench_s": round(bench_tpot_p50, 5),
        "slo": {
            "ttft_target_s": round(slo_target, 4),
            "goodput_engine": round(eng_goodput, 4),
            "goodput_bench": round(bench_goodput, 4),
            "goodput_agreement_pct": round(goodput_agree * 100, 2),
            "ttft_burn_rate_engine": round(eng_burn, 3),
            "ttft_bad_fraction_engine": round(eng_bad_frac, 4),
            "ttft_bad_fraction_bench": round(bench_bad_frac, 4),
            "burn_agreement_abs": round(burn_agree, 4),
            "slo_section": snap.get("serve.slo", {}),
        },
        "tracing_overhead": {
            "tracing_on_tokens_per_sec": round(total_gen / wall0, 1),
            "tracing_off_tokens_per_sec": round(total_gen / notrace_wall,
                                                1),
            "on_vs_off": round(notrace_wall / wall0, 3),
        },
        "trace": {
            "path": trace_file,
            "events": n_events,
            "dropped_events": chrome_trace["metadata"]["dropped_events"],
            "schema_valid": True,            # asserted above
            "terminal_events": len(term_rids),
            "perfetto_howto": "load BENCH_TRACE.json at "
                              "https://ui.perfetto.dev",
            "sample": chrome_trace["traceEvents"][::stride][:400],
        },
    }
    # --- chunked-prefill decode-interference A/B (ISSUE 15) ------------------
    detail["chunked_prefill_ab"] = _chunked_prefill_ab(
        engine, cfg, num_slots_ab=3, block_size=block_size,
        decode_chunk=decode_chunk + 1, kern=kernels[0],
        trace_seed=trace_seed, on_tpu=on_tpu)

    result = {
        "metric": "serve_continuous_batching_tokens_per_sec",
        "value": round(cb_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(cb_tps / max(sb_tps, 1e-9), 3),
        "detail": detail,
    }
    print(json.dumps(result))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def _chunked_prefill_ab(engine, cfg, *, num_slots_ab, block_size,
                        decode_chunk, kern, trace_seed, on_tpu):
    """Decode-interference A/B (``detail.chunked_prefill_ab``): inject
    one LONG prompt into a steady decode load and measure decode while
    it prefills. Unchunked, the whole-prompt prefill is ONE blocking
    executor call — decode emits nothing between the long request's
    admission and its first token. Chunked
    (``serve.prefill_chunk_tokens``), every scheduler step carries
    decode tokens alongside one prefill chunk, so decode tok/s inside
    that window is strictly positive and the max decode gap collapses
    from "whole prefill" to "one chunk". Same trace/engine/weights/
    attention arm; a DEDICATED executor config (distinct decode_chunk)
    so the program-bucket comparison below counts exactly this
    experiment's compiles. Keeps the serve bench's permanent guards:
    zero compiles inside each measured window, and engine-vs-bench
    TTFT/goodput cross-checks on BOTH arms."""
    from deepspeed_tpu.inference.scheduler import Request

    chunk_tok = 64 if on_tpu else 16
    long_len = 12 * block_size
    short_len = block_size
    steady_gen = 96 if on_tpu else 48

    def make_reqs(t0):
        r = np.random.default_rng(trace_seed + 7)

        def at(off):
            return None if t0 is None else t0 + off

        reqs = [Request(rid="steady",
                        prompt=r.integers(1, cfg.vocab_size, short_len),
                        max_new_tokens=steady_gen, arrival_time=at(0.0)),
                Request(rid="long",
                        prompt=r.integers(1, cfg.vocab_size, long_len),
                        max_new_tokens=8, arrival_time=at(0.2))]
        reqs += [Request(rid=f"short{i}",
                         prompt=r.integers(1, cfg.vocab_size, short_len),
                         max_new_tokens=4, arrival_time=at(0.25))
                 for i in range(5)]
        return reqs

    # prefix cache OFF for this experiment: the warm run would otherwise
    # cache these prompts and the timed run would prefill only their
    # uncached tails — there would be no long prefill left to measure
    # (and whole-prompt hits would compile CoW programs inside the
    # measured window). The chunked+prefix-cache composition is pinned
    # in tier-1 (tests/unit/inference/test_serve.py).
    serve_kw = dict(num_slots=num_slots_ab, block_size=block_size,
                    decode_chunk=decode_chunk, attn_kernel=kern,
                    prefix_cache=False)

    def run(chunk_on, timed):
        if timed:
            engine.reset_serve_metrics()
        t0 = time.time() + 0.01 if timed else None
        comps = engine.serve(
            make_reqs(t0),
            prefill_chunk_tokens=chunk_tok if chunk_on else 0,
            record_occupancy=timed, **serve_kw)
        if not timed:
            return None
        assert all(c.status == "COMPLETED" for c in comps), \
            [(c.rid, c.status, c.error) for c in comps]
        by = {c.rid: c for c in comps}
        occ = engine.last_serve_occupancy
        lc = by["long"]
        w0, w1 = lc.t_admitted, lc.t_first_token
        window = max(w1 - w0, 1e-9)
        decode_in_window = sum(e["decode_tokens"] for e in occ
                               if w0 < e["t_wall"] <= w1)
        dtimes = [e["t_wall"] for e in occ if e["decode_tokens"]]
        max_gap = max((b - a for a, b in zip(dtimes, dtimes[1:])),
                      default=0.0)
        ttfts = sorted(c.t_first_token - c.t_submit for c in comps)
        short_ttfts = sorted(c.t_first_token - c.t_submit for c in comps
                             if str(c.rid).startswith("short"))
        # engine-vs-bench cross-checks (both arms run through here)
        snap = engine.serve_metrics()
        eng_ttft = snap["histograms"]["serve.ttft_s"]["p50"]
        bench_ttft = ttfts[len(ttfts) // 2]
        ttft_agree = abs(eng_ttft - bench_ttft) / max(bench_ttft, 1e-9)
        # small-n caveat: ~7 requests per arm, so the histogram's
        # interpolated p50 can sit between two spread-out order
        # statistics — the tolerance is wider than the main flow's 5%
        # at n=48, but the check still catches real accounting drift
        assert ttft_agree <= 0.25, (
            f"chunked-AB engine TTFT p50 {eng_ttft:.4f}s diverges from "
            f"bench {bench_ttft:.4f}s by {ttft_agree:.1%}")
        delivered = sum(len(c.tokens) for c in comps
                        if c.status == "COMPLETED")
        sampled = snap["counters"].get("serve.tokens_sampled", 0)
        eng_goodput = snap["gauges"].get("serve.goodput", 0.0)
        bench_goodput = delivered / max(sampled, 1)
        goodput_agree = abs(eng_goodput - bench_goodput) \
            / max(bench_goodput, 1e-9)
        assert goodput_agree <= 0.05, (
            f"chunked-AB engine goodput {eng_goodput:.4f} diverges from "
            f"bench {bench_goodput:.4f} by {goodput_agree:.1%}")
        return {
            "decode_toks_in_long_prefill_window": int(decode_in_window),
            "long_prefill_window_s": round(window, 4),
            "decode_toks_per_s_during_long_prefill": round(
                decode_in_window / window, 2),
            "max_decode_gap_s": round(max_gap, 4),
            "long_ttft_s": round(w1 - lc.t_submit, 4),
            "short_ttft_p50_s": round(
                short_ttfts[len(short_ttfts) // 2], 4),
            "ttft_p50_engine_s": round(eng_ttft, 4),
            "ttft_p50_bench_s": round(bench_ttft, 4),
            "ttft_p50_agreement_pct": round(ttft_agree * 100, 2),
            "goodput_engine": round(eng_goodput, 4),
            "goodput_bench": round(bench_goodput, 4),
        }

    arms = {}
    prev = engine.compile_obs.compiles_total("serve")
    windows = {}
    for name, chunk_on in (("off", False), ("on", True)):
        run(chunk_on, timed=False)               # warm: compile programs
        warmed = engine.compile_obs.compiles_total("serve")
        arms[name] = run(chunk_on, timed=True)
        after = engine.compile_obs.compiles_total("serve")
        in_window = after - warmed
        assert in_window == 0, (
            f"{in_window} compile(s) inside the chunked-AB measured "
            f"window (arm {name})")
        windows[name] = {"warmup_compiles": warmed - prev,
                         "measured_window_compiles": in_window}
        prev = after

    # program-bucket count: the ragged executor vs the split caches —
    # the SAME executor object served both arms (chunking is a
    # scheduler mode, not an executor shape), so its program dicts
    # split exactly by arm
    ex = None
    for (slots, _bs, _nb, dc, _kv8, arm), (_, cand) in \
            getattr(engine, "_serve_executors", {}).items():
        if slots == num_slots_ab and dc == decode_chunk and arm == kern:
            ex = cand
    assert ex is not None
    split_buckets = len(ex._prefill_fns) + (ex._decode_fn is not None)
    ragged_buckets = len(ex._ragged_fns)
    assert ragged_buckets < split_buckets, (
        f"ragged executor compiled {ragged_buckets} bucket(s) but the "
        f"split prefill/decode caches needed only {split_buckets}")

    on, off = arms["on"], arms["off"]
    assert on["decode_toks_per_s_during_long_prefill"] > \
        off["decode_toks_per_s_during_long_prefill"], (on, off)
    # short-request TTFT must be NO WORSE with chunking on (the
    # fair-shared budget lets a short prompt ride the long prompt's
    # chunk steps instead of queueing behind its whole prefill; 5%
    # timing-noise allowance)
    assert on["short_ttft_p50_s"] <= off["short_ttft_p50_s"] * 1.05, \
        (on["short_ttft_p50_s"], off["short_ttft_p50_s"])
    return {
        "arms": arms,
        "chunk_tokens": chunk_tok,
        "long_prompt_tokens": long_len,
        "short_prompt_tokens": short_len,
        "attn_kernel": kern,
        "decode_stall_removed": True,            # asserted above
        "decode_toks_per_s_during_long_prefill": {
            "off": off["decode_toks_per_s_during_long_prefill"],
            "on": on["decode_toks_per_s_during_long_prefill"],
        },
        "short_ttft_p50_s": {"off": off["short_ttft_p50_s"],
                             "on": on["short_ttft_p50_s"]},
        "program_buckets": {"split_prefill_plus_decode": split_buckets,
                            "ragged": ragged_buckets},
        "compile_windows": windows,
        "zero_compiles_in_measured_window": True,  # asserted above
    }


def serve_prefix_main(num_slots=None, trace_seed=None,
                      out_path="BENCH_SERVE.json", kernel=None,
                      host_cache=False):
    """--serve --shared-prefix: the prefix-cache A/B on a shared-prefix
    trace (N personas x M continuations — the system-prompt/few-shot
    traffic shape), same engine/weights/slots/kernel across arms:

    - ``prefix_on``: serve.prefix_cache on, shared trace — admissions
      reuse each persona's cached blocks and prefill only the tail;
    - ``prefix_off``: cache off, same trace — every prompt prefills in
      full (the PR-2 behavior);
    - ``unique_baseline``: cache ON over a same-shape trace of UNIQUE
      prompts — the hit-rate floor that shows the shared-trace hit rate
      is content reuse, not accounting noise.

    Reports TTFT p50/p95 per arm, block/token cache hit-rates,
    evictions, and asserts the on/off greedy token streams are
    IDENTICAL (the cache must be a pure perf optimization) and that all
    arms replayed the identical request sequence (--trace-seed). Results
    merge into the existing BENCH_SERVE.json under
    ``detail.prefix_cache_ab`` (the continuous-vs-static sections stay).

    The persona length is deliberately several prompt buckets long: an
    offset prefill of the uncached tail drops into a SMALLER compiled
    bucket (engine.prompt_capacity), so the TTFT win is real compute
    skipped, not just accounting.

    ``--host-cache`` adds the TIERED-KV A/B (docs/SERVING.md): the same
    shared trace served from a device pool SHRUNK until the device LRU
    must evict each persona between uses (2 slots, ~live-tokens-only
    slack), with vs without a host-RAM tier (``host_cache_gb``). The
    tiered arm spills evicted persona blocks to host RAM and restores
    them by async device_put ahead of the tail prefill; the no-tier arm
    re-prefills every evicted persona in full. Records per-arm TTFT,
    the host-tier lookup hit-rate, spill/restore bytes, and asserts the
    greedy streams are byte-identical (the tier is a pure capacity/perf
    layer) — merged as ``detail.host_cache_ab``.
    """
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.inference.scheduler import Request
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, scan_layers=True)
        num_slots = num_slots or 8
        block_size = 32
        decode_chunk = 8
        n_personas, n_cont = 4, 12
        persona_len = 224                    # 7 full blocks, 2+ buckets
        cont_lens = (16, 24, 32)
        gen_mix = (16, 32, 64)
        mean_gap = 0.05
    else:
        cfg = LlamaConfig(
            vocab_size=4096, hidden_size=512, intermediate_size=1024,
            num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=512,
            dtype=jnp.float32)
        num_slots = num_slots or 4
        block_size = 8
        decode_chunk = 8
        n_personas, n_cont = 3, 8
        persona_len = 88                     # 11 full blocks; tail
        cont_lens = (5, 8, 11)               # prefills in the T=32 bucket
        gen_mix = (8, 12, 16)                # vs 96/128 for cold prompts
        mean_gap = 0.004
    kernel = kernel or "reference"

    model = LlamaModel(cfg)
    params = jax.jit(
        lambda r: model.init(
            r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    engine = deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "bfloat16" if on_tpu else "float32"})

    trace_seed = 1 if trace_seed is None else int(trace_seed)
    n_requests = n_personas * n_cont

    def make_trace(rng, shared: bool):
        """(prompt, gen, arrival) triples. ``shared``: prompts are
        persona + continuation; else unique random prompts of the SAME
        lengths (apples-to-apples hit-rate floor)."""
        personas = [rng.integers(1, cfg.vocab_size, persona_len)
                    for _ in range(n_personas)]
        items = [(p, int(rng.choice(cont_lens)), int(rng.choice(gen_mix)))
                 for p in personas for _ in range(n_cont)]
        rng.shuffle(items)
        arrivals = np.cumsum(rng.exponential(mean_gap, n_requests))
        trace = []
        for i, (persona, c_len, g_len) in enumerate(items):
            cont = rng.integers(1, cfg.vocab_size, c_len)
            prompt = (np.concatenate([persona, cont]) if shared else
                      rng.integers(1, cfg.vocab_size,
                                   persona_len + c_len))
            trace.append((prompt, g_len, float(arrivals[i])))
        return trace

    def run_arm(shared: bool, prefix_cache: bool, timed: bool):
        arm_trace = make_trace(np.random.default_rng(trace_seed), shared)
        t0 = time.time() + (0.0 if not timed else 0.01)
        reqs = [Request(rid=i, prompt=p, max_new_tokens=g,
                        arrival_time=(t0 + off) if timed else None)
                for i, (p, g, off) in enumerate(arm_trace)]
        engine.reset_prefix_cache()          # every arm starts COLD
        comps = engine.serve(reqs, num_slots=num_slots,
                             block_size=block_size,
                             decode_chunk=decode_chunk,
                             attn_kernel=kernel,
                             prefix_cache=prefix_cache)
        stats = engine.last_serve_scheduler.prefix_cache_stats()
        wall = max(c.t_finish for c in comps) - t0
        return {
            "trace": arm_trace,
            "tokens": {c.rid: np.asarray(c.tokens) for c in comps},
            "ttft": sorted(c.t_first_token - c.t_submit for c in comps),
            "lat": sorted(c.t_finish - c.t_submit for c in comps),
            "wall": wall,
            "stats": stats,
        }

    def warm_arm(prefix_cache: bool):
        """Deterministic compile warm-up: which prefill bucket a trace
        request hits depends on admission order (a cache-hit tail
        buckets smaller than its cold prompt), so replaying the trace
        untimed can MISS a bucket the timed run then compiles mid-flight
        — instead, touch every cold bucket (one distinct persona per
        continuation length), every hit-tail bucket (repeats), and the
        CoW copy program (block-aligned full-cover repeats)
        explicitly."""
        rng = np.random.default_rng(0)
        ps = [rng.integers(1, cfg.vocab_size, persona_len)
              for _ in cont_lens]
        reqs, rid = [], 0
        for rep in range(2):
            for p, c in zip(ps, cont_lens):
                reqs.append(Request(
                    rid=rid, max_new_tokens=4,
                    prompt=np.concatenate(
                        [p, rng.integers(1, cfg.vocab_size, c)])))
                rid += 1
        for _ in range(2):
            reqs.append(Request(rid=rid, prompt=ps[0], max_new_tokens=4))
            rid += 1
        engine.reset_prefix_cache()
        engine.serve(reqs, num_slots=num_slots, block_size=block_size,
                     decode_chunk=decode_chunk, attn_kernel=kernel,
                     prefix_cache=prefix_cache)

    arms_spec = {
        "prefix_on": (True, True),
        "prefix_off": (True, False),
        "unique_baseline": (False, True),
    }
    arms = {}
    for name, (shared, pc) in arms_spec.items():
        warm_arm(pc)
        arms[name] = run_arm(shared, pc, timed=True)

    # A/B hygiene: identical replay across the shared-trace arms, and
    # identical greedy token streams — the cache is a pure perf opt
    assert_traces_equal(arms["prefix_on"]["trace"],
                        arms["prefix_off"]["trace"])
    for rid, toks in arms["prefix_on"]["tokens"].items():
        assert np.array_equal(toks, arms["prefix_off"]["tokens"][rid]), \
            f"request {rid}: prefix-cache arm diverged from cache-off"

    def pct(xs, q):
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    total_gen = sum(g for _, g, _ in arms["prefix_on"]["trace"])

    def arm_detail(name):
        a = arms[name]
        s = a["stats"]
        return {
            "ttft_p50_s": round(pct(a["ttft"], 0.5), 4),
            "ttft_p95_s": round(pct(a["ttft"], 0.95), 4),
            "latency_p50_s": round(pct(a["lat"], 0.5), 4),
            "tokens_per_sec": round(total_gen / a["wall"], 1),
            "wall_s": round(a["wall"], 3),
            "block_hit_rate": s["block_hit_rate"],
            "token_hit_rate": s["token_hit_rate"],
            "hit_blocks": s["hit_blocks"],
            "lookup_blocks": s["lookup_blocks"],
            "evictions": s["evictions"],
            "prefix_cache": s["enabled"],
        }

    on, off = arm_detail("prefix_on"), arm_detail("prefix_off")
    uniq = arm_detail("unique_baseline")
    uniq_rate = max(uniq["block_hit_rate"], 1e-9)
    ab = {
        "arms": {"prefix_on": on, "prefix_off": off,
                 "unique_baseline": uniq},
        "trace": {"personas": n_personas, "continuations": n_cont,
                  "persona_len": persona_len, "cont_lens": list(cont_lens),
                  "gen_mix": list(gen_mix), "n_requests": n_requests,
                  "block_size": block_size, "num_slots": num_slots,
                  "trace_seed": trace_seed, "attn_kernel": kernel,
                  "poisson_mean_gap_s": mean_gap},
        "ttft_p50_speedup_x": round(off["ttft_p50_s"]
                                    / max(on["ttft_p50_s"], 1e-9), 3),
        "block_hit_rate_vs_unique_x": round(
            on["block_hit_rate"] / uniq_rate, 1),
        "greedy_identical": True,            # asserted above
        "backend": jax.default_backend(),
    }

    host_ab = None
    if host_cache:
        from deepspeed_tpu.ops.paged_attention import blocks_for

        # device pool shrunk to LIVE tokens + a sliver: 2 slots' worth
        # of blocks plus ~4 of LRU slack, so a persona can never sit
        # out a full reuse cycle in HBM. The tier trace reshapes the
        # shared-prefix traffic to what the tier targets: DOUBLED
        # personas (long system prompts — a restore must out-save one
        # decode round, and the saving scales with persona length while
        # the cost is fixed) CYCLED round-robin with arrivals spaced
        # near the service rate, so every reuse is separated by the
        # other personas' admissions and the shrunken LRU provably
        # evicts it in between — warm admissions either host-hit (tier
        # on) or re-prefill the whole persona cold (tier off)
        tier_slots = 2
        tier_persona = persona_len * 2
        tier_gap = 0.25
        max_ctx = tier_persona + max(cont_lens) + max(gen_mix)
        t_width = -(-blocks_for(max_ctx, block_size) // 4) * 4
        small_pool = tier_slots * t_width + 5
        host_gb = 0.25 if not on_tpu else 2.0
        tier_kw = dict(num_slots=tier_slots, block_size=block_size,
                       num_blocks=small_pool, max_context=max_ctx,
                       decode_chunk=decode_chunk, attn_kernel=kernel,
                       prefix_cache=True)

        def tier_trace(rng):
            """(prompt, gen, arrival-offset) triples: n_requests over
            n_personas personas, round-robin (reuse is always separated
            by the other personas), deterministic ``tier_gap`` spacing
            (identical arrival pattern across arms by construction)."""
            ps = [rng.integers(1, cfg.vocab_size, tier_persona)
                  for _ in range(n_personas)]
            out = []
            for i in range(n_requests):
                c = int(rng.choice(cont_lens))
                g = int(rng.choice(gen_mix))
                out.append((np.concatenate(
                    [ps[i % n_personas],
                     rng.integers(1, cfg.vocab_size, c)]),
                    g, i * tier_gap))
            return out

        def warm_tier_arm(gb):
            rng = np.random.default_rng(0)
            ps = [rng.integers(1, cfg.vocab_size, tier_persona)
                  for _ in range(n_personas)]
            reqs, rid = [], 0
            for rep in range(3):     # reps 2-3 reuse post-eviction (the
                for p, c in zip(ps, cont_lens):   # restore programs)
                    reqs.append(Request(
                        rid=rid, max_new_tokens=4,
                        prompt=np.concatenate(
                            [p, rng.integers(1, cfg.vocab_size, c)])))
                    rid += 1
            engine.reset_prefix_cache()
            engine.serve(reqs, host_cache_gb=gb, **tier_kw)

        def run_tier_arm(gb):
            arm_trace = tier_trace(np.random.default_rng(trace_seed))
            t0 = time.time() + 0.01
            reqs = [Request(rid=i, prompt=p, max_new_tokens=g,
                            arrival_time=t0 + off)
                    for i, (p, g, off) in enumerate(arm_trace)]
            engine.reset_prefix_cache()          # both arms start COLD
            comps = engine.serve(reqs, host_cache_gb=gb, **tier_kw)
            stats = engine.last_serve_scheduler.prefix_cache_stats()
            return {
                "trace": arm_trace,
                "tokens": {c.rid: np.asarray(c.tokens) for c in comps},
                "ttft": sorted(c.t_first_token - c.t_submit
                               for c in comps),
                "wall": max(c.t_finish for c in comps) - t0,
                "gen_total": sum(len(c.tokens) for c in comps),
                "stats": stats,
            }

        tier_arms = {}
        for name, gb in (("tier_on", host_gb), ("tier_off", 0)):
            warm_tier_arm(gb)
            tier_arms[name] = run_tier_arm(gb)
        assert_traces_equal(tier_arms["tier_on"]["trace"],
                            tier_arms["tier_off"]["trace"])
        for rid, toks in tier_arms["tier_on"]["tokens"].items():
            assert np.array_equal(
                toks, tier_arms["tier_off"]["tokens"][rid]), \
                f"request {rid}: host-tier arm diverged from no-tier"

        def tier_detail(name):
            a = tier_arms[name]
            s = a["stats"]
            return {
                "ttft_p50_s": round(pct(a["ttft"], 0.5), 4),
                "ttft_p95_s": round(pct(a["ttft"], 0.95), 4),
                "tokens_per_sec": round(a["gen_total"] / a["wall"], 1),
                "wall_s": round(a["wall"], 3),
                "device_block_hit_rate": s["block_hit_rate"],
                "token_hit_rate": s["token_hit_rate"],
                "device_evictions": s["device_evictions"],
                "host_tier_enabled": s["host_tier_enabled"],
                "host_hit_rate": s["host_lookup_hit_rate"],
                "host_hits": s["host_hits"],
                "host_spills": s["host_spills"],
                "host_restores": s["host_restores"],
                "host_restore_failures": s["host_restore_failures"],
                "host_evictions": s["host_evictions"],
                "host_bytes_spilled": s["host_bytes_spilled"],
                "host_bytes_restored": s["host_bytes_restored"],
            }

        t_on, t_off = tier_detail("tier_on"), tier_detail("tier_off")
        host_ab = {
            "arms": {"tier_on": t_on, "tier_off": t_off},
            "config": {"num_slots": tier_slots,
                       "num_blocks": small_pool,
                       "table_width": t_width,
                       "block_size": block_size,
                       "persona_len": tier_persona,
                       "arrival_gap_s": tier_gap,
                       "host_cache_gb": host_gb,
                       "trace_seed": trace_seed,
                       "attn_kernel": kernel},
            "ttft_p50_speedup_x": round(
                t_off["ttft_p50_s"] / max(t_on["ttft_p50_s"], 1e-9), 3),
            "host_hit_rate": t_on["host_hit_rate"],
            "greedy_identical": True,        # asserted above
            "backend": jax.default_backend(),
        }

    result = {
        "metric": "serve_prefix_cache_ttft_p50_s",
        "value": on["ttft_p50_s"],
        "unit": "s",
        "vs_baseline": ab["ttft_p50_speedup_x"],
        "detail": ab,
    }
    print(json.dumps(result))
    if host_ab is not None:
        print(json.dumps({
            "metric": "serve_host_cache_ttft_p50_s",
            "value": host_ab["arms"]["tier_on"]["ttft_p50_s"],
            "unit": "s",
            "vs_baseline": host_ab["ttft_p50_speedup_x"],
            "detail": host_ab,
        }))
    if out_path:
        # merge under the serve artifact: the continuous-vs-static and
        # kernel-A/B sections from --serve stay alongside
        artifact = {}
        try:
            with open(out_path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            pass
        artifact.setdefault("detail", {})["prefix_cache_ab"] = ab
        if host_ab is not None:
            artifact["detail"]["host_cache_ab"] = host_ab
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return result


def _serve_multichip_impl(n_devices, out_path):
    """Child body of ``--serve --multichip`` (spawned by
    ``__graft_entry__.serve_multichip`` onto an ``n_devices`` virtual
    CPU mesh — same subprocess bootstrap as the training telemetry
    bench). Three legs, one process:

    - **TP=2 fp32**: the shard_map'd serving executor
      (inference/tp_shard.py — heads + KV pools on the ``tensor`` axis,
      row/column-parallel MLP, one psum per residual boundary) serves a
      greedy trace; its token streams must be BYTE-IDENTICAL to a
      single-device engine on the same weights/trace.
    - **TP=2 int8**: the quantized-collective arm
      (``serve.tp_collective="int8"``): greedy streams are compared to
      fp32 per request (longest-common-prefix fraction), and an eager
      wire-byte A/B cross-checks the measured ``comm.*.bytes`` counters
      against the static ``collective_cost`` table — the same
      ``quantized_psum`` entry the dstlint SPMD budgets price.
    - **DP=2 replica group**: a :class:`ReplicaGroup` behind ONE
      admission queue on a hot-prefix-family trace sized so a single
      replica's pool cannot cache the full working set (device-LRU
      thrash -> full re-prefill per request) while prefix-affinity
      routing lands each family on one replica whose pool CAN hold its
      half (tail-only prefill). The aggregate-throughput win is real
      prefill compute skipped — measurable even on a single host core,
      where replicas timeshare the CPU and pure compute replication
      nets ~1.0x. On real multi-chip hosts compute parallelism
      multiplies on top; the artifact records ``host_cpus`` so readers
      can tell which regime they're looking at.

    Writes the leg results as JSON to ``out_path`` and asserts the
    acceptance gates (parity, wire ratio, DP speedup) in-process.
    """
    import tempfile

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.comm.collective_cost import wire_bytes
    from deepspeed_tpu.inference.replica import ReplicaGroup
    from deepspeed_tpu.inference.scheduler import Request
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
    from deepspeed_tpu.observability.metrics import MetricsRegistry
    from deepspeed_tpu.parallel.mesh import make_mesh

    devs = jax.devices()
    assert len(devs) >= 2, f"need >=2 virtual devices, got {devs}"
    # the --serve CPU bench model, scan_layers=True: the TP executor
    # shards the FUSED scan stack (one stacked qkv/gateup per layer
    # group), and scan keeps all arms on the same compiled structure
    cfg = LlamaConfig(
        vocab_size=4096, hidden_size=512, intermediate_size=1024,
        num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=512,
        dtype=jnp.float32, scan_layers=True)
    block_size = 8
    model = LlamaModel(cfg)
    params = jax.jit(
        lambda r: model.init(
            r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(0))

    one_chip = {"pipe": 1, "data": 1, "expert": 1, "sequence": 1,
                "tensor": 1}

    def single_engine(dev):
        return deepspeed_tpu.init_inference(
            model=model, params=params, model_config=cfg,
            config={"dtype": "float32"},
            mesh=make_mesh(dims=dict(one_chip), devices=[dev]))

    # ---- leg 1+2: TP=2 vs single-device, fp32 and int8 collectives ------
    tp_rng = np.random.default_rng(11)
    tp_trace = [(tp_rng.integers(1, cfg.vocab_size,
                                 (6, 10, 17, 25)[i % 4]),
                 (8, 12)[i % 2]) for i in range(8)]

    def run_tp_arm(engine, timed):
        reqs = [Request(rid=i, prompt=p, max_new_tokens=g)
                for i, (p, g) in enumerate(tp_trace)]
        t0 = time.time()
        comps = engine.serve(reqs, num_slots=2, block_size=block_size,
                             decode_chunk=8, attn_kernel="reference")
        wall = time.time() - t0
        toks = {c.rid: [int(t) for t in np.asarray(c.tokens)]
                for c in comps}
        # the scheduler degrades trace-time errors into empty
        # completions — parity MUST compare token content, so empty
        # streams are a hard failure, not a vacuous pass
        assert all(len(v) > 0 for v in toks.values()), \
            f"empty token streams: { {k: len(v) for k, v in toks.items()} }"
        assert all(c.status == "COMPLETED" for c in comps)
        return toks, wall, sum(len(v) for v in toks.values())

    arms = {}
    eng_1dev = single_engine(devs[0])
    run_tp_arm(eng_1dev, timed=False)                    # compile warm
    toks_1dev, wall_1dev, ntok_1dev = run_tp_arm(eng_1dev, timed=True)
    arms["single_device"] = {"wall_s": round(wall_1dev, 3),
                             "tok_s": round(ntok_1dev / wall_1dev, 1)}

    eng_tp = deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "float32", "tensor_parallel": {"tp_size": 2}})
    run_tp_arm(eng_tp, timed=False)
    toks_tp, wall_tp, ntok_tp = run_tp_arm(eng_tp, timed=True)
    fp32_identical = toks_tp == toks_1dev
    assert fp32_identical, (
        "TP=2 fp32 greedy streams diverged from single-device: "
        f"{ {r: (toks_1dev[r], toks_tp[r]) for r in toks_1dev if toks_1dev[r] != toks_tp.get(r)} }")
    arms["tp2_fp32"] = {"wall_s": round(wall_tp, 3),
                        "tok_s": round(ntok_tp / wall_tp, 1),
                        "greedy_identical_to_single_device": True}

    eng_int8 = deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "float32", "tensor_parallel": {"tp_size": 2},
                "serve": {"tp_collective": "int8"}})
    run_tp_arm(eng_int8, timed=False)
    toks_int8, wall_int8, ntok_int8 = run_tp_arm(eng_int8, timed=True)

    def lcp_frac(a, b):
        n = 0
        for x, y in zip(a, b):
            if x != y:
                break
            n += 1
        return n / max(len(a), len(b), 1)
    agree = [lcp_frac(toks_tp[r], toks_int8[r]) for r in sorted(toks_tp)]
    mean_agree = float(np.mean(agree))
    # int8 rounding perturbs logits by ~1e-2 at this scale; greedy
    # argmax flips only where the fp32 margin is that small, so long
    # common prefixes are the expected shape — a LOW mean means the
    # quantized ring is broken, not merely noisy
    assert mean_agree >= 0.5, f"int8 greedy agreement collapsed: {agree}"
    arms["tp2_int8"] = {"wall_s": round(wall_int8, 3),
                        "tok_s": round(ntok_int8 / wall_int8, 1),
                        "greedy_prefix_agreement_vs_fp32": {
                            "mean": round(mean_agree, 3),
                            "min": round(min(agree), 3),
                            "per_request": [round(a, 3) for a in agree]}}

    # ---- wire bytes: measured counters vs the static table --------------
    from jax.sharding import NamedSharding, PartitionSpec

    reg = MetricsRegistry()
    prev_reg = comm.get_metrics_registry()
    comm.set_metrics_registry(reg)
    try:
        mesh = eng_tp.mesh
        x = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(3), (4, 512),
                              jnp.float32),
            NamedSharding(mesh, PartitionSpec("tensor")))
        out_fp = comm.eager_all_reduce_over_mesh(x, mesh, axis="tensor")
        out_q = comm.eager_quantized_all_reduce_over_mesh(
            x, mesh, axis="tensor")
        a = np.asarray(out_fp, np.float64).ravel()
        b = np.asarray(out_q, np.float64).ravel()
        cosine = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
        max_abs_err = float(np.abs(a - b).max())
        counters = reg.counters()
    finally:
        comm.set_metrics_registry(prev_reg)
    payload = 4 * 512 * 4
    static_fp = wire_bytes("psum", payload, 2)
    static_q = wire_bytes("quantized_psum", payload, 2)
    measured_fp = int(counters["comm.all_reduce.bytes"])
    measured_q = int(counters["comm.quantized_all_reduce.bytes"])
    assert measured_fp == static_fp, (measured_fp, static_fp)
    assert measured_q == static_q, (measured_q, static_q)
    ratio = measured_q / measured_fp
    assert ratio <= 0.30, f"int8 wire ratio {ratio} > 0.30"
    assert cosine >= 0.999, cosine

    # per-decode-step budget cross-ref: the dstlint SPMD pass pins the
    # same numbers for the traced TP decode step (serve_decode_tp2/*)
    budgets = {}
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tools", "dstlint",
                               "comms_budgets.json")) as f:
            allb = json.load(f).get("entries", {})
        budgets = {k: v for k, v in allb.items()
                   if isinstance(k, str) and k.startswith("serve_decode_tp2")}
    except (OSError, ValueError):
        pass
    assert {"serve_decode_tp2/fp32", "serve_decode_tp2/int8"} <= set(budgets), \
        sorted(budgets)
    collectives = {
        "payload_bytes": payload,
        "fp32": {"measured_wire_bytes": measured_fp,
                 "static_wire_bytes": static_fp},
        "int8": {"measured_wire_bytes": measured_q,
                 "static_wire_bytes": static_q},
        "wire_ratio_int8_vs_fp32": round(ratio, 4),
        "measured_equals_static": True,
        "numerics": {"cosine_vs_fp32": round(cosine, 6),
                     "max_abs_err": round(max_abs_err, 6)},
        "spmd_decode_budgets": budgets,
    }

    # ---- leg 3: DP replica group vs one replica (same per-replica cfg) --
    # 4 hot prefix families / 44-block pool: a family's 12 cached prefix
    # blocks survive only until the pool needs them — with 4 families
    # rotating through 2 slots, the 3 intervening full prefills (~45
    # blocks) evict a parked family before its next request (miss ->
    # full 104-token prefill). Affinity routing gives each group replica
    # 2 families (<= its slot count): the completion->admission handoff
    # keeps both prefixes resident (hit -> 8-token tail prefill).
    n_fam, n_cont, persona_len, suffix_len, gen_len = 4, 5, 96, 8, 8
    dp_kwargs = dict(num_slots=2, block_size=block_size, num_blocks=44,
                     decode_chunk=16, attn_kernel="reference",
                     prefix_cache=True)
    fam_rng = np.random.default_rng(7)
    personas = [fam_rng.integers(1, cfg.vocab_size, persona_len)
                for _ in range(n_fam)]
    dp_reqs_spec = []
    for c in range(n_cont):
        for f in range(n_fam):                     # strict A,B,C,D rotation
            dp_reqs_spec.append(np.concatenate(
                [personas[f],
                 fam_rng.integers(1, cfg.vocab_size, suffix_len)]))

    def dp_requests():
        return [Request(rid=i, prompt=p, max_new_tokens=gen_len)
                for i, p in enumerate(dp_reqs_spec)]

    def run_dp(serve_fn, engines, timed):
        for e in engines:
            e.reset_prefix_cache()                 # every run starts COLD
        t0 = time.time()
        comps = serve_fn(dp_requests())
        wall = time.time() - t0
        toks = {c.rid: [int(t) for t in np.asarray(c.tokens)]
                for c in comps}
        assert all(c.status == "COMPLETED" for c in comps)
        assert all(len(v) > 0 for v in toks.values())
        stats = [e.last_serve_scheduler.prefix_cache_stats()
                 for e in engines]
        return toks, wall, sum(len(v) for v in toks.values()), stats

    eng_base = single_engine(devs[0])
    base_serve = lambda reqs: eng_base.serve(reqs, **dp_kwargs)
    run_dp(base_serve, [eng_base], timed=False)    # warm (cold buckets)
    run_dp(base_serve, [eng_base], timed=False)    # warm (hit-tail bucket)
    toks_base, wall_base, ntok_base, stats_base = run_dp(
        base_serve, [eng_base], timed=True)

    fleet_dir = tempfile.mkdtemp(prefix="bench_serve_fleet_")
    group = ReplicaGroup([single_engine(devs[0]), single_engine(devs[1])],
                         fleet_dir=fleet_dir)
    grp_serve = lambda reqs: group.serve(reqs, **dp_kwargs)
    run_dp(grp_serve, group.engines, timed=False)
    run_dp(grp_serve, group.engines, timed=False)
    toks_grp, wall_grp, ntok_grp, stats_grp = run_dp(
        grp_serve, group.engines, timed=True)

    # routing must be a pure perf layer: greedy streams byte-identical
    assert toks_grp == toks_base, "DP routing changed greedy outputs"
    speedup = (ntok_grp / wall_grp) / (ntok_base / wall_base)
    assignment = [len(a) for a in group.last_assignment]
    assert len(assignment) >= 2 and min(assignment) > 0, assignment
    try:
        host_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        host_cpus = os.cpu_count() or 1
    parallel_host = host_cpus >= 2
    base_hit = stats_base[0]["block_hit_rate"]
    grp_hit = min(s["block_hit_rate"] for s in stats_grp)
    per_replica = {}
    for i, assigned in enumerate(group.last_assignment):
        rids = [r.rid for r in assigned]
        t = sum(len(toks_grp[r]) for r in rids)
        per_replica[f"replica{i}"] = {
            "requests": len(rids), "tokens": t,
            "tok_s": round(t / wall_grp, 1),
            "cache_stats": stats_grp[i]}
    merged = group.fleet_view()
    snap = merged.snapshot() if hasattr(merged, "snapshot") else {}
    replicas = {
        "n_replicas": len(group.engines),
        "single_replica": {"wall_s": round(wall_base, 3),
                           "tok_s": round(ntok_base / wall_base, 1),
                           "cache_stats": stats_base[0]},
        "group": {"wall_s": round(wall_grp, 3),
                  "tok_s": round(ntok_grp / wall_grp, 1),
                  "per_replica": per_replica},
        "aggregate_speedup_x": round(speedup, 3),
        "greedy_identical_to_single_replica": True,
        "fleet": {k: v for k, v in snap.get("gauges", {}).items()
                  if k.startswith("fleet.")},
        "replica_labels": snap.get("labeled_gauges", {}).get(
            "fleet.replica", {}),
        "mechanism": (
            "aggregate KV/prefix-cache capacity + affinity routing: the "
            "single replica's device LRU evicts each prefix family "
            "between uses (full re-prefill); each group replica holds "
            "its routed families resident (tail-only prefill). On a "
            "multi-core host compute replication adds on top."),
        "host_cpus": host_cpus,
        "serialized_host": not parallel_host,
        "prefill_tokens_saved_x": round(
            max(stats_base[0]["prompt_tokens"]
                - stats_base[0]["hit_tokens"], 1)
            / max(sum(s["prompt_tokens"] - s["hit_tokens"]
                      for s in stats_grp), 1), 2),
    }
    # the capacity-relief mechanism must engage regardless of host shape:
    # the lone replica thrashes (low hit rate, forced evictions), every
    # group replica's working set stays resident, and routing never
    # regresses throughput
    assert grp_hit >= 0.6 and base_hit <= 0.35, (grp_hit, base_hit)
    assert stats_base[0]["evictions"] > 0
    assert all(s["evictions"] == 0 for s in stats_grp), stats_grp
    assert speedup >= 0.95, f"DP routing regressed throughput: {speedup:.2f}x"
    if parallel_host:
        # replicas genuinely overlap only when the host has cores to
        # run them on; a 1-CPU host timeshares every dispatch, so the
        # aggregate criterion applies to parallel hosts and the
        # artifact records the serialized measurement transparently
        assert speedup > 1.5, (
            f"DP aggregate speedup {speedup:.2f}x <= 1.5x "
            f"(base {ntok_base / wall_base:.1f} tok/s, "
            f"group {ntok_grp / wall_grp:.1f} tok/s)")

    result = {
        "n_devices": len(devs),
        "backend": jax.default_backend(),
        "model": {"hidden": cfg.hidden_size, "layers": cfg.num_layers,
                  "heads": cfg.num_heads, "scan_layers": True},
        "tp": arms,
        "collectives": collectives,
        "replicas": replicas,
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    return result


def serve_multichip_main(out_path="BENCH_SERVE.json"):
    """--serve --multichip: tensor-parallel + data-parallel serving on
    a 2-virtual-chip CPU mesh. The measurement runs in a subprocess
    with ``--xla_force_host_platform_device_count=2`` (the same
    ``__graft_entry__`` bootstrap the training multichip bench uses);
    see :func:`_serve_multichip_impl` for the three legs. Results merge
    into BENCH_SERVE.json under ``detail.serve_multichip`` (the
    single-chip serve sections stay), and the raw child artifact lands
    in BENCH_SERVE_MULTICHIP.json."""
    import __graft_entry__ as g

    child_out = "BENCH_SERVE_MULTICHIP.json"
    g.serve_multichip(2, child_out)
    with open(child_out) as f:
        res = json.load(f)
    # the child already asserted; re-check the headline gates so a stale
    # artifact can't masquerade as a pass
    assert res["tp"]["tp2_fp32"]["greedy_identical_to_single_device"]
    assert res["collectives"]["wire_ratio_int8_vs_fp32"] <= 0.30
    assert res["collectives"]["measured_equals_static"]
    assert res["replicas"]["n_replicas"] >= 2
    if not res["replicas"]["serialized_host"]:
        assert res["replicas"]["aggregate_speedup_x"] > 1.5
    assert res["replicas"]["aggregate_speedup_x"] >= 0.95
    result = {
        "metric": "serve_multichip_dp_aggregate_speedup_x",
        "value": res["replicas"]["aggregate_speedup_x"],
        "unit": "x",
        "vs_baseline": res["collectives"]["wire_ratio_int8_vs_fp32"],
        "detail": res,
    }
    print(json.dumps(result))
    if out_path:
        artifact = {}
        try:
            with open(out_path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            pass
        artifact.setdefault("detail", {})["serve_multichip"] = res
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return result


def serve_speculative_main(num_slots=None, trace_seed=None, kernel=None,
                           out_path="BENCH_SERVE.json"):
    """--serve --speculative: prompt-lookup speculative decoding A/B on
    the ragged serving path (docs/SERVING.md "Speculative decoding").

    Two traces, each served spec-on vs spec-off with the SAME engine,
    weights, slot count, and kernel:

    - ``repetitive``: the templated/extractive traffic shape
      prompt-lookup targets. A random-weight model has no natural
      templated text, so the trace is built by PROBING: serve a pool of
      tiled-pattern candidate prompts once (untimed), replay each greedy
      continuation through the host proposer offline, and keep the
      prompts whose continuations the n-gram lookup predicts best —
      requests whose decode really is self-repeating, the way
      summarization/code-edit output repeats its context. Drafts land
      and a decode step delivers up to ``1 + draft_len`` tokens.
    - ``random`` control: i.i.d. random prompts of the SAME lengths and
      gen budgets, no selection — the honest floor. Whatever acceptance
      the model's own greedy loops produce here is reported as-is; a
      ratio near or below 1.0 is acceptable and is exactly why
      speculation ships off by default.

    Both arms run ``decode_chunk=1`` so the A/B isolates the
    speculation mechanism (rounds-vs-rows on the SAME per-step cadence);
    multi-step decode fusion is a separate axis the main --serve bench
    measures.

    Hygiene per arm: byte-identical greedy streams across spec on/off
    (speculation must be a pure perf optimization), ZERO compiles
    inside every measured window (the warm replay of the identical
    deterministic trace touches the same T=1 / T=1+draft_len verify
    buckets the timed run hits), no preemptions (pool sized for the
    trace), and the scheduler's ``serve.spec`` counters must re-derive
    the delivered decode-token count (``plain_rows + rounds +
    accepted_tokens`` vs the stream recount) within 5%. Results merge
    into BENCH_SERVE.json under ``detail.speculative_ab``.
    """
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.inference.scheduler import Request
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, scan_layers=True)
        num_slots = num_slots or 8
        block_size = 32
        n_requests, gen, n_cands = 16, 96, 64
        unit_lens, reps = (6, 8, 12), 5
    else:
        cfg = LlamaConfig(
            vocab_size=4096, hidden_size=512, intermediate_size=1024,
            num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=512,
            dtype=jnp.float32)
        num_slots = num_slots or 4
        block_size = 8
        n_requests, gen, n_cands = 8, 48, 64
        unit_lens, reps = (4, 6, 8), 4
    decode_chunk = 1                         # same per-step cadence both arms
    draft_len, draft_ngram = 8, 2
    kernel = kernel or "reference"
    trace_seed = 1 if trace_seed is None else int(trace_seed)

    model = LlamaModel(cfg)
    params = jax.jit(
        lambda r: model.init(
            r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    engine = deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "bfloat16" if on_tpu else "float32"})

    from deepspeed_tpu.inference.speculative import propose_ngram_draft

    def pld_score(prompt, cont):
        """Offline replay of the greedy continuation through the host
        proposer: mean tokens delivered per verify round if this request
        were served speculatively (the selection metric)."""
        s = np.concatenate([prompt, np.asarray(cont, np.int32)])
        t, calls, delivered = len(prompt) + 1, 0, 0
        while t < len(s):
            d = propose_ngram_draft(s[:t], k=draft_len, ngram=draft_ngram)
            a = 0
            while a < len(d) and t + a < len(s) and d[a] == s[t + a]:
                a += 1
            calls += 1
            delivered += a + 1
            t += a + 1
        return delivered / calls

    def make_traces():
        rng = np.random.default_rng(trace_seed)
        cands = [np.tile(rng.integers(1, cfg.vocab_size,
                                      int(unit_lens[i % len(unit_lens)])),
                         reps)
                 for i in range(n_cands)]
        probes = engine.serve(
            [Request(rid=i, prompt=p, max_new_tokens=gen)
             for i, p in enumerate(cands)],
            num_slots=num_slots, block_size=block_size,
            decode_chunk=decode_chunk, attn_kernel=kernel,
            prefix_cache=False)
        probes = {c.rid: np.asarray(c.tokens) for c in probes}
        ranked = sorted(range(n_cands),
                        key=lambda i: pld_score(cands[i], probes[i]),
                        reverse=True)
        rep = [(cands[i], gen) for i in ranked[:n_requests]]
        ctl_rng = np.random.default_rng(trace_seed + 1)
        ctl = [(ctl_rng.integers(1, cfg.vocab_size, len(p)), g)
               for p, g in rep]
        return {"repetitive": rep, "random": ctl}

    traces = make_traces()

    def run_arm(trace, spec: bool):
        reqs = [Request(rid=i, prompt=p, max_new_tokens=g)
                for i, (p, g) in enumerate(trace)]
        before = engine.compile_obs.compiles_total("serve")
        t0 = time.time()
        comps = engine.serve(
            reqs, num_slots=num_slots, block_size=block_size,
            decode_chunk=decode_chunk, attn_kernel=kernel,
            # repetitive prompts re-served across arms would start
            # HITTING the engine's persistent prefix cache mid-A/B
            # (CoW copies, skipped prefills) — this bench isolates the
            # speculation win, so the cache stays out of it
            prefix_cache=False,
            speculative="prompt_lookup" if spec else "off",
            draft_len=draft_len, draft_ngram=draft_ngram)
        wall = max(c.t_finish for c in comps) - t0
        sched = engine.last_serve_scheduler
        delivered = sum(len(c.tokens) for c in comps)
        return {
            "tokens": {c.rid: np.asarray(c.tokens) for c in comps},
            "wall": wall,
            # first token of every request comes out of its prefill;
            # everything after is decode-path work — the number the
            # speculative rounds actually compress
            "decode_tokens": delivered - len(comps),
            "compiles_in_window": engine.compile_obs.compiles_total(
                "serve") - before,
            "preemptions": sched.preemptions,
            "spec_stats": sched.spec_stats(),
        }

    arms = {}
    for tname, trace in traces.items():
        for spec in (False, True):
            key = f"{tname}_{'spec_on' if spec else 'spec_off'}"
            run_arm(trace, spec)             # warm: compile every bucket
            arms[key] = run_arm(trace, spec)
            assert arms[key]["compiles_in_window"] == 0, \
                f"{key}: {arms[key]['compiles_in_window']} compiles " \
                f"inside the measured window"
            assert arms[key]["preemptions"] == 0, \
                f"{key}: A/B pool must not thrash"

    # hygiene: speculation is a pure perf opt — byte-identical streams
    for tname in traces:
        on_t = arms[f"{tname}_spec_on"]["tokens"]
        off_t = arms[f"{tname}_spec_off"]["tokens"]
        for rid, toks in off_t.items():
            assert np.array_equal(toks, on_t[rid]), \
                f"{tname} request {rid}: speculative stream diverged"

    # counter cross-check: the scheduler's own accounting must re-derive
    # what the streams actually delivered (engine-vs-bench agreement)
    for tname in traces:
        a = arms[f"{tname}_spec_on"]
        st = a["spec_stats"]
        derived = st["plain_rows"] + st["rounds"] + st["accepted_tokens"]
        assert abs(derived - a["decode_tokens"]) <= \
            max(1, int(0.05 * a["decode_tokens"])), \
            f"{tname}: spec counters derive {derived} decode tokens, " \
            f"streams delivered {a['decode_tokens']}"

    def arm_detail(key):
        a = arms[key]
        st = a["spec_stats"]
        return {
            "wall_s": round(a["wall"], 3),
            "decode_tokens": a["decode_tokens"],
            "decode_tokens_per_sec": round(a["decode_tokens"]
                                           / a["wall"], 1),
            "drafted_tokens": st["drafted_tokens"],
            "accepted_tokens": st["accepted_tokens"],
            "rejected_tokens": st["rejected_tokens"],
            "rounds": st["rounds"],
            "plain_rows": st["plain_rows"],
            "acceptance_rate": st["acceptance_rate"],
            "mean_accepted_per_round": st["mean_accepted_per_round"],
        }

    def speedup(tname):
        on_a = arms[f"{tname}_spec_on"]
        off_a = arms[f"{tname}_spec_off"]
        return round((on_a["decode_tokens"] / on_a["wall"])
                     / max(off_a["decode_tokens"] / off_a["wall"], 1e-9),
                     3)

    ab = {
        "arms": {k: arm_detail(k) for k in arms},
        "decode_speedup_x": {t: speedup(t) for t in traces},
        "trace": {"n_requests": n_requests, "gen": gen,
                  "unit_lens": list(unit_lens), "reps": reps,
                  "probe_candidates": n_cands,
                  "num_slots": num_slots, "block_size": block_size,
                  "decode_chunk": decode_chunk, "draft_len": draft_len,
                  "draft_ngram": draft_ngram, "trace_seed": trace_seed,
                  "attn_kernel": kernel},
        "greedy_identical": True,            # asserted above
        "backend": jax.default_backend(),
    }
    result = {
        "metric": "serve_speculative_decode_speedup_x",
        "value": ab["decode_speedup_x"]["repetitive"],
        "unit": "x",
        "vs_baseline": ab["decode_speedup_x"]["random"],
        "detail": ab,
    }
    print(json.dumps(result))
    if out_path:
        artifact = {}
        try:
            with open(out_path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            pass
        artifact.setdefault("detail", {})["speculative_ab"] = ab
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return result


def serve_disagg_main(num_slots=None, trace_seed=None, kernel=None,
                      out_path="BENCH_SERVE.json"):
    """--serve --disagg: prefill/decode disaggregation A/B over the
    tiered-KV transfer machinery (docs/SERVING.md "Disaggregated
    serving").

    One long-prompt flood trace, served by the SAME two engines (shared
    params, one virtual chip each) in two group shapes:

    - ``colocated``: a plain DP :class:`ReplicaGroup` with chunked
      prefill on — the PR-13 state of the art. Long prompts route by
      affinity/load, so every replica's decode slots share step budget
      with prefill chunks: each mixed step costs
      ~``chunk_tokens + n_decode`` tokens of compute and the shorts'
      TPOT inflates for the whole flood.
    - ``disagg``: the same engines split ``roles=["prefill","decode"]``.
      Longs run 1-token prefill legs on the prefill replica (chunked,
      ``publish_kv=True`` → content-addressed frames in the shared
      transfer tier) and land on the decode replica through
      ``begin_restore`` — already-prefilled. The decode replica runs
      with ``prefill_chunk_tokens=0`` (the split pure-decode program —
      the faithful disagg shape: decode roles never carry a prefill
      token budget), so its steps cost only the live decode tokens and
      the interference term drops out of the shorts' TPOT entirely.

    Headline: decode TPOT p99 across the short requests, colocated vs
    disaggregated (the acceptance gate asserts >= 1.5x). Hygiene:
    greedy streams byte-identical between arms (the transfer moves
    WHERE prefill runs, never WHAT a request decodes), every routed
    long actually restored (zero degrades — the measurement is the
    transfer, not a silent cold-prefill fallback), and ZERO compiles
    inside each arm's measured window summed over BOTH engines. Prefix
    caches reset between runs so the timed floods really prefill
    (cached prompts would erase the interference being measured).
    Results merge into BENCH_SERVE.json under ``detail.disagg_ab``.
    """
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.inference.replica import ReplicaGroup
    from deepspeed_tpu.inference.scheduler import Request
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
    from deepspeed_tpu.parallel.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, scan_layers=True)
        num_slots = num_slots or 8
        block_size = 32
        chunk_tok = 64
        n_long, long_len, long_gen = 6, 24 * block_size, 2
        n_short, short_len, short_gen = 8, 8, 64
    else:
        cfg = LlamaConfig(
            vocab_size=4096, hidden_size=512, intermediate_size=1024,
            num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=512,
            dtype=jnp.float32)
        num_slots = num_slots or 4
        block_size = 8
        chunk_tok = 16
        n_long, long_len, long_gen = 6, 24 * block_size, 2
        n_short, short_len, short_gen = 8, 8, 32
    decode_chunk = 2
    kernel = kernel or "reference"
    trace_seed = 5 if trace_seed is None else int(trace_seed)
    threshold = 8 * block_size

    model = LlamaModel(cfg)
    params = jax.jit(
        lambda r: model.init(
            r, jnp.zeros((1, 8), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    devs = jax.devices()
    dims = {"pipe": 1, "data": 1, "expert": 1, "sequence": 1,
            "tensor": 1}
    engines = [deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "bfloat16" if on_tpu else "float32"},
        mesh=make_mesh(dims=dict(dims), devices=[devs[i % len(devs)]]))
        for i in range(2)]

    def make_reqs(seed, t0=None):
        r = np.random.default_rng(seed)

        def at(off):
            return None if t0 is None else t0 + off

        # the flood: every long is in flight while the shorts decode
        reqs = [Request(rid=f"long{i}",
                        prompt=r.integers(1, cfg.vocab_size, long_len),
                        max_new_tokens=long_gen, arrival_time=at(0.0))
                for i in range(n_long)]
        reqs += [Request(rid=f"short{i}",
                         prompt=r.integers(1, cfg.vocab_size, short_len),
                         max_new_tokens=short_gen,
                         arrival_time=at(0.02))
                 for i in range(n_short)]
        return reqs

    serve_kw = dict(num_slots=num_slots, block_size=block_size,
                    decode_chunk=decode_chunk, attn_kernel=kernel,
                    prefill_chunk_tokens=chunk_tok, prefix_cache=True,
                    max_context=long_len + short_gen)

    def make_group(disagg):
        for eng in engines:
            eng.reset_prefix_cache()
        if disagg:
            return ReplicaGroup(engines, roles=["prefill", "decode"],
                                prefill_threshold_tokens=threshold)
        return ReplicaGroup(engines)

    def compiles_total():
        return sum(e.compile_obs.compiles_total("serve")
                   for e in engines)

    def run(disagg, seed, timed):
        group = make_group(disagg)
        for eng in engines:
            eng.reset_serve_metrics()
        t0 = time.time() + 0.01 if timed else None
        # the decode role runs the split pure-decode program (no ragged
        # prefill token budget in its step) — the disagg shape under
        # measurement, and what makes the interference term visible
        prk = {1: {"prefill_chunk_tokens": 0}} if disagg else None
        comps = group.serve(make_reqs(seed, t0),
                            per_replica_kwargs=prk, **serve_kw)
        assert all(c.status == "COMPLETED" for c in comps), \
            [(c.rid, c.status, c.error) for c in comps]
        if not timed:
            return None, group
        tpots = sorted(
            (c.t_finish - c.t_first_token) / (len(c.tokens) - 1)
            for c in comps if str(c.rid).startswith("short"))
        long_ttfts = sorted(c.t_first_token - c.t_submit for c in comps
                            if str(c.rid).startswith("long"))

        def pct(xs, q):
            return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]

        return {
            "tokens": {str(c.rid): [int(t) for t in c.tokens]
                       for c in comps},
            "decode_tpot_p50_s": round(pct(tpots, 0.50), 5),
            "decode_tpot_p99_s": round(pct(tpots, 0.99), 5),
            "long_ttft_p50_s": round(pct(long_ttfts, 0.50), 4),
        }, group

    arms, windows = {}, {}
    # both warm passes FIRST (fresh prompt seeds so the timed floods
    # never prefix-hit), then the timed passes on one shared seed
    run(False, trace_seed + 100, timed=False)
    run(True, trace_seed + 200, timed=False)
    for name, disagg in (("colocated", False), ("disagg", True)):
        warmed = compiles_total()
        arm, group = run(disagg, trace_seed, timed=True)
        in_window = compiles_total() - warmed
        assert in_window == 0, (
            f"{in_window} compile(s) inside the disagg-AB measured "
            f"window (arm {name})")
        windows[name] = {"measured_window_compiles": in_window}
        if disagg:
            # the win must come from the TRANSFER: every routed long
            # landed already-prefilled, none degraded to cold prefill
            sched = engines[1].last_serve_scheduler
            stats = sched.disagg_stats()
            assert stats["restored"] == n_long and \
                stats["degrades"] == 0, stats
            arm["disagg_stats"] = {k: stats[k] for k in
                                   ("handoffs", "restored", "degrades")}
            snap = engines[1].serve_metrics()
            lat = snap["histograms"].get(
                "serve.disagg.handoff_latency_s", {})
            arm["handoff_latency_p50_s"] = round(lat.get("p50", 0.0), 4)
        arms[name] = arm

    co, dis = arms["colocated"], arms["disagg"]
    assert co["tokens"] == dis["tokens"], \
        "disaggregation changed greedy outputs"
    for arm in arms.values():
        del arm["tokens"]
    improvement = co["decode_tpot_p99_s"] / max(dis["decode_tpot_p99_s"],
                                                1e-9)
    assert improvement >= 1.5, (
        f"decode TPOT p99 improved only {improvement:.2f}x "
        f"(colocated {co['decode_tpot_p99_s']}s vs disagg "
        f"{dis['decode_tpot_p99_s']}s) — the acceptance gate is 1.5x")
    ab = {
        "arms": arms,
        "decode_tpot_p99_improvement_x": round(improvement, 2),
        "byte_identical_between_arms": True,     # asserted above
        "zero_compiles_in_measured_window": True,  # asserted above
        "compile_windows": windows,
        "trace": {"n_long": n_long, "long_prompt_tokens": long_len,
                  "n_short": n_short, "short_prompt_tokens": short_len,
                  "short_gen_tokens": short_gen,
                  "chunk_tokens": chunk_tok,
                  "prefill_role_threshold_tokens": threshold},
        "attn_kernel": kernel,
        "backend": jax.default_backend(),
    }
    result = {
        "metric": "serve_disagg_decode_tpot_p99_improvement_x",
        "value": ab["decode_tpot_p99_improvement_x"],
        "unit": "x",
        "vs_baseline": co["decode_tpot_p99_s"],
        "detail": ab,
    }
    print(json.dumps(result))
    if out_path:
        artifact = {}
        try:
            with open(out_path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            pass
        artifact.setdefault("detail", {})["disagg_ab"] = ab
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return result


def serve_chaos_main(seed=None, out_path="BENCH_SERVE.json"):
    """--serve --chaos: the fault-tolerance contract measured on the
    REAL compiled serving path (docs/SERVING.md).

    Two arms over one seeded mixed-length trace on the same engine:

    - ``fault_free``: the plain continuous-batching run (the
      degradation baseline);
    - ``chaos``: the same trace with a seeded ``FaultInjector`` plan
      (pool-exhaustion window, mid-prefill fault, slot-attributed
      mid-decode fault, cancel burst) plus two requests carrying
      already-expired deadlines, the invariant auditor at EVERY chunk,
      and an abandoned-stream probe (a half-consumed generate_stream
      dropped mid-flight) after the drain.

    The bench ASSERTS the contract before recording: every request
    resolves to a terminal status, unaffected completions are
    byte-identical to the fault-free arm, and the pool ends fully free
    with a clean audit — then writes degradation metrics (tokens/s
    ratio, status counts, injector firing log, preemptions) into
    ``detail.chaos`` of BENCH_SERVE.json.
    """
    import gc

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.inference.faults import FaultInjector, FaultSpec
    from deepspeed_tpu.inference.scheduler import COMPLETED, Request
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    seed = 0 if seed is None else int(seed)
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, scan_layers=True)
        num_slots, n_requests, decode_chunk, block_size = 8, 32, 8, 32
        prompt_lens, gen_mix = (32, 64, 96), (16, 32, 64)
    else:
        cfg = LlamaConfig(
            vocab_size=4096, hidden_size=512, intermediate_size=1024,
            num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=512,
            dtype=jnp.float32)
        num_slots, n_requests, decode_chunk, block_size = 4, 24, 8, 8
        prompt_lens, gen_mix = (6, 10, 17), (8, 12, 24)

    model = LlamaModel(cfg)
    params = jax.jit(
        lambda r: model.init(
            r, jnp.zeros((1, max(prompt_lens)), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    engine = deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "bfloat16" if on_tpu else "float32"})

    def make_trace():
        rng = np.random.default_rng(seed + 1)
        return [(rng.integers(1, cfg.vocab_size,
                              int(rng.choice(prompt_lens))),
                 int(rng.choice(gen_mix)))
                for _ in range(n_requests)]

    trace = make_trace()
    total_gen = sum(g for _, g in trace)
    # deterministic victims: one prefill fault, one decode fault window,
    # a cancel burst, two expired deadlines — all drawn from the seed
    rng = np.random.default_rng(seed)
    victims = rng.choice(n_requests, size=5, replace=False).tolist()
    prefill_victim = victims[0]
    cancel_burst = victims[1:3]
    deadline_victims = set(victims[3:5])
    plan = [
        FaultSpec(site="pool", step=int(rng.integers(3, 8)),
                  duration=int(rng.integers(2, 5))),
        FaultSpec(site="prefill", rid=prefill_victim,
                  message="injected prefill fault"),
        FaultSpec(site="decode", step=int(rng.integers(8, 14)),
                  slot=int(rng.integers(0, num_slots)),
                  message="injected decode fault"),
        FaultSpec(site="cancel", step=int(rng.integers(4, 10)),
                  rids=cancel_burst),
    ]

    def reqs_for(chaos: bool):
        return [Request(
            rid=i, prompt=p, max_new_tokens=g,
            deadline_s=(0.0 if chaos and i in deadline_victims else None))
            for i, (p, g) in enumerate(make_trace())]

    def run(chaos: bool):
        fi = FaultInjector(plan, seed=seed) if chaos else None
        t0 = time.time()
        comps = engine.serve(reqs_for(chaos), num_slots=num_slots,
                             block_size=block_size,
                             decode_chunk=decode_chunk,
                             fault_injector=fi,
                             audit_every=1 if chaos else 0)
        wall = time.time() - t0
        sched = engine.last_serve_scheduler
        sched.audit(context="post-drain")        # clean or this run dies
        assert sched.pool.num_allocated == 0, "pool not fully free"
        return {"comps": {c.rid: c for c in comps}, "wall": wall,
                "preemptions": sched.preemptions,
                "injector": fi.summary() if fi else None}

    run(chaos=False)                             # compile warm-up
    base = run(chaos=False)
    chaos = run(chaos=True)

    # --- the contract, asserted before anything is recorded ------------------
    assert sorted(chaos["comps"]) == list(range(n_requests)), \
        "a request vanished without a terminal status"
    status_counts, affected = {}, set()
    generated_chaos = 0
    for rid, c in chaos["comps"].items():
        status_counts[c.status] = status_counts.get(c.status, 0) + 1
        generated_chaos += len(c.tokens)
        ref = np.asarray(base["comps"][rid].tokens)
        got = np.asarray(c.tokens)
        if c.status == COMPLETED:
            assert np.array_equal(got, ref), \
                f"unaffected request {rid} diverged under chaos"
        else:
            affected.add(rid)
            # partial streams are exact prefixes of the fault-free one
            assert np.array_equal(got, ref[:len(got)]), \
                f"request {rid}: partial stream diverged"

    # --- abandoned-stream probe on the same executor --------------------------
    stream = engine.generate_stream(reqs_for(False)[:6],
                                    num_slots=num_slots,
                                    block_size=block_size,
                                    decode_chunk=decode_chunk)
    next(stream)
    abandoned_pool = engine.last_serve_scheduler.pool
    held_mid_flight = abandoned_pool.num_allocated
    del stream
    gc.collect()
    assert abandoned_pool.num_allocated == 0, \
        "abandoned stream leaked KV blocks"

    base_tps = total_gen / base["wall"]
    chaos_tps = generated_chaos / chaos["wall"]
    detail = {
        "seed": seed,
        "n_requests": n_requests, "num_slots": num_slots,
        "decode_chunk": decode_chunk, "block_size": block_size,
        "total_trace_tokens": int(total_gen),
        "fault_free": {
            "tokens_per_sec": round(base_tps, 1),
            "wall_s": round(base["wall"], 3),
            "generated_tokens": int(total_gen),
        },
        "chaos": {
            "tokens_per_sec": round(chaos_tps, 1),
            "wall_s": round(chaos["wall"], 3),
            "generated_tokens": int(generated_chaos),
            "status_counts": status_counts,
            "affected_requests": sorted(affected),
            "preemptions": chaos["preemptions"],
            "injector": chaos["injector"],
        },
        "degradation": {
            # throughput of the surviving work vs the fault-free run —
            # isolation means faults cost their own tokens, not the arm
            "tokens_per_sec_ratio": round(chaos_tps / max(base_tps, 1e-9),
                                          3),
            "completed_fraction": round(
                status_counts.get(COMPLETED, 0) / n_requests, 3),
        },
        "unaffected_byte_identical": True,       # asserted above
        "pool_fully_free_after_all_arms": True,  # asserted above
        "auditor": "clean (every chunk)",
        "abandoned_stream_probe": {
            "blocks_held_mid_flight": int(held_mid_flight),
            "blocks_after_gc": 0,
        },
        "backend": jax.default_backend(),
    }
    result = {
        "metric": "serve_chaos_tokens_per_sec_ratio",
        "value": detail["degradation"]["tokens_per_sec_ratio"],
        "unit": "x_of_fault_free",
        "vs_baseline": detail["degradation"]["completed_fraction"],
        "detail": detail,
    }
    print(json.dumps(result))
    if out_path:
        artifact = {}
        try:
            with open(out_path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            pass
        artifact.setdefault("detail", {})["chaos"] = detail
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return result


def serve_overload_main(seed=None, out_path="BENCH_SERVE.json"):
    """--serve --overload: the admission-control A/B under an overload
    flood (docs/SERVING.md "Admission control & self-healing").

    One seeded flood — far more deadlined requests than the engine can
    finish in budget — served twice on the same warmed engine:

    - ``shed_off``: every request admitted FIFO; the tail expires
      TIMED_OUT, and requests that die MID-decode burn sampled-but-
      undelivered tokens (wasted work that also inflates the
      survivors' decode TPOT);
    - ``shed_on``: the same flood behind an ``AdmissionController``
      queue-depth band — overflow resolves REJECTED up front
      (structured terminals, zero executor work), the kept set decodes
      with the pool to itself.

    The bench ASSERTS the self-healing contract before recording:
    every request resolves to exactly one terminal in both arms, the
    pool ends fully free with a clean audit, ZERO compiles land inside
    either measured window, no high-priority request is shed, and the
    shed arm's goodput — both the delivered/sampled fraction and
    useful (in-deadline) tokens/s — is at least the unshed arm's,
    with decode TPOT p99 protected. Results merge into
    ``detail.overload_ab`` of BENCH_SERVE.json.
    """
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.inference.scheduler import (
        COMPLETED, REJECTED, TIMED_OUT, Request,
    )
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    seed = 0 if seed is None else int(seed)
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, scan_layers=True)
        num_slots, n_requests, decode_chunk, block_size = 8, 48, 8, 32
        prompt_lens, gen_mix = (32, 64, 96), (16, 32, 64)
    else:
        cfg = LlamaConfig(
            vocab_size=4096, hidden_size=512, intermediate_size=1024,
            num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=512,
            dtype=jnp.float32)
        num_slots, n_requests, decode_chunk, block_size = 4, 32, 8, 8
        prompt_lens, gen_mix = (6, 10, 17), (8, 12, 24)
    # low-water a bit UNDER the deadline capacity (the un-shed arm
    # completes about half the flood before its half-makespan deadline)
    # so the kept set finishes with headroom even on a noisy host; high
    # arms the band well above it so only a genuine flood trips shedding
    band = {"queue_depth_high": 3 * n_requests // 4,
            "queue_depth_low": n_requests // 2 - 2}

    model = LlamaModel(cfg)
    params = jax.jit(
        lambda r: model.init(
            r, jnp.zeros((1, max(prompt_lens)), jnp.int32))["params"])(
        jax.random.PRNGKey(0))
    engine = deepspeed_tpu.init_inference(
        model=model, params=params, model_config=cfg,
        config={"dtype": "bfloat16" if on_tpu else "float32"})

    n_priority = max(2, n_requests // 8)

    def make_reqs(deadline=None):
        rng = np.random.default_rng(seed + 1)
        return [Request(
            rid=i,
            prompt=rng.integers(1, cfg.vocab_size,
                                int(rng.choice(prompt_lens))),
            max_new_tokens=int(rng.choice(gen_mix)),
            deadline_s=deadline,
            # a sprinkling of high-priority requests: the shed ranking
            # must keep every one of them
            priority=(1 if i < n_priority else 0))
            for i in range(n_requests)]

    def compiles_total():
        return engine.compile_obs.compiles_total("serve")

    def run(deadline, shed):
        engine.reset_serve_metrics()
        t0 = time.time()
        comps = engine.serve(make_reqs(deadline), num_slots=num_slots,
                             block_size=block_size,
                             decode_chunk=decode_chunk,
                             admission=(dict(band) if shed else None))
        wall = time.time() - t0
        sched = engine.last_serve_scheduler
        sched.audit(context="post-overload")     # clean or this run dies
        assert sched.pool.num_allocated == 0, "pool not fully free"
        assert sorted(c.rid for c in comps) == list(range(n_requests)), \
            "a request vanished without a terminal status"
        status_counts = {}
        for c in comps:
            status_counts[c.status] = status_counts.get(c.status, 0) + 1
        completed = [c for c in comps if c.status == COMPLETED]
        useful = sum(len(c.tokens) for c in completed)
        tpots = sorted((c.t_finish - c.t_first_token)
                       / (len(c.tokens) - 1)
                       for c in completed if len(c.tokens) > 1)
        sampled = engine.metrics.counter("serve.tokens_sampled")
        delivered = engine.metrics.counter("serve.tokens_delivered")
        return {
            "comps": comps, "wall": wall,
            "status_counts": status_counts,
            "useful_tokens": int(useful),
            "useful_tokens_per_sec": round(useful / max(wall, 1e-9), 1),
            "goodput_fraction": round(delivered / max(sampled, 1), 4),
            "decode_tpot_p99_s": round(
                tpots[min(len(tpots) - 1,
                          int(round(0.99 * (len(tpots) - 1))))], 5)
            if tpots else None,
            "rejected_fraction": round(
                status_counts.get(REJECTED, 0) / n_requests, 3),
            "shed_episodes": int(
                engine.metrics.counter("serve.admission.shed_episodes")),
        }

    def attempt():
        """One calibrated A/B: returns (calib, deadline, arms, windows)
        or raises AssertionError if a contract gate fails."""
        # calibrate the deadline off a compile-free full run: half its
        # makespan leaves the unshed arm genuinely overloaded
        # (mid-decode expiries, not just queue expiries) while the
        # trimmed queue fits with headroom
        calib = run(None, shed=False)
        deadline = max(0.5 * calib["wall"], 0.05)
        arms, windows = {}, {}
        for name, shed in (("shed_off", False), ("shed_on", True)):
            before = compiles_total()
            arm = run(deadline, shed)
            in_window = compiles_total() - before
            assert in_window == 0, (
                f"{in_window} compile(s) inside the overload-AB "
                f"measured window (arm {name})")
            windows[name] = {"measured_window_compiles": in_window}
            if shed:
                assert arm["status_counts"].get(REJECTED, 0) > 0, \
                    "the shed arm never shed — the flood is not an overload"
                for c in arm["comps"]:
                    if c.rid < n_priority:
                        assert c.status != REJECTED, (
                            f"high-priority request {c.rid} was shed")
            del arm["comps"]
            arms[name] = arm
        on, off = arms["shed_on"], arms["shed_off"]
        # the acceptance gates: shedding must PROTECT goodput and decode
        # latency, not just drop work
        assert on["goodput_fraction"] >= off["goodput_fraction"], (
            f"shedding degraded delivered/sampled goodput: "
            f"{on['goodput_fraction']} < {off['goodput_fraction']}")
        assert on["useful_tokens_per_sec"] >= off["useful_tokens_per_sec"], (
            f"shedding degraded useful throughput: "
            f"{on['useful_tokens_per_sec']} < "
            f"{off['useful_tokens_per_sec']} tok/s")
        if on["decode_tpot_p99_s"] and off["decode_tpot_p99_s"]:
            assert (on["decode_tpot_p99_s"]
                    <= 1.25 * off["decode_tpot_p99_s"]), (
                f"shedding inflated decode TPOT p99: "
                f"{on['decode_tpot_p99_s']}s vs {off['decode_tpot_p99_s']}s")
        return calib, deadline, arms, windows

    # warm every prompt bucket + the decode program once; the A/B gates
    # on wall-clock, so a noisy shared host gets a fresh recalibrated
    # attempt before the run is declared a failure
    run(None, shed=False)
    warmed = compiles_total()
    attempts = 3
    for i in range(attempts):
        try:
            calib, deadline, arms, windows = attempt()
            break
        except AssertionError:
            if i == attempts - 1:
                raise
    assert warmed == compiles_total(), "late compile after warm-up"
    on, off = arms["shed_on"], arms["shed_off"]
    ab = {
        "seed": seed,
        "arms": arms,
        "admission_band": band,
        "deadline_s": round(deadline, 4),
        "calibration_wall_s": round(calib["wall"], 3),
        "n_requests": n_requests, "num_slots": num_slots,
        "n_priority": n_priority,
        "goodput_protected": True,               # asserted above
        "priority_never_shed": True,             # asserted above
        "zero_compiles_in_measured_window": True,  # asserted above
        "compile_windows": windows,
        "backend": jax.default_backend(),
    }
    result = {
        "metric": "serve_overload_goodput_fraction_shed_on",
        "value": on["goodput_fraction"],
        "unit": "delivered/sampled",
        "vs_baseline": off["goodput_fraction"],
        "detail": ab,
    }
    print(json.dumps(result))
    if out_path:
        artifact = {}
        try:
            with open(out_path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            pass
        artifact.setdefault("detail", {})["overload_ab"] = ab
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return result


def rlhf_main():
    """--rlhf: the DS-Chat-shaped three-model PPO loop — 770M actor on the
    hybrid engine (rollout prompt 256 + gen 128, the reference RLHF
    workload family, BASELINE.md seq 256+256), a critic engine, and a
    frozen reward model, through DeepSpeedPPOTrainer.generate_experience →
    train_rlhf. Reports e2e tokens/s with the generate/actor-step/
    critic-step wall split; vs_baseline is e2e throughput relative to the
    actor's pure-train throughput (the hybrid flip's efficiency — the
    reference's DS-Chat claim is precisely that generation need not
    dominate the loop)."""
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
    from deepspeed_tpu.runtime.ppo_trainer import (
        DeepSpeedPPOTrainer, LlamaCriticModel, make_actor_ppo_loss,
        make_critic_value_loss,
    )

    on_tpu = jax.default_backend() == "tpu"
    size_1b3 = "1b3" in sys.argv or "--size-1b3" in sys.argv
    if on_tpu and size_1b3:
        # DS-Chat scale (VERDICT r4 #5; BASELINE config #5 names OPT-1.3B,
        # blogs/deepspeed-chat/README.md:66 single-device capacity table):
        # a ~1.34B actor trained HBM-resident via bf16 mu + factored nu
        # (~13.4 GB of actor state on the 15.75 GB chip)
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_layers=24, num_heads=16, num_kv_heads=16, max_seq_len=2048,
            dtype=jnp.bfloat16, remat=True, remat_policy="nothing_saveable",
            scan_layers=True)
        critic_cfg = LlamaConfig(
            vocab_size=32000, hidden_size=512, intermediate_size=1408,
            num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=2048,
            dtype=jnp.bfloat16, remat=True, scan_layers=True)
        batch, prompt_len, gen_len, iters = 4, 256, 128, 3
    elif on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, remat=True, remat_policy="nothing_saveable",
            scan_layers=True)
        # DS-Chat pairs a big actor with a smaller critic/reward model
        critic_cfg = LlamaConfig(
            vocab_size=32000, hidden_size=512, intermediate_size=1408,
            num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=2048,
            dtype=jnp.bfloat16, remat=True, scan_layers=True)
        batch, prompt_len, gen_len, iters = 8, 256, 128, 3
    else:
        cfg = LlamaConfig.tiny(dtype=jnp.float32)
        critic_cfg = LlamaConfig.tiny(dtype=jnp.float32, num_layers=1)
        batch, prompt_len, gen_len, iters = 4, 8, 8, 2

    actor_model = LlamaModel(cfg)
    critic_model = LlamaCriticModel(critic_cfg)
    reward_model = LlamaCriticModel(critic_cfg)
    seq = prompt_len + gen_len
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, size=(batch, seq))
    sample = {"input_ids": toks, "labels": toks}

    def ds_cfg(extra=None):
        opt_params = {"lr": 1e-5}
        if size_1b3:
            # 1.34B actor on a 15.75 GB chip: fp32 m/v alone are 10.8 GB;
            # bf16 mu + factored nu keep the actor HBM-resident
            opt_params.update({"mu_dtype": "bfloat16",
                               "nu_dtype": "factored"})
        c = {"train_micro_batch_size_per_gpu": batch,
             "gradient_accumulation_steps": 1,
             "optimizer": {"type": "adamw", "params": opt_params},
             "zero_optimization": {"stage": 1},
             "bf16": {"enabled": on_tpu},
             "steps_per_print": 1000}
        c.update(extra or {})
        return c

    int8_rollout = "--int8-rollout" in sys.argv
    actor = deepspeed_tpu.initialize(
        model=actor_model, model_config=cfg,
        config=ds_cfg({"hybrid_engine": {
            "enabled": True, "max_out_tokens": seq + gen_len,
            "int8_streaming_rollout": int8_rollout}}),
        loss_fn=make_actor_ppo_loss(actor_model), sample_batch=sample)
    critic = deepspeed_tpu.initialize(
        model=critic_model, config=ds_cfg(),
        loss_fn=make_critic_value_loss(critic_model), sample_batch=sample)
    reward_params = reward_model.init(
        jax.random.PRNGKey(7), jnp.asarray(toks[:1]))["params"]
    reward_fn = DeepSpeedPPOTrainer.reward_from_params(reward_model,
                                                       reward_params)
    trainer = DeepSpeedPPOTrainer(actor, critic, reward_fn)

    prompts = rng.integers(0, cfg.vocab_size, size=(batch, prompt_len))

    def one_iter(i):
        return trainer.step(prompts, gen_len, rng=jax.random.PRNGKey(i))

    stats = one_iter(0)             # compile all programs
    windows = 3 if on_tpu else 1
    split = {"generate_s": [], "actor_step_s": [], "critic_step_s": []}

    def e2e_window():
        for i in range(iters):
            one_iter(i + 1)
            split["generate_s"].append(trainer.generate_time)
            split["actor_step_s"].append(trainer.actor_step_time)
            split["critic_step_s"].append(trainer.critic_step_time)

    e2e_tok_s = iters * batch * seq / time_best(e2e_window, windows)

    # ACTOR pure-train throughput at the same shapes for the overhead
    # ratio (the hybrid-flip efficiency claim is about the actor; timing
    # train_rlhf here would fold in the critic step + host GAE loop and
    # overstate the ratio)
    exp0 = trainer.generate_experience(prompts, gen_len,
                                       rng=jax.random.PRNGKey(99))
    adv0, ret0 = trainer._advantages(exp0)
    seq0 = exp0["seq"]
    actor_batch0 = {"input_ids": seq0[:, :-1], "labels": seq0[:, 1:],
                    "old_logp": exp0["old_logp"], "advantages": adv0,
                    "loss_mask": exp0["loss_mask"]}
    float(actor.train_batch(actor_batch0))

    def train_window():
        for _ in range(iters):
            float(actor.train_batch(actor_batch0))

    train_tok_s = iters * batch * seq / time_best(train_window, windows)

    med = lambda xs: round(float(np.median(xs)), 3) if xs else 0.0
    print(json.dumps({
        "metric": ("llama1b3_rlhf_e2e_tokens_per_sec" if size_1b3
                   else "llama770m_rlhf_e2e_tokens_per_sec")
                  + ("_int8roll" if int8_rollout else ""),
        "value": round(e2e_tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(e2e_tok_s / max(train_tok_s, 1e-6), 3),
        "detail": {"batch": batch, "prompt_len": prompt_len,
                   "gen_len": gen_len, "iters": iters,
                   "actor_hidden": cfg.hidden_size,
                   "actor_layers": cfg.num_layers,
                   "generate_s_p50": med(split["generate_s"]),
                   "actor_step_s_p50": med(split["actor_step_s"]),
                   "critic_step_s_p50": med(split["critic_step_s"]),
                   "train_only_tokens_per_sec": round(train_tok_s, 1),
                   "actor_loss": stats["actor_loss"],
                   "critic_loss": stats["critic_loss"],
                   "backend": jax.default_backend()},
    }))


def longseq_main():
    """--longseq: long-context training throughput — 770M at seq 8192,
    batch 1 (same tokens/step as the default bench): the Pallas flash
    fwd+bwd keeps attention O(S) so the step fits and runs at speed; the
    chunked LM loss keeps the [1, S, V] logits out of HBM. vs_baseline is
    the same MFU ratio as the default metric."""
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=8192,
            dtype=jnp.bfloat16, remat=True, remat_policy="nothing_saveable",
            scan_layers=True)
        batch, seq, steps = 1, 8192, 10
    else:
        cfg = LlamaConfig.tiny(dtype=jnp.float32)
        batch, seq, steps = 2, 128, 3

    model = LlamaModel(cfg)
    ds_config = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": on_tpu},
        "fused_lm_loss": {"enabled": True, "chunk_size": 512},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
    }
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, size=(batch, seq + 1))
    engine = deepspeed_tpu.initialize(
        model=model, config=ds_config,
        sample_batch={"input_ids": toks[:1, :-1], "labels": toks[:1, 1:]})
    batches = []
    for _ in range(2):
        t = rng.integers(0, cfg.vocab_size, size=(batch, seq + 1))
        batches.append({"input_ids": t[:, :-1], "labels": t[:, 1:]})
    float(engine.train_batch(batches[0]))

    state = {}

    def window():
        # async-chained steps, ONE host transfer at the end (per-step
        # blocking would serialize the tunnel)
        for i in range(steps):
            state["loss"] = engine.train_batch(batches[i % 2])
        float(state["loss"])

    dt = time_best(window, 4 if on_tpu else 1)
    n_chips = jax.device_count()
    tok_s = steps * batch * seq / dt / n_chips
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(engine.params))
    mfu = 6.0 * n_params * tok_s / (197e12 if on_tpu else 1e12)
    print(json.dumps({
        "metric": "llama770m_seq8192_train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / (49.0 / 125.0), 3),
        "detail": {"params": int(n_params), "batch": batch, "seq": seq,
                   "steps": steps, "wall_s": round(dt, 2), "n_chips": n_chips,
                   "mfu": round(mfu, 4), "loss": float(state["loss"]),
                   "backend": jax.default_backend()},
    }))


def attention_main():
    """--attention: chip perf rows for the long-context attention ops
    (VERDICT r4 #8) — dense Pallas flash vs block-sparse (BigBird and
    sliding-window layouts) vs ring-flash/Ulysses at P=1, fwd+bwd, seq
    4k/8k. The reference's sparse attention exists BECAUSE it wins at
    long sequence (ops/sparse_attention/sparse_self_attention.py:12);
    these rows measure where that crossover actually sits on this chip.
    Ring/Ulysses on ONE chip measure orchestration overhead at P=1 (the
    degenerate ring), NOT scaling — scaling is pinned on the CPU mesh
    (tests/unit/ops/) and in dryrun A2. All candidates run adjacent in
    one process per tpu-tunnel discipline."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.flash_attention import flash_attention
    from deepspeed_tpu.ops.sparse_attention import (
        BigBirdSparsityConfig, LocalSlidingWindowSparsityConfig,
        sparse_attention,
    )

    on_tpu = jax.default_backend() == "tpu"
    B, H, D = 1, 16, 128                       # 7B-like head geometry
    seqs = (4096, 8192) if on_tpu else (256,)
    block = 64
    rng = np.random.default_rng(0)
    rows = []

    def timed(fn, *args):
        # grad over ALL of q/k/v — argnums=0 alone would let XLA
        # dead-code-eliminate the dk/dv backward (sparse's whole dkv
        # kernel) while the flops model credits the full backward
        f = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2)))
        fence = lambda outs: float(jnp.sum(outs[0]) + jnp.sum(outs[1])
                                   + jnp.sum(outs[2]))
        fence(f(*args))                        # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.time()
            fence(f(*args))                    # element fence
            best = min(best, time.time() - t0)
        return best

    for S in seqs:
        q, k, v = (jnp.asarray(rng.standard_normal((B, S, H, D)) * 0.1,
                               jnp.bfloat16) for _ in range(3))
        flops = 4.0 * B * H * S * S * D * 3 / 2   # causal fwd+bwd(2x) halves
        res = {}

        def record(name, fn, density=1.0):
            try:
                t = timed(fn, q, k, v)
                res[name] = {"ms": round(t * 1e3, 1),
                             "dense_tflops_equiv": round(
                                 flops / t / 1e12, 1)}
                if density < 1.0:
                    res[name]["density"] = round(density, 3)
            except Exception as e:             # noqa: BLE001
                res[name] = {"error": repr(e)[:160]}

        record("flash", lambda q, k, v: flash_attention(q, k, v,
                                                        causal=True))
        for name, cfgc in (
                ("sparse_bigbird", BigBirdSparsityConfig(
                    num_heads=H, block=block)),
                ("sparse_local512", LocalSlidingWindowSparsityConfig(
                    num_heads=H, block=block, num_sliding_window_blocks=8))):
            layout = cfgc.make_layout(S)
            density = float(np.asarray(layout).mean())
            record(name, lambda q, k, v, layout=layout: sparse_attention(
                q, k, v, layout, block), density)

        # ring/ulysses at P=1 — overhead row, honestly labeled
        from functools import partial

        from jax.sharding import Mesh, PartitionSpec as P

        from deepspeed_tpu.ops.ring_attention import ring_flash_attention
        from deepspeed_tpu.ops.ulysses import ulysses_attention

        mesh1 = Mesh(np.array(jax.devices()[:1]), ("sequence",))
        for name, op in (("ring_flash_p1", ring_flash_attention),
                         ("ulysses_p1", partial(ulysses_attention,
                                                attention_impl="flash"))):
            def sharded(q, k, v, op=op):
                f = jax.shard_map(
                    lambda a, b, c: op(a, b, c, causal=True),
                    mesh=mesh1,
                    in_specs=(P(None, "sequence"),) * 3,
                    out_specs=P(None, "sequence"), check_vma=False)
                return f(q, k, v)
            record(name, sharded)
        rows.append({"seq": S, "results": res})
        print(f"# seq {S}: " + json.dumps(res), file=sys.stderr, flush=True)

    flash4k = rows[0]["results"].get("flash", {}).get("ms")
    best_sparse = min((r.get("ms", 1e9)
                       for r in rows[-1]["results"].values()
                       if isinstance(r, dict) and "density" in r),
                      default=None)
    flash_last = rows[-1]["results"].get("flash", {}).get("ms", None)
    speedup = (round(flash_last / best_sparse, 2)
               if best_sparse and flash_last else 0.0)
    print(json.dumps({
        "metric": f"attention_fwd_bwd_ms_flash_seq{seqs[0]}",
        "value": flash4k if flash4k is not None else -1,
        "unit": "ms",
        "vs_baseline": speedup,   # best sparse speedup over flash @ max seq
        "detail": {"rows": rows, "shape": {"B": B, "H": H, "D": D,
                                           "block": block},
                   "backend": jax.default_backend()},
    }))
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tools", "bench_attention.json"), "w") as f:
        json.dump(rows, f, indent=1)


def moe_main():
    """--moe: expert-parallel GPT training throughput (BASELINE.json config
    #3 — DeepSpeed-MoE alternating dense/MoE layers, reference
    moe/sharded_moe.py). Single-chip proxy: measures the full capacity-based
    gating + dispatch/combine + batched-expert path; multi-chip all_to_all
    rides the same sharding constraints over the expert mesh axis
    (dry-run-compiled in __graft_entry__ case C). vs_baseline is MFU over
    ACTIVE FLOPs (top-k experts/token) against the same 49/125 V100 bar."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import loss_fn as lm_loss
    from deepspeed_tpu.models.transformer import (
        GatedMLP, RMSNorm, SelfAttention, make_causal_mask,
    )
    from deepspeed_tpu.moe.layer import MoE

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        V, D, F, L, H, E, K = 32000, 1024, 4096, 12, 16, 8, 1
        batch, seq, steps = 8, 512, 10
        dtype = jnp.bfloat16
    else:
        V, D, F, L, H, E, K = 256, 64, 128, 2, 4, 4, 1
        batch, seq, steps = 4, 64, 3
        dtype = jnp.float32

    class MoEGPT(nn.Module):
        """Alternating dense/MoE decoder (DeepSpeed-MoE structure:
        every other layer's MLP is a capacity-gated expert layer)."""

        @nn.compact
        def __call__(self, ids):
            B, S = ids.shape
            x = nn.Embed(V, D, dtype=dtype, param_dtype=jnp.float32,
                         name="wte")(ids)
            mask = make_causal_mask(S)
            aux_total = 0.0
            for i in range(L):
                h = RMSNorm(dtype=dtype, name=f"ln_a{i}")(x)
                x = x + SelfAttention(num_heads=H, dtype=dtype,
                                      assume_causal_mask=True,
                                      name=f"attn{i}")(h, mask=mask)
                h = RMSNorm(dtype=dtype, name=f"ln_m{i}")(x)
                if i % 2 == 1:
                    out, aux = MoE(num_experts=E, hidden_size=D,
                                   intermediate_size=F, k=K, dtype=dtype,
                                   name=f"moe{i}")(h)
                    x = x + out
                    aux_total = aux_total + aux
                else:
                    x = x + GatedMLP(intermediate_size=F, dtype=dtype,
                                     name=f"mlp{i}")(h)
            x = RMSNorm(dtype=dtype, name="ln_f")(x)
            logits = nn.Dense(V, use_bias=False, dtype=dtype,
                              param_dtype=jnp.float32, name="lm_head")(x)
            return logits.astype(jnp.float32), aux_total

    model = MoEGPT()

    def loss_fn(params, batch_d, rngs=None):
        logits, aux = model.apply({"params": params}, batch_d["input_ids"])
        return lm_loss(logits, batch_d["labels"]) + 0.01 * aux

    rng = np.random.default_rng(0)
    t0 = rng.integers(0, V, size=(batch, seq + 1))
    engine = deepspeed_tpu.initialize(
        model=model, loss_fn=loss_fn,
        config={"train_micro_batch_size_per_gpu": batch,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 1},
                "bf16": {"enabled": on_tpu},
                "gradient_clipping": 1.0, "steps_per_print": 1000},
        sample_batch={"input_ids": t0[:1, :-1], "labels": t0[:1, 1:]})

    batches = []
    for _ in range(3):
        t = rng.integers(0, V, size=(batch, seq + 1))
        batches.append({"input_ids": t[:, :-1], "labels": t[:, 1:]})
    float(engine.train_batch(batches[0]))

    state = {}

    def window():
        for i in range(steps):
            state["loss"] = engine.train_batch(batches[i % len(batches)])
        float(state["loss"])

    dt = time_best(window, 4 if on_tpu else 1)
    n_chips = jax.device_count()
    tok_s = steps * batch * seq / dt / n_chips
    # active params: experts contribute K/E of their stack per token
    from deepspeed_tpu.moe.utils import moe_param_mask
    mask = moe_param_mask(engine.params)
    total = expert = 0
    for leaf, is_moe in zip(jax.tree_util.tree_leaves(engine.params),
                            jax.tree_util.tree_leaves(mask)):
        total += leaf.size
        if is_moe:
            expert += leaf.size
    active = total - expert + expert * K // E
    mfu = 6.0 * active * tok_s / (197e12 if on_tpu else 1e12)
    print(json.dumps({
        "metric": "moe_gpt_e8_top1_train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / (49.0 / 125.0), 3),
        "detail": {"total_params": int(total), "active_params": int(active),
                   "experts": E, "top_k": K, "batch": batch, "seq": seq,
                   "steps": steps, "wall_s": round(dt, 2), "n_chips": n_chips,
                   "mfu_active": round(mfu, 4), "loss": float(state["loss"]),
                   "backend": jax.default_backend()},
    }))


def aio_main():
    """--aio: measure the C++ AIO threadpool (VERDICT r2 #7 — the AIO layer
    needed performance evidence; reference csrc/aio + tests/perf).
    Sequential/random read+write MB/s through the swap path, plus the
    projected ZeRO-Infinity step overhead at 770M against README's
    16 bytes/param/step budget."""
    import os
    import tempfile

    from deepspeed_tpu.ops.native import AsyncIOHandle

    chunk_mb = 64
    n_chunks = 8
    total = chunk_mb * n_chunks * (1 << 20)
    bufs = [np.random.default_rng(i).integers(
        0, 255, chunk_mb << 20, dtype=np.uint8) for i in range(n_chunks)]
    out = {}
    with tempfile.TemporaryDirectory(dir="/tmp") as d:
        aio = AsyncIOHandle(block_size=1 << 20, queue_depth=16,
                            thread_count=4)
        paths = [os.path.join(d, f"blk{i}.bin") for i in range(n_chunks)]

        t0 = time.time()
        for p, b in zip(paths, bufs):
            aio.pwrite(p, b)
        assert aio.wait() == 0
        out["seq_write_MBps"] = total / (time.time() - t0) / 1e6

        # evict the just-written pages so preads hit storage, not the page
        # cache (sync flushes but does NOT evict)
        for p in paths:
            fd = os.open(p, os.O_RDONLY)
            try:
                os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
            finally:
                os.close(fd)
        reads = [np.empty(chunk_mb << 20, np.uint8) for _ in range(n_chunks)]
        t0 = time.time()
        for p, b in zip(paths, reads):
            aio.pread(p, b)
        assert aio.wait() == 0
        out["seq_read_MBps"] = total / (time.time() - t0) / 1e6

        # random 1MB reads at random offsets within the written files
        for p in paths:
            fd = os.open(p, os.O_RDONLY)
            try:
                os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
            finally:
                os.close(fd)
        rng = np.random.default_rng(0)
        small = [np.empty(1 << 20, np.uint8) for _ in range(64)]
        t0 = time.time()
        for b in small:
            p = paths[rng.integers(n_chunks)]
            off = int(rng.integers(chunk_mb - 1)) << 20
            aio.pread(p, b, offset=off)
        assert aio.wait() == 0
        out["rand_read_1M_MBps"] = 64 * (1 << 20) / (time.time() - t0) / 1e6
        aio.close()

    # ZeRO-Infinity budget: each step reads AND writes fp32 m+v → 16 B/param
    p770 = 777_856_512
    rw_mbps = 2 / (1 / out["seq_read_MBps"] + 1 / out["seq_write_MBps"])
    out["projected_770m_step_overhead_s"] = 16 * p770 / (rw_mbps * 1e6)
    print(json.dumps({
        "metric": "aio_seq_rw_MBps",
        "value": round(rw_mbps, 1),
        "unit": "MB/s",
        "vs_baseline": 0,
        "detail": {k: round(v, 2) for k, v in out.items()},
    }))


BASE_770M_KWARGS = dict(
    vocab_size=32000, hidden_size=1536, intermediate_size=4096,
    num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
    remat=True, remat_policy="nothing_saveable", scan_layers=True)


def _autotune_trial(spec_path: str):
    """--autotune-trial <spec.json>: ONE isolated tuner experiment (child
    process of --autotune). Prints a single JSON result line; a crash (OOM,
    compile-helper failure) exits nonzero without poisoning the parent's
    backend — the reference's per-experiment job isolation
    (autotuning/scheduler.py)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    with open(spec_path) as f:
        spec = json.load(f)
    cfg_dict = dict(spec["config"])
    overrides = cfg_dict.pop("_model_overrides", None) or {}
    mcfg = LlamaConfig(**{**spec["model_kwargs"], **overrides,
                          "dtype": jnp.bfloat16 if spec["bf16"]
                          else jnp.float32})
    seq = spec["seq"]
    mbs = cfg_dict.get("train_micro_batch_size_per_gpu", 1)
    gas = cfg_dict.get("gradient_accumulation_steps", 1)
    rng = np.random.default_rng(0)

    def batch():
        t = rng.integers(0, mcfg.vocab_size, size=(mbs * gas, seq + 1))
        return {"input_ids": t[:, :-1], "labels": t[:, 1:]}

    engine = deepspeed_tpu.initialize(model=LlamaModel(mcfg),
                                      config=cfg_dict,
                                      sample_batch=batch())
    b = batch()
    steps = max(spec["end"], spec["start"] + 1)
    t0, timed = None, 0
    for i in range(steps):
        if i == spec["start"]:
            t0 = time.perf_counter()
        loss = engine.train_batch(b)
        _ = float(loss)
        if t0 is not None:
            timed += 1
    elapsed = time.perf_counter() - t0
    print(json.dumps({"throughput": mbs * gas * timed / max(elapsed, 1e-9),
                      "latency": elapsed / max(timed, 1)}))


def autotune_main():
    """--autotune: close the loop between the autotuner and the shipping
    bench (VERDICT r2 #4) — the tuner searches zero-stage × micro-batch ×
    remat-policy × fused_lm_loss over REAL timed trials on this chip
    (each trial an isolated subprocess: a crashing candidate must not
    poison the backend for later ones) and must reproduce-or-beat the
    hand-picked 16×512 / whole-block-remat operating point. Prints the
    BENCH JSON line measured with the TUNER'S chosen config (plus the
    search trace in detail)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.autotuning.autotuner import Autotuner, ModelInfo
    from deepspeed_tpu.autotuning.config import get_autotuning_config
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        base_model_cfg = LlamaConfig(dtype=jnp.bfloat16, **BASE_770M_KWARGS)
        seq, steps = 512, 6
        # exhaustive over the axes that matter (early stopping with the
        # memory-cheapest-first candidate order would otherwise stop
        # inside the small-batch tier before ever timing mbs=16 — the
        # round-3 expanded grid hit exactly that)
        search = {"zero_stages": [1], "micro_batch_sizes": [16, 24],
                  "remat_policies": ["block:nothing_saveable",
                                     "block:save_mlp", "none"],
                  "fused_lm_loss_options": [False],
                  "moment_dtypes": [None, "bfloat16", "bf16mu+factored"],
                  "tuner_early_stopping": 100,
                  "start_profile_step": 2, "end_profile_step": 5}
        hbm = 15.75e9
    else:   # CPU smoke: tiny model, tiny search
        base_model_cfg = LlamaConfig.tiny(dtype=jnp.float32)
        seq, steps = 64, 3
        search = {"zero_stages": [1], "micro_batch_sizes": [2, 4],
                  "remat_policies": ["block:nothing_saveable", "none"],
                  "start_profile_step": 1, "end_profile_step": 2}
        hbm = None

    base_config = {
        "train_micro_batch_size_per_gpu": 16 if on_tpu else 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": on_tpu},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
        "autotuning": {"enabled": True, "tuner_type": "gridsearch",
                       "metric": "throughput", **search},
    }
    rng = np.random.default_rng(0)
    vocab = base_model_cfg.vocab_size

    def batch_factory(mbs, gas):
        t = rng.integers(0, vocab, size=(mbs * gas, seq + 1))
        return {"input_ids": t[:, :-1], "labels": t[:, 1:]}

    def engine_factory(cfg_dict):
        cfg_dict = dict(cfg_dict)
        overrides = cfg_dict.pop("_model_overrides", None) or {}
        mcfg = dataclasses.replace(base_model_cfg, **overrides)
        model = LlamaModel(mcfg)
        mbs = cfg_dict.get("train_micro_batch_size_per_gpu", 1)
        return deepspeed_tpu.initialize(
            model=model, config=cfg_dict,
            sample_batch=batch_factory(min(mbs, 2), 1))

    # model info from a cheap traced forward of the base model
    probe_engine = engine_factory({k: v for k, v in base_config.items()
                                   if k != "autotuning"})
    n_params = sum(x.size for x in
                   jax.tree_util.tree_leaves(probe_engine.params))
    act_per_sample = int(
        (2 if on_tpu else 4) * seq * base_model_cfg.hidden_size
        * base_model_cfg.num_layers * 2)       # residual-pair rule of thumb
    info = ModelInfo(n_params, act_per_sample, 6.0 * n_params * seq)
    probe_engine.destroy()
    del probe_engine
    import gc

    gc.collect()

    def subprocess_runner(cand, cfg_dict):
        """One trial in its own process (see _autotune_trial)."""
        import subprocess
        import tempfile

        spec = {"config": cfg_dict, "seq": seq,
                "start": search["start_profile_step"],
                "end": search["end_profile_step"],
                "model_kwargs": BASE_770M_KWARGS, "bf16": on_tpu}
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(spec, f)
            path = f.name
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--autotune-trial", path],
                capture_output=True, text=True, timeout=1200,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        finally:
            os.unlink(path)
        if r.returncode != 0:
            raise RuntimeError(
                f"trial failed (rc={r.returncode}): {r.stdout[-300:]} "
                f"{r.stderr[-300:]}")
        return json.loads(r.stdout.strip().splitlines()[-1])

    tuner = Autotuner(engine_factory, batch_factory, base_config, info,
                      dp_size=1, hbm_bytes_per_device=hbm,
                      config=get_autotuning_config(base_config),
                      experiment_runner=subprocess_runner if on_tpu
                      else None)
    best_cfg = tuner.tune()
    assert best_cfg is not None, "autotuner found no feasible config"
    gc.collect()       # last trial's buffers must be gone before the bench

    # measure the BENCH metric with the tuner's chosen config
    overrides = best_cfg.pop("_model_overrides", None) or {}
    mcfg = dataclasses.replace(base_model_cfg, **overrides)
    model = LlamaModel(mcfg)
    mbs = best_cfg["train_micro_batch_size_per_gpu"]
    engine = deepspeed_tpu.initialize(model=model, config=best_cfg,
                                      sample_batch=batch_factory(mbs, 1))
    batches = [batch_factory(mbs, 1) for _ in range(4)]
    float(engine.train_batch(batches[0]))
    state = {}

    def window():
        for i in range(steps):
            state["loss"] = engine.train_batch(batches[i % len(batches)])
        float(state["loss"])

    dt = time_best(window, 3 if on_tpu else 1)
    tok = steps * mbs * seq / dt
    flops_per_sec = 6.0 * n_params * tok
    peak = 197e12 if on_tpu else 1e12
    mfu = flops_per_sec / peak
    trials = {k: (round(v.get("throughput", 0), 1)
                  if "error" not in v else "infeasible")
              for k, v in tuner.results.items()}
    best_key = max((k for k, v in tuner.results.items() if "error" not in v),
                   key=lambda k: tuner.results[k].get("throughput", 0),
                   default="?")
    print(json.dumps({
        "metric": "llama770m_autotuned_train_tokens_per_sec_per_chip",
        "value": round(tok, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / (49.0 / 125.0), 3),
        "detail": {"chosen": best_key, "micro_batch": mbs, "seq": seq,
                   "model_overrides": overrides,
                   "fused_lm_loss": best_cfg.get("fused_lm_loss", {}),
                   "mfu": round(mfu, 4), "trials": trials,
                   "backend": jax.default_backend()},
    }))


def multichip_main(dryrun: bool = False, train_telemetry: bool = True,
                   fleet: bool = True):
    """--multichip [--dryrun] [--no-train-telemetry] [--no-fleet]:
    record the STATIC collective inventory — every multi-chip entry
    point's collectives by mesh axis (count + per-device wire bytes per
    step, the dstlint SPMD pass's abstract trace) — into
    MULTICHIP_COMMS.json, so the perf trajectory carries comms
    structure alongside step time. By default it also runs the MEASURED
    dsttrain telemetry leg: a real pipe=2 × data=4 1F1B train on the
    8-device virtual mesh (__graft_entry__.telemetry_multichip)
    collecting bubble fraction, schedule efficiency, the grad-norm
    trajectory and MoE drop fraction into the same artifact — with the
    engine-reported step time cross-checked against the bench's
    external measurement within 5% (the training twin of the serving
    bench's TTFT agreement guard); the telemetry leg now also measures
    a real host-boundary all-reduce and asserts its wire bytes equal
    the static budget pricing. The dstfleet leg
    (__graft_entry__.fleet_multichip) then runs 8 REAL train
    PROCESSES exchanging rank<k>.json snapshots through a shared
    fleet_dir, merges them with MetricsRegistry.merge, and ASSERTS
    merged counter totals == per-rank sums, merged histogram counts ==
    per-rank count sums, a clean host-labeled exposition, and that the
    doubled-accumulation straggler rank surfaces in
    fleet.step_time.skew. ``--dryrun`` additionally runs the full
    8-device parallelism dry run (__graft_entry__) first."""
    import tempfile

    import __graft_entry__

    if dryrun:
        __graft_entry__.dryrun_multichip(8)

    from deepspeed_tpu.tools.dstlint.spmdpass import (
        inventory_summary, trace_spmd_entry_points,
    )

    reports = trace_spmd_entry_points()
    summary = inventory_summary(reports)
    errors = sorted(n for n, rep in reports.items() if rep.error)
    tele = None
    if train_telemetry:
        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as tf:
            tele_path = tf.name
        __graft_entry__.telemetry_multichip(8, tele_path)
        with open(tele_path) as f:
            tele = json.load(f)
        os.unlink(tele_path)
    artifact = {
        "source": "dstlint spmd pass (abstract meshes; "
                  "comm/collective_cost.py wire arithmetic)",
        "entries": summary,
        "total_wire_bytes_per_step": sum(
            e.get("total_wire_bytes", 0) for e in summary.values()),
    }
    if tele is not None:
        # measured dsttrain leg rides the same artifact the static
        # inventory lives in (the MULTICHIP_* series)
        artifact["train_telemetry"] = tele
    fleet_summary = None
    if fleet:
        with tempfile.TemporaryDirectory(prefix="dst_fleet_") as fd:
            fleet_summary = __graft_entry__.fleet_multichip(8, fd)
        artifact["fleet"] = fleet_summary
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MULTICHIP_COMMS.json")
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    per_axis = {}
    for entry in summary.values():
        for axes, rec in entry.get("per_axis", {}).items():
            tot = per_axis.setdefault(axes, {"count": 0, "bytes": 0})
            tot["count"] += rec["count"]
            tot["bytes"] += rec["bytes"]
    out = {
        "metric": "static_collective_inventory",
        "entries": len(summary), "errors": errors,
        "per_axis": per_axis,
        "total_wire_bytes_per_step": artifact["total_wire_bytes_per_step"],
        "artifact": "MULTICHIP_COMMS.json",
    }
    if tele is not None:
        out["train_telemetry"] = {
            "bubble_fraction": tele["bubble_fraction"],
            "schedule_efficiency": tele["schedule_efficiency"],
            "step_time_agreement": tele["step_time_crosscheck"][
                "agreement"],
            "moe_token_drop_fraction": tele["moe"].get(
                "token_drop_fraction"),
            "measured_wire_vs_static": tele.get(
                "measured_collectives", {}).get("all_reduce", {}),
        }
    if fleet_summary is not None:
        out["fleet"] = {
            "ranks": fleet_summary["ranks"],
            "counters_equal_rank_sums": fleet_summary["merge"][
                "counters_equal_rank_sums"],
            "step_time_skew": fleet_summary["fleet_gauges"][
                "step_time_skew"],
        }
    print(json.dumps(out))
    if errors:
        sys.exit(f"spmd trace errors: {errors}")


def main():
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    on_tpu = jax.default_backend() == "tpu"
    # Size to chip: ~770M params on a single v5e chip (best measured MFU of
    # the 350M/550M/770M/1B ladder — larger matmuls, still fits fp32
    # optimizer states + remat activations); tiny on CPU smoke runs.
    # Operating point 16x512 over 8x1024: same tokens/step, but the XLA
    # attention softmax traffic scales with S^2 per sequence — measured
    # 17.5k tok/s (MFU 0.415) at 16x512 vs 13.1k (0.311) at 8x1024.
    # 512 matches the reference's RLHF workload seqlen (BASELINE.md,
    # 256 prompt + 256 gen).
    # Round-3 operating point (tools/perf_sweep_remat_gas_moments.json):
    # bf16 Adam moments (moment_dtype — m/v storage 12.4 -> 9.3 GB) free
    # enough HBM for the save_mlp partial-remat policy, which every fp32-
    # moment config OOMed on. Same-session ladder: fp32+block 17.6k ->
    # bf16mom+block 17.9k -> bf16mom+save_mlp 18.5k tok/s.
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=24, num_heads=24, num_kv_heads=24, max_seq_len=2048,
            dtype=jnp.bfloat16, remat=True, remat_policy="save_mlp",
            scan_layers=True)
        batch, seq, steps = 16, 512, 10
    else:
        cfg = LlamaConfig.tiny(dtype=jnp.float32)
        batch, seq, steps = 4, 128, 3

    model = LlamaModel(cfg)
    ds_config = {
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-4, "weight_decay": 0.01,
                                 "moment_dtype": "bfloat16"}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
    }
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(batch, seq + 1))
    sample = {"input_ids": tokens[:1, :-1], "labels": tokens[:1, 1:]}
    engine = deepspeed_tpu.initialize(model=model, config=ds_config,
                                      sample_batch=sample)

    def make_batch():
        t = rng.integers(0, cfg.vocab_size, size=(batch, seq + 1))
        return {"input_ids": t[:, :-1], "labels": t[:, 1:]}

    # warmup / compile. NOTE: through the axon remote-execution tunnel,
    # jax.block_until_ready can return before execution; only a real host
    # transfer (float()) forces the chain. Timing = async loop + one final
    # transfer, minus the measured scalar-transfer latency.
    batches = [make_batch() for _ in range(4)]
    float(engine.train_batch(batches[0]))

    state = {}

    def window():
        # async-chained steps, one final transfer forcing the whole chain
        for i in range(steps):
            state["loss"] = engine.train_batch(batches[i % len(batches)])
        float(state["loss"])

    dt = time_best(window, 4 if on_tpu else 1)
    loss = state["loss"]
    n_chips = jax.device_count()
    tokens_per_sec = steps * batch * seq / dt
    tok_per_chip = tokens_per_sec / n_chips

    # model FLOPs ≈ 6 * params * tokens (fwd+bwd)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(engine.params))
    flops_per_sec = 6.0 * n_params * tokens_per_sec / n_chips
    # reference bar: 49 TFLOPs/GPU on V100 (125 TF peak) → MFU 0.392
    ref_mfu = 49.0 / 125.0
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak; CPU smoke placeholder
    our_mfu = flops_per_sec / peak
    vs_baseline = our_mfu / ref_mfu

    print(json.dumps({
        "metric": "llama770m_zero1_train_tokens_per_sec_per_chip",
        "value": round(tok_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "detail": {
            "params": int(n_params), "batch": batch, "seq": seq,
            "steps": steps, "wall_s": round(dt, 2),
            "model_tflops_per_chip": round(flops_per_sec / 1e12, 2),
            "mfu": round(our_mfu, 4), "backend": jax.default_backend(),
            "remat_policy": cfg.remat_policy,
            "moment_dtype": "bfloat16",
            "loss": float(loss),
        },
    }))


if __name__ == "__main__":
    if "--inference" in sys.argv and "--pld" in sys.argv:
        pld_main()
    elif "--inference" in sys.argv:
        bs = 1
        if "--batch" in sys.argv:
            i = sys.argv.index("--batch") + 1
            if i >= len(sys.argv) or not sys.argv[i].isdigit() \
                    or int(sys.argv[i]) < 1:
                sys.exit("--batch requires a positive integer, e.g. "
                         "bench.py --inference --batch 8")
            bs = int(sys.argv[i])
        panel = None
        if "--panel" in sys.argv:
            i = sys.argv.index("--panel") + 1
            if i >= len(sys.argv) or not sys.argv[i].isdigit() \
                    or int(sys.argv[i]) < 1:
                sys.exit("--panel requires a positive integer, e.g. "
                         "bench.py --inference --int8 --stream --panel 256")
            panel = int(sys.argv[i])
            streaming_run = (("--int8" in sys.argv
                              and "--stream" in sys.argv)
                             or any(f in sys.argv for f in
                                    ("--ab", "--kv8-ab", "--panel-ab")))
            if not streaming_run:
                # panel only reaches the config on the int8-STREAMING
                # path; silently ignoring it breaks the documented
                # calibration flow
                sys.exit("--panel applies to the int8 streaming path only; "
                         "add --int8 --stream (or --ab/--kv8-ab), e.g. "
                         "bench.py --inference --int8 --stream --panel 256")
        if "--panel-ab" in sys.argv:
            # panel ranking in the REAL decode program, same session
            for pn in (256, 512, 128):
                inference_main(int8=True, batch_size=bs, stream=True,
                               panel=pn)
        elif "--kv8-ab" in sys.argv:
            # same-session pair isolating the int8 KV cache: int8-stream
            # with bf16 cache, then with the int8 cache
            inference_main(int8=True, batch_size=bs, stream=True,
                           panel=panel)
            inference_main(int8=True, batch_size=bs, stream=True,
                           panel=panel, kv8=True)
        elif "--ab" in sys.argv:
            # official same-session pair (tunnel throttle makes cross-
            # session absolutes incomparable): bf16 then int8-streaming
            inference_main(int8=False, batch_size=bs)
            inference_main(int8=True, batch_size=bs, stream=True,
                           panel=panel)
        else:
            inference_main(int8="--int8" in sys.argv, batch_size=bs,
                           stream="--stream" in sys.argv, panel=panel,
                           kv8="--kv8" in sys.argv)
    elif "--serve" in sys.argv:
        def _intflag(name):
            if name not in sys.argv:
                return None
            i = sys.argv.index(name) + 1
            if i >= len(sys.argv) or not sys.argv[i].isdigit() \
                    or int(sys.argv[i]) < 1:
                sys.exit(f"{name} requires a positive integer, e.g. "
                         f"bench.py --serve {name} 8")
            return int(sys.argv[i])

        kernels = None
        if "--kernel" in sys.argv:
            i = sys.argv.index("--kernel") + 1
            arm = sys.argv[i] if i < len(sys.argv) else ""
            if arm not in ("reference", "pallas", "both"):
                sys.exit("--kernel requires reference|pallas|both, e.g. "
                         "bench.py --serve --kernel pallas")
            kernels = None if arm == "both" else [arm]
        if "--multichip" in sys.argv:
            serve_multichip_main()
        elif "--chaos" in sys.argv:
            serve_chaos_main(seed=_intflag("--seed"))
        elif "--overload" in sys.argv:
            serve_overload_main(seed=_intflag("--seed"))
        elif "--speculative" in sys.argv:
            serve_speculative_main(num_slots=_intflag("--slots"),
                                   trace_seed=_intflag("--trace-seed"),
                                   kernel=(kernels or [None])[0])
        elif "--disagg" in sys.argv:
            serve_disagg_main(num_slots=_intflag("--slots"),
                              trace_seed=_intflag("--trace-seed"),
                              kernel=(kernels or [None])[0])
        elif "--shared-prefix" in sys.argv:
            serve_prefix_main(num_slots=_intflag("--slots"),
                              trace_seed=_intflag("--trace-seed"),
                              kernel=(kernels or [None])[0],
                              host_cache="--host-cache" in sys.argv)
        else:
            serve_main(num_slots=_intflag("--slots"),
                       n_requests=_intflag("--requests"),
                       decode_chunk=_intflag("--chunk"),
                       kernels=kernels,
                       trace_seed=_intflag("--trace-seed"))
    elif "--multichip" in sys.argv:
        multichip_main(
            dryrun="--dryrun" in sys.argv,
            train_telemetry="--no-train-telemetry" not in sys.argv,
            fleet="--no-fleet" not in sys.argv)
    elif "--rlhf" in sys.argv:
        rlhf_main()
    elif "--longseq" in sys.argv:
        longseq_main()
    elif "--attention" in sys.argv:
        attention_main()
    elif "--moe" in sys.argv:
        moe_main()
    elif "--autotune-trial" in sys.argv:
        _autotune_trial(sys.argv[sys.argv.index("--autotune-trial") + 1])
    elif "--autotune" in sys.argv:
        autotune_main()
    elif "--aio" in sys.argv:
        aio_main()
    else:
        main()
