"""Elastic worker agent — membership-change supervision for TPU jobs.

Analogue of the reference's ``DSElasticAgent``
(deepspeed/elasticity/elastic_agent.py:28) and the torch-elastic restart
loop it rides on. The reference patches torch-elastic's worker env and lets
rendezvous restart ranks when membership changes; on TPU the natural design
is a host-side supervisor:

  resolve world → compute the compatible elastic config
  (``compute_elastic_config``, elasticity/elasticity.py) → export env →
  run the training process → on failure or membership change, re-resolve
  and restart; recovery state comes from the latest checkpoint (the
  reference's actual recovery story too — SURVEY.md §5).

``resolve_world`` defaults to local device count but accepts any callable
(TPU pod metadata, GKE downward API, a hostfile watcher), which is the
rendezvous-backend plug point.
"""

import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import (
    ElasticityIncompatibleWorldSize, compute_elastic_config,
)
from deepspeed_tpu.utils.logging import logger


def _default_resolve_world() -> int:
    try:
        import jax
        return jax.device_count()
    except Exception:
        return 1


class DSElasticAgent:
    """Supervise a training command under an elastic world-size contract.

    Parameters
    ----------
    cmd : the training command (list of argv strings).
    ds_config : DeepSpeed-style config dict with an ``elasticity`` section.
    resolve_world : callable returning the currently available chip count.
    max_restarts : restarts allowed before giving up (torch-elastic
        ``max_restarts`` analogue).
    env : extra env vars for the worker (reference ``ds_env``).
    """

    def __init__(self, cmd: List[str], ds_config: Dict,
                 resolve_world: Optional[Callable[[], int]] = None,
                 max_restarts: int = 3, env: Optional[Dict[str, str]] = None,
                 restart_backoff_s: float = 1.0):
        self.cmd = list(cmd)
        self.ds_config = ds_config
        self.resolve_world = resolve_world or _default_resolve_world
        self.max_restarts = max_restarts
        self.extra_env = dict(env or {})
        self.restart_backoff_s = restart_backoff_s
        self.restart_count = 0
        self._proc: Optional[subprocess.Popen] = None

    def _worker_env(self, world_size: int) -> Dict[str, str]:
        final_batch, valid_world_sizes, micro_batch = compute_elastic_config(
            self.ds_config, world_size=world_size, return_microbatch=True)
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update({
            "WORLD_SIZE": str(world_size),
            "DST_ELASTIC_WORLD_SIZE": str(world_size),
            "DST_ELASTIC_TRAIN_BATCH": str(final_batch),
            "DST_ELASTIC_MICRO_BATCH": str(micro_batch),
            "DST_ELASTIC_RESTART_COUNT": str(self.restart_count),
        })
        return env

    def _spawn(self, env: Dict[str, str]) -> subprocess.Popen:
        return subprocess.Popen(self.cmd, env=env)

    def stop(self):
        if self._proc is not None and self._proc.poll() is None:
            self._proc.send_signal(signal.SIGTERM)
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._proc.kill()

    def run(self) -> int:
        """Supervision loop; returns the final exit code."""
        while True:
            world = self.resolve_world()
            try:
                env = self._worker_env(world)
            except ElasticityIncompatibleWorldSize as e:
                logger.error(f"world size {world} incompatible: {e}")
                return 1
            logger.info(
                f"elastic agent: starting worker, world={world}, "
                f"restart={self.restart_count}/{self.max_restarts}")
            self._proc = self._spawn(env)
            rc = self._proc.wait()
            if rc == 0:
                return 0
            if self.restart_count >= self.max_restarts:
                logger.error(f"worker failed (rc={rc}); restart budget "
                             f"exhausted ({self.max_restarts})")
                return rc
            self.restart_count += 1
            logger.warning(
                f"worker failed (rc={rc}); re-resolving membership and "
                f"restarting from checkpoint (was world={world})")
            time.sleep(self.restart_backoff_s)


def main(argv: Optional[List[str]] = None) -> int:
    """``dst_elastic`` CLI (reference ``bin/ds_elastic``): print the elastic
    config and, with ``--world-size``, the resolved batch/micro-batch; with
    ``--run``, supervise a training command elastically."""
    import argparse
    import json

    parser = argparse.ArgumentParser(prog="dst_elastic")
    parser.add_argument("-c", "--config", required=True,
                        help="DeepSpeed-style config json")
    parser.add_argument("-w", "--world-size", type=int, default=0)
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--run", nargs=argparse.REMAINDER, default=None,
                        help="training command to supervise elastically")
    args = parser.parse_args(argv)

    with open(args.config) as f:
        ds_config = json.load(f)
    print(json.dumps(ds_config.get("elasticity", {}), indent=4,
                     sort_keys=True))

    if args.run:
        agent = DSElasticAgent(args.run, ds_config,
                               max_restarts=args.max_restarts)
        return agent.run()

    if args.world_size > 0:
        batch, valid, micro = compute_elastic_config(
            ds_config, world_size=args.world_size, return_microbatch=True)
        print(f"final_batch_size .... {batch}")
        print(f"valid_gpus .......... {valid}")
        print(f"micro_batch_size .... {micro}")
    else:
        batch, valid = compute_elastic_config(ds_config)
        print(f"final_batch_size .... {batch}")
        print(f"valid_gpus .......... {valid}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
