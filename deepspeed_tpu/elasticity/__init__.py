from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    get_valid_gpus,
)
from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
