"""Elastic training configuration math.

TPU-native analogue of reference ``deepspeed/elasticity/elasticity.py``
(v0.1 ``_get_compatible_gpus_v01`` :83, v0.2 ``_get_compatible_gpus_v02``
:126, ``compute_elastic_config`` :233): pre-compute the set of (total batch,
micro-batch, chip-count) combinations that keep the global batch size
constant as the world size changes, so a resumed job on a different pod
slice picks a valid configuration deterministically.

v0.2 adds the "model-parallel aware" variant: compatible chip counts must be
multiples of ``model_parallel_size * num_chips_per_host`` so TP groups never
straddle hosts.
"""

import math
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.1.0"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def get_candidate_batch_sizes(base_list: List[int], max_acc_step: int) -> List[int]:
    """All micro_batch * accumulation products up to max_acc_step."""
    candidates = set()
    for base in base_list:
        for acc in range(1, max_acc_step + 1):
            candidates.add(base * acc)
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """Chip counts w such that batch_size = micro * gas * w for some micro."""
    valid = set()
    for micro in micro_batches:
        if batch_size % micro != 0:
            continue
        slots = batch_size // micro  # gas * world
        for w in range(1, slots + 1):
            if slots % w == 0 and min_valid_gpus <= w <= max_valid_gpus:
                valid.add(w)
    return sorted(valid)


def _get_compatible_gpus_v01(micro_batches: List[int], max_batch: int,
                             min_gpus: int, max_gpus: int,
                             prefer_larger: bool = True
                             ) -> Tuple[int, List[int]]:
    """Pick the candidate batch with the widest chip-count coverage."""
    max_acc = max(1, max_batch // min(micro_batches))
    candidates = [b for b in get_candidate_batch_sizes(micro_batches, max_acc)
                  if b <= max_batch]
    best_batch, best_gpus = None, []
    order = sorted(candidates, reverse=prefer_larger)
    for batch in order:
        gpus = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        if len(gpus) > len(best_gpus):
            best_batch, best_gpus = batch, gpus
    if best_batch is None:
        raise ElasticityError(
            f"No valid batch size found for micro_batches={micro_batches} "
            f"max_batch={max_batch}")
    return best_batch, best_gpus


def _get_compatible_gpus_v02(micro_batches: List[int], max_batch: int,
                             min_gpus: int, max_gpus: int,
                             current_num_gpus: int,
                             model_parallel_size: int = 1,
                             num_gpus_per_node: int = 1,
                             prefer_larger: bool = True):
    """v0.2: chip counts must be multiples of mp_size*chips_per_host."""
    quantum = model_parallel_size * num_gpus_per_node
    if current_num_gpus % quantum != 0:
        raise ElasticityIncompatibleWorldSize(
            f"world size {current_num_gpus} not a multiple of "
            f"model_parallel_size*chips_per_host = {quantum}")
    batch, gpus = _get_compatible_gpus_v01(
        micro_batches, max_batch, min_gpus, max_gpus, prefer_larger)
    dp_gpus = [g for g in gpus if (g * quantum) <= max_gpus]
    final = [g * quantum for g in dp_gpus]
    return batch * quantum, final


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """reference compute_elastic_config (:233): resolve the elastic section
    into (final_batch_size, valid_gpus[, micro_batch])."""
    elastic = ds_config.get("elasticity", {})
    if not elastic.get("enabled", False):
        raise ElasticityConfigError("elasticity not enabled in config")
    micro_batches = elastic.get("micro_batch_sizes", [2, 4, 6])
    max_batch = elastic.get("max_train_batch_size", 2000)
    min_gpus = elastic.get("min_gpus", 1)
    max_gpus = elastic.get("max_gpus", 10000)
    prefer_larger = elastic.get("prefer_larger_batch", True)
    version = elastic.get("version", LATEST_ELASTICITY_VERSION)

    if float(version) >= 0.2:
        mp = elastic.get("model_parallel_size", 1)
        per_node = elastic.get("num_gpus_per_node", 1)
        final_batch, valid_gpus = _get_compatible_gpus_v02(
            micro_batches, max_batch, min_gpus, max_gpus,
            current_num_gpus=max(world_size, mp * per_node),
            model_parallel_size=mp, num_gpus_per_node=per_node,
            prefer_larger=prefer_larger)
    else:
        final_batch, valid_gpus = _get_compatible_gpus_v01(
            micro_batches, max_batch, min_gpus, max_gpus, prefer_larger)

    if world_size > 0 and world_size not in valid_gpus:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not in valid set {valid_gpus} for "
            f"batch {final_batch}")

    if not return_microbatch:
        return final_batch, valid_gpus
    micro = None
    if world_size > 0:
        for m in sorted(micro_batches, reverse=prefer_larger):
            if final_batch % (m * world_size) == 0:
                micro = m
                break
    return final_batch, valid_gpus, micro
