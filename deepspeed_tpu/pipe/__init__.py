"""Public pipeline-module surface (reference ``deepspeed/pipe``:
``PipelineModule`` / ``LayerSpec`` / ``TiedLayerSpec``,
runtime/pipe/module.py:85,29,76).

The reference's ``PipelineModule`` takes a flat list of layer specs,
partitions them over pipeline stages (uniform / parameter-count / regex
class-name match, module.py:353) and owns tied-weight groups. The TPU build
keeps that exact user surface; execution differs: a stage's layers run
sequentially inside ONE jitted program whose stage parallelism comes from
the ``pipe`` mesh axis (runtime/pipe/engine.py + spmd.py), so the module
here is the *structure* — specs, partitioning, parameter building, tied
groups — not a torch container.
"""

from deepspeed_tpu.pipe.module import (  # noqa: F401
    LayerSpec,
    PipelineModule,
    TiedLayerSpec,
)
