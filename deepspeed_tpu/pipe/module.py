"""PipelineModule / LayerSpec / TiedLayerSpec.

Reference contract (runtime/pipe/module.py):
- ``LayerSpec(cls, *args, **kwargs)`` defers construction so only the
  owning stage materializes a layer (module.py:29 — there it avoids
  allocating CUDA memory on other ranks; here it bounds host memory and
  lets each stage init only its params).
- ``TiedLayerSpec(name, cls, ...)`` declares layers sharing one weight
  group (module.py:76); the reference all-reduces tied grads across stages
  (module.py:406) — under SPMD the tie is the SAME pytree leaf referenced
  by both layers, so gradient summing falls out of autodiff.
- ``partition_method``: "uniform" (equal layer counts), "parameters"
  (balance trainable-parameter counts), or "type:REGEX" (balance layers
  whose class name matches the regex) — module.py:353-398.
"""

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.utils import partition_balanced, partition_uniform
from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Deferred layer: ``build()`` constructs the (flax) module."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self, log: bool = False):
        if log:
            logger.info(f"building {self.typename.__name__}")
        return self.typename(*self.module_args, **self.module_kwargs)

    @property
    def name(self) -> str:
        return getattr(self.typename, "__name__", str(self.typename))


class TiedLayerSpec(LayerSpec):
    """A layer sharing its parameters with every other TiedLayerSpec of the
    same ``key`` (reference module.py:76; e.g. embedding / lm-head tying
    across the first and last stage)."""

    def __init__(self, key: str, typename: Callable, *module_args,
                 forward_fn: Optional[Callable] = None, **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn


class PipelineModule:
    """Structure of a pipeline-parallel model: specs + stage partition +
    tied groups + per-stage parameter building.

    ``forward_fn(module, params, x)`` defaults to flax
    ``module.apply({"params": params}, x)``.
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False, base_seed: int = 1234,
                 probe_input=None):
        """``probe_input``: a sample input for the first layer, used to
        weigh layers by parameter count for ``partition_method=
        "parameters"`` (layer i+1 is probed with layer i's eval_shape
        output). Without it the probe falls back to a [1, 8] float input."""
        assert num_stages >= 1
        self.layer_specs = list(layers)
        for i, l in enumerate(self.layer_specs):
            assert isinstance(l, LayerSpec), \
                f"layer {i} is not a LayerSpec (got {type(l)})"
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self.base_seed = base_seed
        self.seed_layers = seed_layers
        self.probe_input = probe_input
        self._modules = [spec.build() for spec in self.layer_specs]
        self.parts = self._partition_layers()

    # --- partitioning (reference module.py:353-398) ------------------------
    def _layer_param_counts(self) -> List[float]:
        """Per-layer parameter counts via chained eval_shape: each layer is
        probed with the previous layer's abstract output, so embeddings
        (int inputs) and [B, S, D] blocks weigh correctly when
        ``probe_input`` is given."""
        x = jnp.zeros((1, 8), jnp.float32) if self.probe_input is None \
            else jnp.asarray(self.probe_input)
        weights: List[float] = []
        for i, mod in enumerate(self._modules):
            try:
                shapes, out = jax.eval_shape(
                    lambda r, x_: (mod.init(r, x_),
                                   mod.apply(mod.init(r, x_), x_)),
                    jax.random.PRNGKey(0), x)
                weights.append(float(sum(
                    int(np.prod(s.shape)) for s in
                    jax.tree_util.tree_leaves(shapes))))
                x = out
            except Exception as e:
                logger.warning(
                    f"PipelineModule: parameter probe failed for layer {i} "
                    f"({self.layer_specs[i].name}): {type(e).__name__}: {e} "
                    f"— weighing it as 1 (pass probe_input= for accurate "
                    f"'parameters' partitioning)")
                weights.append(1.0)
        return weights

    def _partition_layers(self) -> List[int]:
        n = len(self.layer_specs)
        method = self.partition_method.lower()
        if method == "uniform":
            return partition_uniform(n, self.num_stages)
        if method == "parameters":
            return partition_balanced(self._layer_param_counts(),
                                      self.num_stages)
        if method.startswith("type:"):
            pat = method.split(":", 1)[1]
            weights = [1.0 if re.search(pat, spec.name, re.IGNORECASE)
                       else 0.0 for spec in self.layer_specs]
            if sum(weights) == 0:
                raise ValueError(
                    f"partition_method {self.partition_method!r} matched no "
                    f"layers ({[s.name for s in self.layer_specs]})")
            return partition_balanced(weights, self.num_stages)
        raise NotImplementedError(
            f"partition_method {self.partition_method!r}")

    def stage_owner(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def stage_layers(self, stage_id: int) -> List[Any]:
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        return self._modules[lo:hi]

    # --- tied groups -------------------------------------------------------
    def tied_keys(self) -> List[str]:
        seen = []
        for spec in self.layer_specs:
            if isinstance(spec, TiedLayerSpec) and spec.key not in seen:
                seen.append(spec.key)
        return seen

    def tied_stages(self, key: str) -> List[int]:
        """Stages owning a layer of this tied group (reference
        tied_comms, module.py:406)."""
        return sorted({
            self.stage_owner(i) for i, s in enumerate(self.layer_specs)
            if isinstance(s, TiedLayerSpec) and s.key == key})

    # --- parameter building ------------------------------------------------
    def init_params(self, rng: jax.Array, sample_input,
                    stage_id: Optional[int] = None) -> Dict[str, Any]:
        """Init params for all layers (or one stage's slice). Tied groups
        materialize ONE param subtree under ``tied/<key>`` shared by every
        member layer; member slots hold the string marker ``"tied:<key>"``.
        """
        params: Dict[str, Any] = {}
        tied: Dict[str, Any] = {}
        x = jnp.asarray(sample_input)
        lo, hi = (0, len(self._modules)) if stage_id is None else \
            (self.parts[stage_id], self.parts[stage_id + 1])
        for i in range(lo, hi):
            spec, mod = self.layer_specs[i], self._modules[i]
            if self.seed_layers:
                rng = jax.random.PRNGKey(self.base_seed + i)
            rng, sub = jax.random.split(rng)
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied:
                    tied[spec.key] = mod.init(sub, x)["params"]
                params[f"layer_{i}"] = f"tied:{spec.key}"
            else:
                params[f"layer_{i}"] = mod.init(sub, x)["params"]
            x = self._apply_one(i, params, tied, x)
        if tied:
            params["tied"] = tied
        return params

    def _apply_one(self, i: int, params, tied, x):
        spec, mod = self.layer_specs[i], self._modules[i]
        p = params[f"layer_{i}"]
        if isinstance(p, str) and p.startswith("tied:"):
            p = tied[p.split(":", 1)[1]]
        if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
            return spec.forward_fn(mod, p, x)
        return mod.apply({"params": p}, x)

    def apply(self, params: Dict[str, Any], x,
              stage_id: Optional[int] = None):
        """Sequential forward over all layers (or one stage's slice) —
        correctness surface; pipelined execution is runtime/pipe/."""
        tied = params.get("tied", {})
        lo, hi = (0, len(self._modules)) if stage_id is None else \
            (self.parts[stage_id], self.parts[stage_id + 1])
        for i in range(lo, hi):
            x = self._apply_one(i, params, tied, x)
        return x
