"""Config plumbing shared by every feature sub-config.

TPU-native analogue of reference ``deepspeed/runtime/config_utils.py:16``
(``DeepSpeedConfigModel``): a pydantic base model with alias support and a
deprecated-field mechanism that transparently forwards old names to their
replacements with a warning.
"""

from typing import Any, Dict

from pydantic import BaseModel, ConfigDict

from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigModel(BaseModel):
    """Base for all feature sub-configs.

    Extra keys are rejected (strict parity with the reference's value checks),
    aliases are honored on input, and fields marked ``deprecated=True`` in
    ``json_schema_extra`` with a ``new_param`` entry are remapped.
    """

    model_config = ConfigDict(
        extra="forbid",
        populate_by_name=True,
        validate_assignment=True,
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    def __init__(self, strict: bool = False, **data: Any):
        if not strict:  # drop None values so field defaults apply
            data = {k: v for k, v in data.items() if v is not None}
        data = self._remap_deprecated(data)
        super().__init__(**data)

    @classmethod
    def _remap_deprecated(cls, data: Dict[str, Any]) -> Dict[str, Any]:
        for name, field in cls.model_fields.items():
            extra = field.json_schema_extra or {}
            if not isinstance(extra, dict) or not extra.get("deprecated"):
                continue
            keys = {name}
            if field.alias:
                keys.add(field.alias)
            present = keys & set(data.keys())
            if not present:
                continue
            new_param = extra.get("new_param")
            old_key = present.pop()
            if new_param:
                logger.warning(
                    f"Config parameter {old_key} is deprecated; use {new_param} instead"
                )
                if new_param not in data:
                    data[new_param] = data.pop(old_key)
                else:
                    data.pop(old_key)
            else:
                logger.warning(f"Config parameter {old_key} is deprecated and ignored")
        return data


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json.load hook rejecting duplicate keys (reference config_utils.py:134)."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counts = {}
        for k, _ in ordered_pairs:
            counts[k] = counts.get(k, 0) + 1
        dupes = [k for k, c in counts.items() if c > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {dupes}")
    return d
