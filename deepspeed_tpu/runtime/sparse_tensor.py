"""Sparse gradient tensors (reference ``runtime/sparse_tensor.py:13``).

Embedding gradients touch only the rows of the tokens in the batch; the
reference wraps them as (indices, values) pairs and all-gathers both sides
over the data-parallel group instead of all-reducing the dense [vocab, d]
array (engine.py:2312-2383 ``sparse_allreduce_bucket``). Here:

- ``SparseTensor`` — the (indices, values, dense_size) triple with
  ``to_dense`` (duplicate indices accumulate) and ``add``;
- ``from_dense_rows`` — build one from a dense grad + the touched row ids;
- ``sparse_all_reduce`` — the collective: all_gather indices and values
  over a mesh axis, return the merged SparseTensor whose ``to_dense``
  equals the dense all-reduce. Must run inside shard_map/pjit tracing
  (same contract as every verb in deepspeed_tpu.comm).
"""

from typing import Optional

import jax
import jax.numpy as jnp


class SparseTensor:
    def __init__(self, indices: jnp.ndarray, values: jnp.ndarray,
                 dense_size: int):
        assert indices.shape[0] == values.shape[0], \
            f"indices {indices.shape} / values {values.shape} mismatch"
        self.indices = indices
        self.values = values
        self.dense_size = int(dense_size)

    @staticmethod
    def from_dense_rows(dense: jnp.ndarray, row_ids: jnp.ndarray
                        ) -> "SparseTensor":
        """Rows of ``dense`` selected by ``row_ids`` (the batch's tokens)."""
        row_ids = row_ids.reshape(-1)
        return SparseTensor(row_ids, jnp.take(dense, row_ids, axis=0),
                            dense.shape[0])

    def to_dense(self) -> jnp.ndarray:
        """Scatter-add values back into the dense shape (duplicates sum —
        the reference's coalescing step)."""
        shape = (self.dense_size,) + tuple(self.values.shape[1:])
        return jnp.zeros(shape, self.values.dtype).at[self.indices].add(
            self.values)

    def add(self, other: "SparseTensor") -> "SparseTensor":
        assert self.dense_size == other.dense_size
        return SparseTensor(
            jnp.concatenate([self.indices, other.indices]),
            jnp.concatenate([self.values, other.values]), self.dense_size)

    def sparse_size(self) -> int:
        return self.indices.shape[0] * (
            1 + int(jnp.prod(jnp.asarray(self.values.shape[1:]))))

    def __repr__(self):
        return (f"SparseTensor(nnz_rows={self.indices.shape[0]}, "
                f"dense_size={self.dense_size})")


def sparse_all_reduce(st: SparseTensor, group: str = "data") -> SparseTensor:
    """All-gather (indices, values) over the mesh axis — the sparse
    equivalent of a grad all-reduce. Payload is O(nnz · world) instead of
    O(dense · world); ``to_dense`` of the result equals the dense sum."""
    indices = jax.lax.all_gather(st.indices, group, tiled=True)
    values = jax.lax.all_gather(st.values, group, tiled=True)
    return SparseTensor(indices, values, st.dense_size)


def should_use_sparse(dense_shape, nnz_rows: int,
                      world_size: int, threshold: float = 0.5) -> bool:
    """Bandwidth heuristic (reference engine chooses per-bucket): gathered
    sparse payload vs dense all-reduce bytes."""
    dense_elems = 1
    for d in dense_shape:
        dense_elems *= d
    row_elems = dense_elems // max(dense_shape[0], 1)
    sparse_elems = nnz_rows * (1 + row_elems) * world_size
    return sparse_elems < threshold * dense_elems
