"""Data loaders (reference ``deepspeed/runtime/dataloader.py``:
``DeepSpeedDataLoader`` :41, ``RepeatingLoader`` :17).

TPU-shaped: a loader yields dicts of numpy/jax arrays with the global batch
leading dim; the engine shards them onto the mesh (data/sequence axes). No
pinned-memory machinery — host→device transfer is one async device_put of
the already-assembled global batch.
"""

import math
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import numpy as np


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference :17).

    Each wrap-around advances the wrapped loader's epoch (``set_epoch``)
    so a shuffling loader reshuffles per epoch instead of replaying the
    same batch order forever.
    """

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self._epoch = 0

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self._epoch += 1
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(self._epoch)
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batched loader over an indexable dataset.

    dataset: sequence of per-sample dicts (or tuples) of arrays.
    Collation stacks along a new leading dim to the global batch size
    (micro_batch * dp world — the engine consumes global batches directly).
    """

    def __init__(self, dataset: Sequence, batch_size: int,
                 shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 data_sampler: Optional[Iterator[Sequence[int]]] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or self._default_collate
        self.data_sampler = data_sampler
        self._epoch = 0
        if drop_last:
            self.len = len(dataset) // batch_size
        else:
            self.len = math.ceil(len(dataset) / batch_size)

    @staticmethod
    def _default_collate(samples):
        first = samples[0]
        if isinstance(first, dict):
            return {k: np.stack([np.asarray(s[k]) for s in samples])
                    for k in first}
        if isinstance(first, (tuple, list)):
            return tuple(np.stack([np.asarray(s[i]) for s in samples])
                         for i in range(len(first)))
        return np.stack([np.asarray(s) for s in samples])

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self):
        return self.len

    def __iter__(self):
        if self.data_sampler is not None:
            for idx_batch in self.data_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])
            return
        order = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.default_rng(self.seed + self._epoch).shuffle(order)
        for b in range(self.len):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in idx])
