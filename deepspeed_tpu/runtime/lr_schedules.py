"""LR schedules (reference ``deepspeed/runtime/lr_schedules.py``).

Same four families — LRRangeTest (:258), OneCycle (:361), WarmupLR (:626),
WarmupDecayLR (:715) — expressed as pure step->lr callables (optax schedule
convention) so they can live inside the jitted train step.
"""

import math
from typing import Any, Callable, Dict

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

Schedule = Callable[[Any], Any]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    """Linearly/staircase-increasing LR probe (reference :258)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: int = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0, **_) -> Schedule:
    """Triangular cycle then decay (reference :361, momentum cycling omitted —
    optax handles momentum separately)."""
    second = cycle_second_step_size if cycle_second_step_size is not None \
        else cycle_first_step_size
    total = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        in_up = step < cycle_first_step_size
        up_frac = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down_frac = jnp.clip((step - cycle_first_step_size) / max(second, 1), 0.0, 1.0)
        cycle_lr = jnp.where(
            in_up,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up_frac,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_frac,
        )
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - total, 0.0) / decay_step_size
            decay = 1.0 / (1.0 + decay_lr_rate * decay_steps)
        else:
            decay = 1.0
        return jnp.where(step < total, cycle_lr, cycle_min_lr * decay)

    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_) -> Schedule:
    """Warm up then hold (reference :626)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip((step + 1) / warmup_num_steps, 1e-8, 1.0)
        if warmup_type == "log":
            # log warmup: gamma goes 0→1 as log(step) approaches log(warmup)
            gamma = jnp.clip(1.0 + jnp.log(frac) / math.log(max(warmup_num_steps, 2)), 0.0, 1.0)
        else:
            gamma = frac
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma

    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_) -> Schedule:
    """Warm up then linear decay to zero (reference :715)."""
    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip(
            (total_num_steps - step) / max(total_num_steps - warmup_num_steps, 1),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps, warm(step), warmup_max_lr * decay)

    return schedule


_FACTORIES: Dict[str, Callable[..., Schedule]] = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
}


def get_lr_schedule(name: str, params: Dict[str, Any]) -> Schedule:
    if name not in _FACTORIES:
        raise ValueError(f"Unknown lr schedule {name}; valid: {VALID_LR_SCHEDULES}")
    return _FACTORIES[name](**params)
