"""DeepSpeedEngine — the core training engine.

TPU-native analogue of ``deepspeed/runtime/engine.py:181``. The reference
wraps a torch module and owns distributed setup, precision, ZeRO, optimizer,
and checkpointing imperatively; here the engine owns a ``Mesh``, a sharded
parameter/optimizer pytree, and a set of jitted step functions:

- ``train_batch(batch)`` — the hot path: one jitted program covering all
  gradient-accumulation micro-steps (lax.scan) + optimizer update, with
  donated buffers. This is the analogue of forward+backward+step fused, and
  it is what benchmarks should call.
- ``forward/backward/step`` — API-parity path with the reference's
  ``loss = engine(batch); engine.backward(loss); engine.step()`` loop
  (engine.py:1663/:1804/:2000). ``forward`` computes loss *and* grads in one
  jitted call (reverse-mode AD is fused under XLA; splitting them would
  recompute), ``backward`` accumulates, ``step`` applies at the
  gradient-accumulation boundary (:1885 boundary logic).

ZeRO stages are sharding plans (runtime/zero/stages.py), not optimizer
subclasses. fp16 keeps the reference's dynamic loss scaling
(fp16/loss_scaler.py) as carried scaler state inside jit.
"""

import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
from deepspeed_tpu.utils.jax_compat import set_mesh
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu import comm as dist
from deepspeed_tpu.parallel.mesh import DATA_AXIS, make_mesh, mesh_axis_size
from deepspeed_tpu.parallel.partition import batch_spec, data_axes
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.fp16.loss_scaler import (
    grads_finite, make_dynamic_scaler_state, make_static_scaler_state,
    update_scaler,
)
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
from deepspeed_tpu.runtime.lr_schedules import get_lr_schedule
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.runtime.quantize import Quantizer
from deepspeed_tpu.runtime.zero.stages import (
    COMM_DTYPES, ZeroShardingPlan, constrain_gradients, opt_state_shardings,
    plan_zero_shardings,
)
from deepspeed_tpu.compression import (
    Compressor, CompressionScheduler, STEP_KEY, get_compression_config,
)
from deepspeed_tpu.observability import (
    CompileWatcher, MetricsRegistry, device_memory_section,
    make_train_tracer, pipeline_lane_spans, publish_train_stats,
    schedule_efficiency, train_health_stats,
)
from deepspeed_tpu.ops.optimizers import build_optimizer
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (
    BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER,
    SynchronizedWallClockTimer, ThroughputTimer, TRAIN_BATCH_TIMER,
)

TrainLossFn = Callable[[Any, Dict[str, jnp.ndarray], Any], jnp.ndarray]


def _default_lm_loss(module, fused: bool = False,
                     chunk_size: int = 256) -> TrainLossFn:
    """batch = {input_ids, labels[, positions]} → causal-LM cross entropy.

    With ``fused`` (config "fused_lm_loss") and a model exposing
    ``return_hidden`` (LlamaModel), uses the chunked loss
    (ops/fused_losses.chunked_lm_xent): the lm_head matmul + softmax stream
    over sequence chunks instead of materializing [B, S, V] fp32 logits —
    ~2 GB of activation memory at 770M/32k-vocab scale. Off by default:
    at sizes where full logits fit comfortably it costs a few % step time."""
    from deepspeed_tpu.models.llama import (
        LlamaModel, StreamedLlamaModel, loss_fn as lm_loss,
    )
    from deepspeed_tpu.ops.fused_losses import chunked_lm_xent

    if fused:
        mcfg = getattr(module, "cfg", None)
        # any module exposing return_hidden + lm_kernel qualifies (both
        # streamed twins); plain LlamaModel derives the kernel from params.
        # A biased or absent head cannot ride the bias-free chunked matmul.
        chunkable = isinstance(module, (LlamaModel, StreamedLlamaModel)) or (
            hasattr(module, "lm_kernel")
            and getattr(mcfg, "lm_head", True)
            and not getattr(mcfg, "lm_head_bias", False))
        if chunkable:
            tied = module.cfg.tie_embeddings

            def fn(params, batch, rngs=None):
                h = module.apply({"params": params}, batch["input_ids"],
                                 positions=batch.get("positions"), rngs=rngs,
                                 return_hidden=True)
                if hasattr(module, "lm_kernel"):
                    # host-resident weights: the head kernel must be
                    # fetched to device before the chunked matmul
                    kernel = module.lm_kernel(params)
                else:
                    kernel = (params["embed_tokens"]["embedding"].T if tied
                              else params["lm_head"]["kernel"])
                return chunked_lm_xent(h, kernel, batch["labels"],
                                       chunk_size=chunk_size)

            return fn
        why = ("its lm_head carries a bias the chunked matmul would drop"
               if getattr(mcfg, "lm_head_bias", False)
               else "it has no LM head" if not getattr(mcfg, "lm_head", True)
               else "it does not expose return_hidden/lm_kernel")
        logger.warning(
            "fused_lm_loss is enabled but %s cannot use the chunked loss "
            "(%s); falling back to the full-logits loss (the [B, S, V] "
            "fp32 logits WILL be materialized)", type(module).__name__, why)

    def fn(params, batch, rngs=None):
        logits = module.apply({"params": params}, batch["input_ids"],
                              positions=batch.get("positions"), rngs=rngs)
        return lm_loss(logits, batch["labels"])

    return fn


class DeepSpeedEngine:
    def __init__(self,
                 model=None,
                 config: Optional[Any] = None,
                 loss_fn: Optional[TrainLossFn] = None,
                 params: Optional[Any] = None,
                 mesh: Optional[Mesh] = None,
                 sharding_rules=None,
                 lr_scheduler=None,
                 sample_batch: Optional[Dict[str, Any]] = None,
                 dont_change_device: bool = False):
        self.module = model
        self.client_lr_scheduler = lr_scheduler
        # a user-supplied mesh may span a device subset; the batch triangle
        # must use ITS size, not jax.device_count()
        world = mesh.size if mesh is not None else None
        self._config = config if isinstance(config, DeepSpeedConfig) \
            else DeepSpeedConfig(config or {}, world_size=world)

        dist.init_distributed()
        dist.configure(self._config)

        mics = getattr(self._config.zero_config, "mics_shard_size", -1) or -1
        self.mesh = mesh if mesh is not None else make_mesh(
            self._config.mesh, mics_shard_size=max(mics, 0))
        groups.initialize_groups(self.mesh)
        # batch parallelism spans data × expert × mics (expert/MiCS
        # sub-groups are carved out of data and are still DP for the batch)
        self.dp_world_size = (mesh_axis_size(self.mesh, DATA_AXIS)
                              * mesh_axis_size(self.mesh, "expert")
                              * mesh_axis_size(self.mesh, "mics"))

        # precision -----------------------------------------------------------
        self.fp16_enabled = self._config.fp16.enabled
        self.bfloat16_enabled = self._config.bf16.enabled
        self.compute_dtype = {
            "float16": jnp.float16, "bfloat16": jnp.bfloat16, "float32": jnp.float32,
        }[self._config.precision_dtype]

        # loss / model fn -----------------------------------------------------
        model = self._maybe_enable_fsdp_gather(model, loss_fn)
        if loss_fn is not None:
            self.loss_fn = loss_fn
        elif model is not None and hasattr(model, "apply"):
            self.loss_fn = _default_lm_loss(
                model, fused=self._config.fused_lm_loss_enabled,
                chunk_size=self._config.fused_lm_loss_chunk)
        else:
            raise ValueError("Provide a flax module as `model` or an explicit `loss_fn`")

        # params --------------------------------------------------------------
        self._rng = jax.random.PRNGKey(self._config.seed)
        # ZeRO-Infinity parameter offload (reference partitioned_param_
        # swapper.py:36): params+states live on NVMe; the step is a host
        # interpreter over per-layer programs (zero/param_nvme.py), so the
        # fused-program machinery below is not built at all
        self._pnvme = None
        if self._config.zero_config.offload_param_device == "nvme":
            self._init_param_nvme(model, params, loss_fn)
            return
        if (self._config.zero_config.offload_param_device == "cpu"
                and self._config.zero_config.offload_param.grouped_stream):
            self._init_grouped_stream(model, params, loss_fn)
            return
        if params is None:
            assert sample_batch is not None and hasattr(model, "init"), \
                "Need sample_batch (+ flax model) to initialize parameters"
            params = self._sharded_init(model, sample_batch, sharding_rules)
        self.zero_plan: ZeroShardingPlan = plan_zero_shardings(
            params, self.mesh, self._config.zero_config, sharding_rules)

        def _adopt(p, s):
            # arrays from _sharded_init are already globally placed; only
            # host-provided params need (process-aware) placement
            if isinstance(p, jax.Array) and p.sharding.is_equivalent_to(
                    s, p.ndim):
                return p
            if jax.process_count() > 1:
                return self._place_global(p, s)
            return jax.device_put(p, s)

        self.params = jax.tree_util.tree_map(
            _adopt, params, self.zero_plan.param_shardings)
        if self.zero_plan.offload_param:
            self._setup_param_streaming(model, loss_fn)

        # compression (reference compression/compress.py) ----------------------
        self._compressor = None
        self.compression_scheduler = None
        _ccfg = get_compression_config(self._config.compression_config)
        if _ccfg.any_enabled:
            if _ccfg.layer_reduction.enabled:
                log_dist("layer_reduction is a structural edit: apply "
                         "init_compression(params, config) BEFORE engine "
                         "construction; the engine only applies QAT/pruning",
                         ranks=[0])
            self._compressor = Compressor(_ccfg, self.params)
            self.loss_fn = self._compressor.wrap_loss(self.loss_fn)
            self.compression_scheduler = CompressionScheduler(
                _ccfg, verbose=_ccfg.weight_quantization
                .shared_parameters.quantize_verbose)

        # misc runtime features (reference eigenvalue/PLD/MoQ wiring) ----------
        self.eigenvalue = None
        self._last_eigenvalues = None
        self._last_micro_batch = None
        if self._config.eigenvalue_enabled:
            ec = self._config.eigenvalue_config
            self.eigenvalue = Eigenvalue(
                verbose=ec.get("verbose", False),
                max_iter=ec.get("max_iter", 100),
                tol=ec.get("tol", 1e-2),
                stability=ec.get("stability", 1e-6),
                gas_boundary_resolution=ec.get("gas_boundary_resolution", 1),
                layer_name=ec.get("layer_name", "layer_"),
                layer_num=ec.get("layer_num", 0))
        self.progressive_layer_drop = None
        if self._config.pld_enabled:
            pc = self._config.pld_config
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=pc.get("theta", 0.5), gamma=pc.get("gamma", 0.001))
        self.quantizer = None
        if self._config.quantize_training_enabled:
            qc = self._config.quantize_training_config
            self.quantizer = Quantizer(
                q_start_bits=qc.get("quantize_bits", {}).get("start_bits", 16),
                q_target_bits=qc.get("quantize_bits", {}).get("target_bits", 8),
                q_period=qc.get("quantize_schedule", {}).get(
                    "quantize_period", 100),
                q_rounding=qc.get("quantize_algo", {}).get(
                    "rounding", "nearest"),
                q_type=qc.get("quantize_algo", {}).get(
                    "q_type", "symmetric"),
                q_groups=qc.get("quantize_groups", 1),
                q_verbose=qc.get("quantize_verbose", False),
                layer_name=qc.get(
                    "layer_name",
                    self.eigenvalue.layer_name if self.eigenvalue is not None
                    else "layer_"))

        # optimizer -----------------------------------------------------------
        self.optimizer, self._lr_schedule = self._configure_optimizer()
        # ZeRO-Offload/Infinity (reference stage3.py:1775-1835): optimizer
        # states live on NVMe (or in host RAM when offload_param pins params
        # to the host too); the step swaps them through per sub-group
        from deepspeed_tpu.runtime.zero.infinity import (
            OffloadedOptimizerStates, validate_offload_config,
        )

        validate_offload_config(self._config)
        self._nvme = None
        if (self._config.zero_config.offload_optimizer_device == "nvme"
                or self.zero_plan.offload_param):
            import weakref

            self._nvme = OffloadedOptimizerStates(self.params, self.zero_plan,
                                                  self.mesh, self._config)
            # AIO thread pools/fds must not outlive the engine (long-lived
            # processes build many engines — sweeps, test suites)
            self._nvme_finalizer = weakref.finalize(self, self._nvme.close)
            self.opt_state = ()     # states are on NVMe, not in the pytree
        else:
            self.opt_state = self._sharded_opt_init()

        self._init_runtime_state()

        self._build_step_functions()
        log_dist(
            f"DeepSpeedEngine initialized: zero_stage={self.zero_optimization_stage()}, "
            f"dtype={self._config.precision_dtype}, mesh={dict(self.mesh.shape)}, "
            f"micro_bs={self.train_micro_batch_size_per_gpu()}, "
            f"gas={self.gradient_accumulation_steps()}, "
            f"train_bs={self.train_batch_size()}", ranks=[0])
        if self._config.dump_state:
            # reference `dump_state` config: print the engine's param map
            # (utils/debug.py name maps → per-param shape/dtype lines)
            from deepspeed_tpu.utils.debug import debug_rank0, param_summary

            debug_rank0("engine parameter state:\n"
                        + param_summary(self.params, stats=False))

    def _init_param_nvme(self, model, params, loss_fn):
        """Alternate engine init for ``offload_param.device=nvme`` — builds
        the host-interpreter trainer (zero/param_nvme.py) instead of the
        fused jitted step. Unsupported feature combinations raise loudly in
        ``validate_param_nvme_config``."""
        from deepspeed_tpu.runtime.zero.param_nvme import (
            NVMeParamTrainer, validate_param_nvme_config,
        )

        self._init_interpreter_engine(
            model, params, loss_fn, trainer_cls=NVMeParamTrainer,
            validator=validate_param_nvme_config,
            tier="offload_param.device=nvme", label="param-NVMe")

    def _init_grouped_stream(self, model, params, loss_fn):
        """Alternate engine init for ``offload_param.grouped_stream`` — the
        grouped host-driven interpreter over pinned-host state
        (zero/grouped_stream.py). Same duck-typed surface as the param-NVMe
        trainer, so every ``self._pnvme`` touchpoint (train/eval/export/
        checkpoint) serves this tier too."""
        from deepspeed_tpu.runtime.zero.grouped_stream import (
            GroupedStreamTrainer, validate_grouped_stream_config,
        )

        self._init_interpreter_engine(
            model, params, loss_fn, trainer_cls=GroupedStreamTrainer,
            validator=validate_grouped_stream_config,
            tier="offload_param.grouped_stream", label="grouped-stream")

    def _init_interpreter_engine(self, model, params, loss_fn, *,
                                 trainer_cls, validator, tier, label):
        """Shared init for host-interpreter tiers (param-NVMe and
        grouped-stream): validate, build the trainer, wire the duck-typed
        ``self._pnvme`` surface + API-parity attributes."""
        validator(self._config, self.mesh)
        self._interpreter_tier = tier
        if loss_fn is not None:
            raise NotImplementedError(
                f"{tier} streams the built-in causal-LM loss layer-group "
                f"by layer-group; a custom loss_fn cannot be decomposed — "
                f"drop it or use plain offload_param.device=cpu")
        cfg = getattr(model, "cfg", None)
        init_rng, self._rng = jax.random.split(self._rng)
        self._pnvme = trainer_cls(cfg, self._config, self.mesh, init_rng)
        import weakref

        # finalizer BEFORE ingest: a mismatched params tree must not leak
        # the AIO thread pools / partially-written swap files
        self._pnvme_finalizer = weakref.finalize(self, self._pnvme.close)
        if params is not None:
            self._pnvme.ingest(params)
        # API-parity attributes the shared code paths read
        self.params = {}
        self.opt_state = ()
        self.zero_plan = None
        self._nvme = None
        self._compressor = None
        self.compression_scheduler = None
        self.eigenvalue = None
        self.progressive_layer_drop = None
        self.quantizer = None
        self._last_eigenvalues = None
        self._last_micro_batch = None
        self.optimizer, self._lr_schedule = self._configure_optimizer()
        self._init_runtime_state()
        log_dist(
            f"DeepSpeedEngine initialized ({label} interpreter): "
            f"zero_stage=3, dtype={self._config.precision_dtype}, "
            f"mesh={dict(self.mesh.shape)}, "
            f"micro_bs={self.train_micro_batch_size_per_gpu()}, "
            f"gas={self.gradient_accumulation_steps()}", ranks=[0])

    def _init_runtime_state(self):
        """Scaler + counters + timers + monitor + curriculum + flops-profiler
        state shared by the fused-program and param-NVMe init paths."""
        # loss scaler (fp16 only) ---------------------------------------------
        if self.fp16_enabled:
            if self._config.fp16.loss_scale > 0:
                self.scaler_state = make_static_scaler_state(self._config.fp16.loss_scale)
                self._dynamic_scale = False
            else:
                self.scaler_state = make_dynamic_scaler_state(
                    self._config.fp16.initial_scale_power, self._config.fp16.hysteresis)
                self._dynamic_scale = True
        else:
            self.scaler_state = make_static_scaler_state(1.0)
            self._dynamic_scale = False
        # scaler scalars live replicated on the mesh so checkpoint restore
        # returns them with a mesh-wide sharding compatible with jit args
        rep = NamedSharding(self.mesh, PartitionSpec())
        self.scaler_state = jax.tree_util.tree_map(
            lambda x: self._place_global(x, rep), self.scaler_state)

        # counters / timers / monitor -----------------------------------------
        self.micro_steps = 0
        self.global_steps = 0
        self.global_samples = 0
        self.skipped_steps = 0
        self._step_count = jnp.zeros((), jnp.int32)
        # dstrace metrics registry (docs/OBSERVABILITY.md): step/fwd/
        # bwd/optimizer timer histograms, train throughput, ZeRO
        # reduction bytes, and — via the collector — the comms logger's
        # wire totals, all behind one engine.metrics.snapshot(); the
        # monitor sinks drain it at steps_per_print boundaries
        self.metrics = MetricsRegistry()
        from deepspeed_tpu.comm.comm import comms_logger, \
            set_metrics_registry
        self.metrics.register_collector("comm",
                                        comms_logger.registry_section)
        # measured-collective sink (dstfleet): eager comm verbs record
        # real per-verb latency histograms + wire-byte counters here
        set_metrics_registry(self.metrics)
        # dstprof (docs/OBSERVABILITY.md): compile observability over
        # the train-step jits (hit once per program life — the thing
        # watched here is compile latency + cost analysis, which the
        # MFU gauge consumes) and per-device memory as a pull section
        self.compile_obs = CompileWatcher(self.metrics)
        self.metrics.register_collector("memory", device_memory_section)
        self.metrics.register_collector("train.efficiency",
                                        self._efficiency_section)
        self._train_step_flops: Optional[float] = None
        self._zero_bytes_cache = None
        self.timers = SynchronizedWallClockTimer(registry=self.metrics)
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self._config.steps_per_print,
            registry=self.metrics)
        self.monitor = self._configure_monitor()
        self.losses = 0.0
        self._cached_grads = None
        self._grad_acc = None
        self._loss_ok_acc = None
        self.training_dataloader = None
        self._train_iter = None
        self.wall_clock_breakdown = self._config.wall_clock_breakdown

        # legacy curriculum learning (reference engine.py:1702-1705 +
        # data_pipeline/curriculum_scheduler.py): difficulty = seqlen
        self.curriculum_scheduler = None
        _cl = self._config.curriculum_learning_legacy
        if isinstance(_cl, dict) and _cl.get("enabled", False):
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler \
                import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(_cl)

        # flops profiler (reference profiling/flops_profiler; engine hooks
        # at engine.py:1692,2070-2081): print a cost-analysis report once at
        # profile_step. Its output also lands in the registry as the
        # ``profiling`` pull section (empty until profile_step fires), so
        # `dst prof --train` and the Prometheus exporter see it instead
        # of only its own log lines.
        self._flops_profiler_cfg = self._config.flops_profiler
        self._flops_profiled = False
        self._flops_prof = None
        self.metrics.register_collector("profiling", self._profiling_section)

        # dsttrain (docs/OBSERVABILITY.md "Training"): in-graph gradient/
        # MoE health stats riding the compiled step + step-lane tracing.
        # Publication is lag-one (_publish_pending_train_stats): step N's
        # scalars are read while step N+1 runs, so telemetry never drains
        # the async dispatch queue the fused program relies on.
        self._telemetry_on = bool(
            getattr(self._config, "train_telemetry_enabled", True))
        self.train_tracer = None
        if self._telemetry_on and self._config.train_telemetry_trace:
            self.train_tracer = make_train_tracer(
                self._config.train_telemetry_trace_capacity)
        # dstlint: benign-race=constructor-time write; the engine has
        # not escaped to any other thread yet
        self._pending_train_stats = None
        # guards the pending-stats hand-off: a metrics-server scrape
        # thread flushes concurrently with the training thread's
        # _after_step — take-and-clear must be atomic or one step's
        # stats publish twice (double-counted histograms/counters)
        self._train_stats_lock = threading.Lock()
        self._pipe_lane_info = None       # (num_micro, num_stages) on 1F1B
        self._pipe_bubble = None          # static schedule bubble fraction
        self._jit_health = None
        self._metrics_server = None
        if getattr(self._config, "metrics_port", 0):
            self.start_metrics_server()
        # dstfleet (docs/OBSERVABILITY.md "Fleet"): file-based fleet
        # snapshot exchange — every rank publishes rank<k>.json at its
        # monitor drain; rank 0 merges + runs straggler detection, so
        # its scrape/monitor pipeline carries the fleet.* gauges
        self.fleet_monitor = None
        if getattr(self._config, "fleet_dir", None):
            from deepspeed_tpu.observability import FleetMonitor
            from deepspeed_tpu.observability.fleet import (
                resolve_fleet_rank,
            )

            rank = resolve_fleet_rank(
                int(getattr(self._config, "fleet_rank", -1)))
            self.fleet_monitor = FleetMonitor(
                self._config.fleet_dir, rank, metrics=self.metrics,
                tracer=self.train_tracer,
                straggler_threshold=float(getattr(
                    self._config, "fleet_straggler_threshold", 1.5)),
                straggler_windows=int(getattr(
                    self._config, "fleet_straggler_windows", 3)))

    def _ctx(self):
        """Scoped ambient-mesh context: PartitionSpec-based sharding
        constraints (MoE dispatch, sequence parallel) resolve against this
        engine's mesh during tracing, without leaking a global mesh."""
        return set_mesh(self.mesh)

    # --- config accessors (reference engine.py exposes the same names) -------
    def train_batch_size(self) -> int:
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self._config.gradient_accumulation_steps

    def zero_optimization_stage(self) -> int:
        return self._config.zero_config.stage

    def zero_optimization(self) -> bool:
        return self.zero_optimization_stage() > 0

    def gradient_clipping(self) -> float:
        return self._config.gradient_clipping

    def get_lr(self):
        return [float(self._lr_schedule(self.global_steps))] if self._lr_schedule \
            else [float(self._config.optimizer.params.get("lr", 0.0))
                  if self._config.optimizer else 0.0]

    # --- init helpers ---------------------------------------------------------
    def _offload_stream_shardings(self):
        """Device-side shardings the streamed forward fetches host params
        into (models/llama.StreamedLlamaModel): scanned-block leaves get
        their one-layer slice spec — the stacked spec minus the leading
        layers axis — everything else its full spec."""
        specs = self.zero_plan.param_specs
        mesh = self.mesh
        is_spec = lambda x: isinstance(x, PartitionSpec)

        def sliced(spec):
            if len(spec) and spec[0] is not None:
                logger.warning(
                    "offload_param: stacked block spec %s shards the layer "
                    "axis; the streamed slice re-shards on every fetch",
                    spec)
            return NamedSharding(mesh, PartitionSpec(*spec[1:]))

        out = {}
        for key, sub in specs.items():
            mapper = sliced if key == "blocks" else \
                (lambda s: NamedSharding(mesh, s))
            out[key] = jax.tree_util.tree_map(mapper, sub, is_leaf=is_spec)
        return out

    def _maybe_enable_fsdp_gather(self, model, user_loss_fn):
        """Stage-3 HBM-resident training over a real data axis: rebuild a
        scan-layers LlamaModel with ``fsdp_gather_scan`` so each scan
        iteration gathers ONE layer's sharded weights inside the loop
        (reference analogue: the per-submodule fetch/release of
        parameter_offload.py:201 — here expressed as an in-scan sharding
        constraint for XLA to schedule; see LlamaConfig.fsdp_gather_scan
        and tools/zero3_7b_projection.py for the 7B memory consequence)."""
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

        zc = self._config.zero_config
        self.fsdp_gather_scan_enabled = False
        if (zc.stage < 3 or zc.offload_param_device != "none"
                or self.mesh.shape.get("data", 1) <= 1
                or any(self.mesh.shape.get(ax, 1) > 1
                       for ax in ("tensor", "sequence", "expert"))
                or user_loss_fn is not None
                or not isinstance(model, LlamaModel)
                or not getattr(model.cfg, "scan_layers", False)
                or model.cfg.fsdp_gather_scan):
            return model
        import dataclasses

        self.fsdp_gather_scan_enabled = True
        return LlamaModel(dataclasses.replace(model.cfg,
                                              fsdp_gather_scan=True))

    def _setup_param_streaming(self, model, user_loss_fn):
        """ZeRO-3 parameter offload compute path (reference
        parameter_offload.py:201 fetch/release hooks work on ANY nn.Module
        → here the model-side ``streamed_twin`` protocol): a model exposing
        ``streamed_twin(stream_shardings)`` (scan-layers LlamaModel, the
        unified TransformerLM across all policy archs incl. MoE layers)
        streams one layer's weights at a time. Models without a twin (or a
        custom loss) RAISE — the whole-tree fallback re-materializes the
        full parameter set in HBM each step, forfeiting exactly the
        capacity the feature exists for — unless the user opts in with
        ``offload_param.fallback_whole_tree: true``."""
        twin_fn = getattr(model, "streamed_twin", None)
        streamed = (twin_fn(self._offload_stream_shardings())
                    if user_loss_fn is None and twin_fn is not None else None)
        if streamed is not None:
            self._streamed_module = streamed
            self.loss_fn = _default_lm_loss(
                streamed, fused=self._config.fused_lm_loss_enabled,
                chunk_size=self._config.fused_lm_loss_chunk)
            return
        why = ("a custom loss_fn owns the forward" if user_loss_fn is not None
               else f"{type(model).__name__} exposes no streamed_twin"
               + ("" if twin_fn is None else
                  " for this config (scan_layers=False?)"))
        if not self._config.zero_config.offload_param.fallback_whole_tree:
            raise NotImplementedError(
                f"offload_param.device=cpu cannot stream per-layer: {why}. "
                f"Streaming needs the scanned-model protocol "
                f"(model.streamed_twin + the engine's default LM loss). "
                f"Set zero_optimization.offload_param.fallback_whole_tree: "
                f"true to accept the degraded whole-tree fetch, where HBM "
                f"transiently holds the FULL parameter set during fwd/bwd "
                f"(params stay host-resident between steps only)")
        logger.warning(
            "offload_param: %s — parameters stream as ONE block per step "
            "(fallback_whole_tree), so HBM transiently holds the full "
            "parameter set during fwd/bwd", why)
        base = self.loss_fn
        dev_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self.zero_plan.param_specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec))

        def fetched_loss(params, batch, rngs=None):
            pd = jax.tree_util.tree_map(lambda p, sh: jax.device_put(p, sh),
                                        params, dev_shardings)
            return base(pd, batch, rngs=rngs)

        self.loss_fn = fetched_loss

    def _sharded_init(self, model, sample_batch, rules):
        """Initialize params already sharded (never materialize full replicas).

        Analogue of zero.Init (partition_parameters.py:603): the reference
        monkey-patches Module.__init__ to shard at construction; here we
        eval_shape the initializer, plan shardings from the abstract tree,
        then run the real init jitted with those out_shardings.
        """
        init_rng, self._rng = jax.random.split(self._rng)
        if jax.process_count() > 1:
            # a committed single-device key cannot feed a global-mesh jit;
            # a host array is treated as replicated (same seed everywhere)
            init_rng = np.asarray(init_rng)
        # numpy closure constant: safe to embed in a global-mesh program
        input_ids = np.asarray(sample_batch["input_ids"])[:1]

        def init_fn(rng):
            return model.init(rng, input_ids)["params"]

        abstract = jax.eval_shape(init_fn, init_rng)
        plan = plan_zero_shardings(abstract, self.mesh, self._config.zero_config, rules)
        out_sh = plan.param_shardings
        if plan.offload_param and \
                self.mesh.devices.flat[0].platform == "cpu":
            # the virtual CPU backend cannot annotate host placement on jit
            # OUTPUTS (works fine on TPU); initialize to device memory and
            # let the engine's eager device_put move the tree to host —
            # on CPU both are the same RAM
            out_sh = jax.tree_util.tree_map(
                lambda s: s.with_memory_kind("device"), out_sh,
                is_leaf=lambda x: isinstance(x, NamedSharding))
        with self._ctx():
            params = jax.jit(init_fn, out_shardings=out_sh)(init_rng)
        return params

    def _configure_optimizer(self):
        """reference _configure_optimizer (engine.py:1143): build base opt +
        lr schedule + global-norm clipping chain."""
        opt_cfg = self._config.optimizer
        sched_cfg = self._config.scheduler
        lr_schedule = None
        if sched_cfg is not None and sched_cfg.type:
            lr_schedule = get_lr_schedule(sched_cfg.type, sched_cfg.params)
        elif self.client_lr_scheduler is not None and callable(self.client_lr_scheduler):
            lr_schedule = self.client_lr_scheduler

        if opt_cfg is None:
            base = optax.adamw(lr_schedule if lr_schedule else 1e-3)
        else:
            base = build_optimizer(opt_cfg.type, opt_cfg.params, lr=lr_schedule)

        chain = []
        if self._config.grad_accum_dtype == "bfloat16":
            # grads arrive bf16 (data_types.grad_accum_dtype); upcast at
            # the head so global-norm clipping and Adam math run fp32 —
            # the converts fuse into the per-leaf update kernels, so the
            # fp32 tree is never materialized whole
            def _upcast(updates, state, params=None):
                del params
                return jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), updates), state

            chain.append(optax.GradientTransformation(
                lambda params: optax.EmptyState(), _upcast))
        if self._config.gradient_clipping > 0:
            chain.append(optax.clip_by_global_norm(self._config.gradient_clipping))
        chain.append(base)
        return optax.chain(*chain), lr_schedule

    def _sharded_opt_init(self):
        abstract = jax.eval_shape(self.optimizer.init, self.params)
        shardings = opt_state_shardings(abstract, self.params, self.zero_plan, self.mesh)
        self._opt_shardings = shardings
        with self._ctx():
            return jax.jit(self.optimizer.init, out_shardings=shardings)(self.params)

    def _configure_monitor(self):
        if not self._config.monitor_config_enabled:
            return None
        from deepspeed_tpu.monitor.monitor import MonitorMaster

        return MonitorMaster(self._config)

    # --- jitted step functions ------------------------------------------------
    def _build_step_functions(self):
        mesh = self.mesh
        plan = self.zero_plan
        gas = self.gradient_accumulation_steps()
        loss_fn = self.loss_fn
        optimizer = self.optimizer
        fp16 = self.fp16_enabled
        dynamic = self._dynamic_scale
        cfg16 = self._config.fp16
        numerics = self._config.numerics_check_enabled
        grad_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), plan.grad_specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec))
        self._grad_shardings = grad_shardings
        bspec = batch_spec(mesh)
        self._batch_sharding = NamedSharding(mesh, bspec)

        # reference engine.py:776-788 reduction knobs. The boundary cast +
        # constraint live in zero/stages.constrain_gradients — the shared
        # seam the dstlint SPMD pass traces, so the comms the linter
        # budgets are the comms this program emits. Scope note: XLA may
        # still pick its own internal accumulation dtype for the
        # collective it synthesizes.
        accum_dtype = ({"bfloat16": jnp.bfloat16, "float32": None}
                       [self._config.grad_accum_dtype]
                       if self._config.grad_accum_dtype else None)
        comm_dtype = None
        if self._config.communication_data_type:
            key = self._config.communication_data_type.lower()
            if key not in COMM_DTYPES:
                raise ValueError(
                    f"communication_data_type={key!r}: supported values "
                    f"are {sorted(COMM_DTYPES)}")
            comm_dtype = COMM_DTYPES[key]
        predivide = float(self._config.gradient_predivide_factor or 1.0)

        def constrain_grads(grads):
            return constrain_gradients(grads, grad_shardings, comm_dtype,
                                       predivide)

        telemetry = self._telemetry_on
        loss_aux = self._config.train_telemetry_loss_aux

        def grad_step(params, batch, scale):
            if loss_aux:
                # train_telemetry.loss_aux: the loss_fn contract becomes
                # (loss, {name: scalar}) — the aux dict rides the stats
                # pytree out of the compiled step and publishes as
                # train.aux.<name> gauges (the MoE gate-telemetry channel)
                def scaled_loss(p):
                    loss, aux = loss_fn(p, batch)
                    return loss * scale, aux

                (loss, aux), grads = jax.value_and_grad(
                    scaled_loss, has_aux=True)(params)
            else:
                def scaled_loss(p):
                    loss = loss_fn(p, batch)
                    return loss * scale

                loss, grads = jax.value_and_grad(scaled_loss)(params)
                aux = {}
            grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
            grads = constrain_grads(grads)
            if accum_dtype is not None:
                # data_types.grad_accum_dtype: store the materialized grad
                # tree at the accumulation dtype (the backward computed in
                # the bf16 compute dtype; fp32 storage only re-encodes) —
                # at 770M this is 1.55 GB of HBM back before the update.
                # AFTER constrain_grads: the sharding-constraint boundary
                # is where XLA places the cross-replica reduction, and the
                # reduction dtype is communication_data_type's knob, not
                # this one (reference keeps grad_accum_dtype storage-only)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(accum_dtype), grads)
            return loss / scale, grads, aux

        def apply_update(params, opt_state, grads, scaler_state,
                         loss_ok=jnp.asarray(True)):
            grads_ok = (grads_finite(grads) if (fp16 or numerics)
                        else jnp.asarray(True))
            # loss_ok gates the update but NOT the loss scaler below: a
            # finite-grad NaN loss is a numerics bug, not a scale overflow —
            # halving the scale can't fix it and would grind to min_scale
            finite = jnp.logical_and(grads_ok, loss_ok)

            def do_step(operand):
                params, opt_state, grads = operand
                if plan.offload_optimizer:
                    # host-offloaded optimizer states (reference
                    # ZeRO-Offload, zero/stage_1_and_2.py:1037): explicit
                    # in-graph host→HBM transfers around the update — XLA
                    # schedules the reads to overlap the tail of backward,
                    # and m/v never occupy HBM outside the update window
                    opt_state = jax.tree_util.tree_map(
                        lambda x, sh: jax.device_put(
                            x, sh.with_memory_kind("device"))
                        if isinstance(sh, NamedSharding) else x,
                        opt_state, self._opt_shardings)
                updates, new_opt = optimizer.update(grads, opt_state, params)
                if plan.offload_optimizer:
                    new_opt = jax.tree_util.tree_map(
                        lambda x, sh: jax.device_put(x, sh)
                        if isinstance(sh, NamedSharding) else x,
                        new_opt, self._opt_shardings)
                return optax.apply_updates(params, updates), new_opt

            def skip_step(operand):
                params, opt_state, _ = operand
                return params, opt_state

            new_params, new_opt = jax.lax.cond(
                finite, do_step, skip_step, (params, opt_state, grads))
            new_scaler = update_scaler(
                scaler_state, grads_ok, dynamic,
                scale_window=cfg16.loss_scale_window,
                min_scale=cfg16.min_loss_scale,
                hysteresis=cfg16.hysteresis) if fp16 else scaler_state
            return new_params, new_opt, new_scaler, finite

        def accumulate_grads(params, scale, batch):
            """All GAS micro-batches → (mean loss, mean grads, mean aux);
            shared by the fused and NVMe step programs so their
            trajectories cannot desynchronize."""
            if gas == 1:
                # no accumulator buffer needed — one fused fwd+bwd
                mb = jax.tree_util.tree_map(lambda x: x[0], batch)
                return grad_step(params, mb, scale)

            def micro(carry, mb):
                acc, loss_sum = carry
                loss, grads, aux = grad_step(params, mb, scale)
                # the scan CARRY accumulates in fp32 even when
                # grad_accum_dtype=bf16: each micro-grad arrives
                # bf16-stored (grad_step's cast — the per-micro
                # materialization stays cheap) but summing in bf16 loses
                # one ulp per add, an error that GROWS with gas; fp32
                # carry + one final cast bounds it at a single rounding
                # (regression-pinned in tests/unit/test_engine.py)
                acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), acc, grads)
                return (acc, loss_sum + loss), aux

            zero_grads = jax.tree_util.tree_map(
                lambda p, s: jax.lax.with_sharding_constraint(
                    jnp.zeros(p.shape, jnp.float32), s),
                params, grad_shardings)
            (acc, loss_sum), auxs = jax.lax.scan(micro, (zero_grads, 0.0),
                                                 batch)
            # the STORED tree keeps the configured accumulation dtype
            # (grad_accum_dtype is a storage knob — the NVMe/grouped
            # tiers bank this tree host-side)
            grads = jax.tree_util.tree_map(
                lambda g: (g / gas).astype(accum_dtype)
                if accum_dtype is not None else g / gas, acc)
            aux = jax.tree_util.tree_map(
                lambda a: jnp.mean(a.astype(jnp.float32), axis=0), auxs)
            return loss_sum / gas, grads, aux

        def train_batch_fn(params, opt_state, scaler_state, batch):
            """(gas, micro_global, ...) batch → scan accumulate → update.
            The trailing ``stats`` output is the dsttrain health pytree
            (a few fp32 scalars off the accumulated grads — comms-free,
            pinned by the SPMD budget gate on the zero-step seam)."""
            loss, grads, aux = accumulate_grads(params, scaler_state.scale,
                                                batch)
            stats = train_health_stats(grads, aux=aux) if telemetry else {}
            # the guard checks the loss too (a finite-grad NaN loss is
            # possible with masked losses); it feeds the skip gate, so a
            # tripped check really does leave params/opt_state untouched
            loss_ok = (jnp.isfinite(loss) if numerics else jnp.asarray(True))
            new_params, new_opt, new_scaler, finite = apply_update(
                params, opt_state, grads, scaler_state, loss_ok)
            if telemetry and fp16:
                # the post-update scale rides the stats pytree as its own
                # output: the live scaler_state is DONATED to the next
                # step, so the lag-one publisher cannot read it later
                stats = dict(stats, loss_scale=new_scaler.scale)
            return new_params, new_opt, new_scaler, loss, finite, stats

        def grads_batch_fn(params, scaler_state, batch):
            """NVMe path: the fused program minus the update — loss, grads,
            global norm, finiteness and the health stats, all in one
            compiled program."""
            loss, grads, aux = accumulate_grads(params, scaler_state.scale,
                                                batch)
            stats = train_health_stats(grads, aux=aux) if telemetry else {}
            gnorm = optax.global_norm(jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads))
            grads_ok = (grads_finite(grads) if (fp16 or numerics)
                        else jnp.asarray(True))
            loss_ok = (jnp.isfinite(loss) if numerics else jnp.asarray(True))
            return loss, grads, gnorm, grads_ok, loss_ok, stats

        with set_mesh(mesh):
            self._jit_loss = jax.jit(lambda p, b: loss_fn(p, b))
            self._jit_grad = jax.jit(grad_step)
            ts_out_sh = None
            if ((plan.offload_param or plan.offload_optimizer)
                    and mesh.devices.flat[0].platform != "cpu"):
                # offloaded params/states come back out of the step still
                # host-resident: the TPU AOT path refuses a program whose
                # entry outputs were moved to host without a host-memory
                # output layout ("layout for this output is not set to
                # host memory") — declare them. (The virtual CPU backend
                # cannot annotate host jit outputs; there host and device
                # memory are the same RAM, so nothing is lost.)
                ts_out_sh = (self.zero_plan.param_shardings,
                             self._opt_shardings
                             if plan.offload_optimizer and self._nvme is None
                             else None,
                             None, None, None, None)
            self._jit_apply = jax.jit(
                apply_update, donate_argnums=(0, 1, 2),
                out_shardings=(ts_out_sh[0], ts_out_sh[1], None, None)
                if ts_out_sh is not None else None)
            if telemetry:
                # fwd/backward/step API path: stats off the accumulated
                # grad tree at the GAS boundary (the fused path computes
                # them inside train_batch_fn)
                self._jit_health = jax.jit(
                    lambda g: train_health_stats(g))
            self._jit_train_batch = self.compile_obs.wrap(
                "train_step", "train_batch",
                jax.jit(train_batch_fn, donate_argnums=(0, 1, 2),
                        out_shardings=ts_out_sh))
            self._jit_accum = jax.jit(
                lambda acc, g: jax.tree_util.tree_map(jnp.add, acc, g),
                donate_argnums=(0,))
            if self._nvme is not None:
                grads_out_sh = None
                zc_op = self._config.zero_config.offload_param
                if plan.offload_param and zc_op.grads_to_host and \
                        mesh.devices.flat[0].platform != "cpu":
                    # param offload at capacity scale: the full grad tree
                    # must not sit in HBM through the sub-group update loop
                    # — land it in pinned host memory as backward produces
                    # it; the update fetches one group's grads at a time.
                    # (CPU backend cannot annotate host jit outputs; there
                    # device memory IS host RAM, so nothing is lost.)
                    ghost = jax.tree_util.tree_map(
                        lambda s: NamedSharding(mesh, s,
                                                memory_kind="pinned_host"),
                        plan.grad_specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))
                    grads_out_sh = (None, ghost, None, None, None, None)
                self._jit_grads_batch = self.compile_obs.wrap(
                    "train_step", "grads_batch",
                    jax.jit(grads_batch_fn, out_shardings=grads_out_sh))
                self._jit_gnorm_finite = jax.jit(
                    lambda g: (optax.global_norm(jax.tree_util.tree_map(
                        lambda x: x.astype(jnp.float32), g)),
                               grads_finite(g) if (fp16 or numerics)
                               else jnp.asarray(True)))

    # --- data placement -------------------------------------------------------
    def _place_global(self, x, sharding: NamedSharding):
        """Place a host array onto the (possibly multi-process) mesh. In a
        multi-controller run ``jax.device_put`` cannot address other
        processes' devices; every process holds the same global batch (the
        dataloader is seed-deterministic) and materializes only its
        addressable shards via ``make_array_from_callback`` — the reference
        feeds each rank its slice of the global batch the same way
        (engine.py deepspeed_io + DistributedSampler)."""
        if jax.process_count() > 1:
            xnp = np.asarray(x)
            return jax.make_array_from_callback(
                xnp.shape, sharding, lambda idx: xnp[idx])
        return jax.device_put(jnp.asarray(x), sharding)

    def _shard_batch(self, batch: Dict[str, Any], leading_gas: bool = False):
        seq_size = mesh_axis_size(self.mesh, "sequence")

        def put(x):
            x = jnp.asarray(x) if not isinstance(x, np.ndarray) else x
            if x.ndim == 0:
                return self._place_global(
                    x, NamedSharding(self.mesh, PartitionSpec()))
            axes = [None] * x.ndim
            b_axis = 1 if leading_gas else 0
            axes[b_axis] = data_axes(self.mesh)
            # context parallelism: tokens shard over the sequence axis too
            s_axis = b_axis + 1
            if seq_size > 1 and x.ndim > s_axis and x.shape[s_axis] % seq_size == 0:
                axes[s_axis] = "sequence"
            return self._place_global(
                x, NamedSharding(self.mesh, PartitionSpec(*axes)))

        return {k: put(v) for k, v in batch.items()}

    # --- data pipeline (reference deepspeed_io, engine.py:1571) ---------------
    def deepspeed_io(self, dataset, batch_size: Optional[int] = None,
                     route: str = "train", data_sampler=None,
                     collate_fn=None, difficulties=None,
                     num_local_io_workers=None, pin_memory: bool = False):
        """Build a :class:`DeepSpeedDataLoader` over ``dataset`` sized to the
        engine's global train batch. With data-efficiency v2 sampling enabled
        (``data_efficiency.data_sampling``), wraps a curriculum-aware
        :class:`DeepSpeedDataSampler` — per-sample ``difficulties`` come from
        the argument or the configured metric's ``analysis_path`` (a
        DataAnalyzer output dir). The train-route loader is attached as
        ``engine.training_dataloader`` and feeds ``train_batch()`` when no
        batch is passed."""
        from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

        batch_size = batch_size or self.train_batch_size()
        if len(dataset) < batch_size:
            raise ValueError(
                f"dataset has {len(dataset)} samples but the global train "
                f"batch needs {batch_size} (micro*gas*dp) — not one full "
                f"batch (drop_last)")
        de = self._config.data_efficiency_config or {}
        # both gates, like the reference: the top-level data_efficiency
        # switch turns the whole feature off regardless of nested flags
        ds_cfg = de.get("data_sampling", {}) if de.get("enabled", False) \
            else {}
        if data_sampler is None and ds_cfg.get("enabled", False):
            from deepspeed_tpu.runtime.data_pipeline import (
                CurriculumScheduler, DeepSpeedDataSampler,
            )

            curriculum, metric_cfg, metric_name = None, None, None
            cl = ds_cfg.get("curriculum_learning", {})
            if cl.get("enabled", False):
                metrics = cl.get("curriculum_metrics", {})
                if metrics:
                    metric_name, metric_cfg = sorted(metrics.items())[0]
                    if len(metrics) > 1:
                        logger.warning(
                            "data_sampling: %d curriculum metrics "
                            "configured but only one is supported — using "
                            "%r, ignoring %s", len(metrics), metric_name,
                            sorted(m for m in metrics if m != metric_name))
                    curriculum = CurriculumScheduler(metric_cfg)
            if difficulties is not None:
                data_sampler = DeepSpeedDataSampler(
                    difficulties, batch_size, curriculum=curriculum,
                    seed=self._config.seed)
            elif metric_cfg is not None and metric_cfg.get("analysis_path"):
                data_sampler = DeepSpeedDataSampler.from_analysis(
                    metric_cfg["analysis_path"], metric_name, batch_size,
                    curriculum=curriculum, seed=self._config.seed)
            else:
                raise ValueError(
                    "data_efficiency.data_sampling is enabled but no "
                    "per-sample difficulties are available — pass "
                    "deepspeed_io(..., difficulties=...) or set "
                    "curriculum_metrics.<name>.analysis_path to a "
                    "DataAnalyzer output directory")
        loader = DeepSpeedDataLoader(
            dataset, batch_size=batch_size,
            shuffle=(route == "train" and data_sampler is None),
            seed=self._config.seed, collate_fn=collate_fn,
            data_sampler=data_sampler)
        if route == "train":
            self.training_dataloader = loader
            self._train_iter = None
        return loader

    def next_batch(self):
        """Next global batch from the attached training dataloader
        (repeating across epochs)."""
        if self.training_dataloader is None:
            raise ValueError(
                "train_batch() without a batch needs a dataloader: pass "
                "initialize(training_data=...) or call "
                "engine.deepspeed_io(dataset) first")
        if self._train_iter is None:
            from deepspeed_tpu.runtime.dataloader import RepeatingLoader

            self._train_iter = iter(RepeatingLoader(self.training_dataloader))
        return next(self._train_iter)

    # --- public API -----------------------------------------------------------
    def train_batch(self, batch: Optional[Dict[str, Any]] = None):
        """Run one full global step (all GAS micro-batches + update) as a
        single jitted program. Batch arrays: leading dim is the global train
        batch (micro*gas*dp) or already (gas, micro*dp, ...). With no batch,
        pulls the next one from ``training_dataloader`` (reference
        ``train_batch(data_iter)``, pipe/engine.py:286)."""
        t_step0 = time.monotonic()
        if batch is None:
            batch = self.next_batch()
        gas = self.gradient_accumulation_steps()
        micro_global = self.train_micro_batch_size_per_gpu() * self.dp_world_size
        batch = self._apply_curriculum(batch)

        def to_gas_layout(x):
            x = np.asarray(x) if not isinstance(x, jax.Array) else x
            if x.ndim >= 2 and x.shape[0] == gas and x.shape[1] == micro_global:
                return x
            assert x.shape[0] == gas * micro_global, (
                f"batch leading dim {x.shape[0]} != train_batch_size "
                f"{gas * micro_global}")
            return x.reshape((gas, micro_global) + x.shape[1:])

        batch = {k: to_gas_layout(v) for k, v in batch.items()}
        batch = self._shard_batch(batch, leading_gas=True)
        if self._compressor is not None:
            batch[STEP_KEY] = self._place_global(
                jnp.full((gas,), self.global_steps, jnp.int32),
                NamedSharding(self.mesh, PartitionSpec()))

        t_data1 = time.monotonic()
        if self.wall_clock_breakdown:
            self.timers(TRAIN_BATCH_TIMER).start()
        self.tput_timer.start()
        self._maybe_profile_flops(batch)
        t_prog0 = time.monotonic()
        stats = None
        if self._pnvme is not None:
            # param-NVMe interpreter (zero/param_nvme.py): LR from applied-
            # update count, like the optimizer-NVMe path (_nvme_apply)
            lr = (float(self._lr_schedule(self._pnvme.count))
                  if self._lr_schedule else None)
            with self._ctx():
                loss, finite = self._pnvme.train_batch(batch, lr=lr)
        elif self._nvme is not None:
            loss, finite, stats = self._train_batch_nvme(batch)
        else:
            with self._ctx():
                (self.params, self.opt_state, self.scaler_state, loss,
                 finite, stats) = self._jit_train_batch(
                    self.params, self.opt_state, self.scaler_state, batch)
        t_prog1 = time.monotonic()
        if self.eigenvalue is not None or self.quantizer is not None:
            mb = None
            if self.eigenvalue is not None:  # only the eigenvalue path reads it
                mb = {k: jax.tree_util.tree_map(lambda x: x[0], v)
                      for k, v in batch.items() if k != STEP_KEY}
            self._misc_runtime_step(mb, finite)
        self._numerics_raise_if_tripped(finite, timer=TRAIN_BATCH_TIMER)
        self._after_step(finite, loss=loss, stats=stats)
        self.micro_steps += gas
        self._trace_step_lanes(t_step0, t_data1, t_prog0, t_prog1)
        if self.wall_clock_breakdown:
            self.timers(TRAIN_BATCH_TIMER).stop(synchronize=True)
        return loss

    def _clip_scale(self, gnorm: float) -> float:
        clip = self._config.gradient_clipping
        if clip and clip > 0:
            return min(1.0, clip / (gnorm + 1e-6))
        return 1.0

    def _nvme_apply(self, grads, gnorm, grads_ok, loss_ok):
        """Shared NVMe update epilogue: host-gated sub-group swap step +
        loss-scaler update (the in-graph lax.cond skip of the fused path
        becomes a host branch — the step already syncs on disk I/O)."""
        finite = jnp.logical_and(grads_ok, loss_ok)
        if bool(finite):
            # LR from the count of APPLIED updates (the NVMe analogue of
            # optax's internal count, which the fused path's lax.cond skip
            # leaves unincremented on overflow) — NOT global_steps, which
            # advances on skipped steps too
            lr = (float(self._lr_schedule(self._nvme.count))
                  if self._lr_schedule else None)
            t0 = time.monotonic()
            self.params = self._nvme.step(
                self.params, grads, self._clip_scale(float(gnorm)), lr=lr)
            # the swapped sub-group update is a REAL host boundary (the
            # fused path's in-graph update has none) — an OPTIM span/
            # histogram of its own
            if self._telemetry_on:
                t1 = time.monotonic()
                self.metrics.observe("train.phase.optim_s", t1 - t0)
                if self.train_tracer is not None:
                    self.train_tracer.span("OPTIM", t0, t1, cat="train",
                                           tid=0,
                                           step=self.global_steps + 1)
        if self.fp16_enabled:
            cfg16 = self._config.fp16
            self.scaler_state = update_scaler(
                self.scaler_state, grads_ok, self._dynamic_scale,
                scale_window=cfg16.loss_scale_window,
                min_scale=cfg16.min_loss_scale,
                hysteresis=cfg16.hysteresis)
        return finite

    def _train_batch_nvme(self, batch):
        """ZeRO-Infinity train step: one jitted grads program, then the
        pipelined per-sub-group swapped update (reference stage3.py:1775)."""
        with self._ctx():
            loss, grads, gnorm, grads_ok, loss_ok, stats = \
                self._jit_grads_batch(self.params, self.scaler_state, batch)
            finite = self._nvme_apply(grads, gnorm, grads_ok, loss_ok)
        return loss, finite, stats

    def __call__(self, batch: Dict[str, Any]):
        return self.forward(batch)

    def forward(self, batch: Dict[str, Any]):
        """Compute loss (and grads — fused reverse AD) for one micro-batch."""
        if self._pnvme is not None:
            raise NotImplementedError(
                f"{self._interpreter_tier} supports only train_batch() — "
                "the forward/backward/step split would re-stream every "
                "layer group per phase")
        if self.wall_clock_breakdown:
            self.timers(FORWARD_GLOBAL_TIMER).start()
        if self._compressor is not None:
            batch = {**batch, STEP_KEY: jnp.asarray(self.global_steps, jnp.int32)}
        batch = self._shard_batch(batch)
        with self._ctx():
            loss, grads, _aux = self._jit_grad(self.params, batch,
                                               self.scaler_state.scale)
        self._cached_grads = grads
        if self._config.numerics_check_enabled:
            # device-side loss-finiteness accumulator across micro-steps, so
            # step() can gate the update like the fused path (no host sync)
            ok = jnp.isfinite(loss)
            self._loss_ok_acc = ok if self._loss_ok_acc is None \
                else jnp.logical_and(self._loss_ok_acc, ok)
        # eigenvalue/MoQ at the next step() boundary need a batch
        self._last_micro_batch = {k: v for k, v in batch.items()
                                  if k != STEP_KEY}
        if self.wall_clock_breakdown:
            self.timers(FORWARD_GLOBAL_TIMER).stop(synchronize=True)
        return loss

    def backward(self, loss=None):
        """Accumulate the cached micro-batch grads (reference engine.py:1804)."""
        assert self._cached_grads is not None, "call forward() before backward()"
        if self.wall_clock_breakdown:
            self.timers(BACKWARD_GLOBAL_TIMER).start()
        gas = self.gradient_accumulation_steps()
        scaled = jax.tree_util.tree_map(lambda g: g / gas, self._cached_grads)
        if self._grad_acc is None:
            self._grad_acc = scaled
        else:
            with self._ctx():
                self._grad_acc = self._jit_accum(self._grad_acc, scaled)
        self._cached_grads = None
        self.micro_steps += 1
        if self.wall_clock_breakdown:
            self.timers(BACKWARD_GLOBAL_TIMER).stop(synchronize=True)
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        """reference engine.py:1885."""
        return self.micro_steps % self.gradient_accumulation_steps() == 0

    def _numerics_raise_if_tripped(self, finite, timer=None):
        """numerics_check raise, shared by the fused train_batch and the
        forward/backward/step path. Fires BEFORE step bookkeeping (the
        message must name the offending step). fp16 with DYNAMIC loss
        scaling is exempt — a scale overflow is a routine self-recovering
        skip; static-scale fp16 has no recovery, so it raises too."""
        if not self._config.numerics_check_enabled:
            return
        if self.fp16_enabled and self._dynamic_scale:
            return
        # bool(finite) syncs on the step result — only reached when the
        # guard is active, so the async dispatch pipeline stays intact
        # for unguarded runs
        if bool(finite):
            return
        if timer is not None and self.wall_clock_breakdown:
            self.timers(timer).stop(synchronize=True)
        raise FloatingPointError(
            f"numerics_check: non-finite loss or gradients at global "
            f"step {self.global_steps} (update skipped). Inspect the "
            f"batch/learning rate; disable 'numerics_check' to run on.")

    def step(self):
        """Apply the update at the GAS boundary (reference engine.py:2000)."""
        if not self.is_gradient_accumulation_boundary():
            return
        assert self._grad_acc is not None, "no accumulated gradients"
        t0 = time.monotonic()
        if self.wall_clock_breakdown:
            self.timers(STEP_GLOBAL_TIMER).start()
        loss_ok = (self._loss_ok_acc if self._loss_ok_acc is not None
                   else jnp.asarray(True))
        stats = None
        with self._ctx():
            # health stats BEFORE the apply program — it donates (and so
            # invalidates) the accumulated gradient buffers
            if self._jit_health is not None:
                stats = self._jit_health(self._grad_acc)
            if self._nvme is not None:
                gnorm, grads_ok = self._jit_gnorm_finite(self._grad_acc)
                finite = self._nvme_apply(self._grad_acc, gnorm, grads_ok,
                                          loss_ok)
            else:
                self.params, self.opt_state, self.scaler_state, finite = \
                    self._jit_apply(self.params, self.opt_state,
                                    self._grad_acc, self.scaler_state, loss_ok)
        self._grad_acc = None
        self._loss_ok_acc = None
        self._numerics_raise_if_tripped(finite, timer=STEP_GLOBAL_TIMER)
        self._misc_runtime_step(self._last_micro_batch, finite)
        self._after_step(finite, stats=stats)
        if self._telemetry_on and self.train_tracer is not None:
            self.train_tracer.span("STEP", t0, time.monotonic(),
                                   cat="train", tid=0,
                                   step=self.global_steps)
        if self.wall_clock_breakdown:
            self.timers(STEP_GLOBAL_TIMER).stop(synchronize=True)

    def _misc_runtime_step(self, micro_batch, finite):
        """Eigenvalue / MoQ hooks at the GAS boundary (reference
        engine.py:1984,2058-2066). ``micro_batch``: one micro-batch dict."""
        if (self.eigenvalue is not None and micro_batch is not None
                and self.global_steps % max(
                    self.eigenvalue.gas_boundary_resolution, 1) == 0):
            mb = micro_batch
            with self._ctx():
                self._last_eigenvalues = self.eigenvalue.compute_eigenvalue(
                    self.loss_fn, self.params, mb)
            if self.quantizer is not None:
                from deepspeed_tpu.runtime.eigenvalue import block_paths
                self.quantizer.update_eigenvalues(
                    self._last_eigenvalues,
                    block_paths(self.params, self.eigenvalue.layer_name))
            if self.monitor is not None:
                self.monitor.write_events([
                    (f"Train/Eigenvalues/ModelBlockParam_{i}", ev,
                     self.global_samples)
                    for i, ev in enumerate(self._last_eigenvalues)])
        if self.quantizer is not None:
            with self._ctx():
                self.params = self.quantizer.quantize(
                    self.params, overflow=not bool(finite))

    def curriculum_enabled_legacy(self) -> bool:
        """reference engine.py curriculum_enabled_legacy."""
        return self.curriculum_scheduler is not None

    @property
    def curriculum_seqlen(self) -> Optional[int]:
        if self.curriculum_scheduler is None:
            return None
        return self.curriculum_scheduler.get_current_difficulty()

    def _apply_curriculum(self, batch):
        """Legacy curriculum learning: truncate sequences to the scheduled
        difficulty (reference engine.py:1702-1705 — seqlen is the difficulty
        metric; the reference's Megatron fork does the same truncation)."""
        if self.curriculum_scheduler is None:
            return batch
        seqlen = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)

        def trunc(x):
            x = jnp.asarray(x) if not isinstance(x, (jax.Array, np.ndarray)) \
                else x
            s_axis = x.ndim - 1
            if x.ndim >= 2 and x.shape[s_axis] > seqlen:
                return x[..., :seqlen]
            return x

        return {k: trunc(v) for k, v in batch.items()}

    def _maybe_profile_flops(self, batch):
        """One-shot flops report at profile_step (reference engine.py:1692)."""
        cfg = self._flops_profiler_cfg
        if (not cfg.enabled or self._flops_profiled
                or self.global_steps + 1 < cfg.profile_step):
            return
        from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler

        self._flops_profiled = True
        prof = FlopsProfiler(self.loss_fn, self.params)
        mb = {k: jax.tree_util.tree_map(lambda x: x[0], v)
              for k, v in batch.items()}
        report = prof.profile(self.loss_fn, self.params, mb, time_it=False)
        prof.n_params = int(sum(
            x.size for x in jax.tree_util.tree_leaves(self.params)
            if hasattr(x, "size")))
        self._flops_prof = prof     # feeds the 'profiling' registry section
        if cfg.detailed:
            try:
                prof.profile_modules(self.loss_fn, self.params, mb)
            except Exception as e:   # profiling must never kill training
                logger.warning("per-module flops attribution failed: %s", e)
        text = prof.print_model_profile(params=self.params,
                                        detailed=cfg.detailed,
                                        module_depth=cfg.module_depth,
                                        top_modules=cfg.top_modules)
        if cfg.output_file:
            with open(cfg.output_file, "w") as f:
                f.write(text or "")
        return report

    def _account_zero_reduction(self) -> None:
        """Per-step gradient-reduction byte counters (dstrace): every
        global step moves the full gradient tree through one
        data-parallel reduction — reduce-scatter under ZeRO's sharded
        grad layout (stage >= 1), ring all-reduce at stage 0 — at the
        ``communication_data_type`` boundary dtype. The payload is
        STATIC (param tree shape × comm itemsize), so the accounting is
        host arithmetic computed once and accumulated per step, priced
        by the same ``collective_cost`` table the dstlint SPMD pass
        budgets and the runtime comms logger record with."""
        params = getattr(self, "params", None)
        if self.dp_world_size <= 1 or params is None:
            return
        if self._zero_bytes_cache is None:
            from deepspeed_tpu.comm.collective_cost import wire_bytes

            cdt = self._config.communication_data_type
            dtype = COMM_DTYPES[cdt.lower()] if cdt else self.compute_dtype
            itemsize = np.dtype(dtype).itemsize
            n_elems = sum(int(np.prod(l.shape)) for l in
                          jax.tree_util.tree_leaves(params)
                          if hasattr(l, "shape"))
            payload = n_elems * itemsize
            kind = ("reduce_scatter" if self.zero_optimization()
                    else "psum")
            self._zero_bytes_cache = (
                payload, wire_bytes(kind, payload, self.dp_world_size),
                kind)
        payload, wire, kind = self._zero_bytes_cache
        self.metrics.inc("train.zero.reduce_payload_bytes", payload)
        self.metrics.inc("train.zero.reduce_wire_bytes", wire)
        self.metrics.set_gauge("train.zero.reduce_group_size",
                               self.dp_world_size)

    def _step_flops(self) -> float:
        """Model FLOPs of one global step from the train-step program's
        compile-time cost analysis (CompileWatcher records it when the
        AOT wrapper compiles; 0.0 until then / when the backend exposes
        no analysis). Cached — the program is compiled once."""
        if self._train_step_flops is None:
            progs = self.compile_obs.section().get("train_step", {})
            flops = sum(e.get("flops", 0.0) for e in progs.values())
            if not progs:
                return 0.0               # nothing compiled yet: retry later
            self._train_step_flops = flops
            if flops:
                self.metrics.set_gauge("train.flops_per_step", flops)
                nbytes = sum(e.get("bytes_accessed", 0.0)
                             for e in progs.values())
                if nbytes:
                    self.metrics.set_gauge(
                        "train.roofline_intensity_flops_per_byte",
                        flops / nbytes)
        return self._train_step_flops

    def _efficiency_section(self) -> dict:
        """``train.efficiency`` registry collector: the MFU arithmetic
        (model FLOPs per step x counted steps / elapsed vs peak) next to
        its ingredients, so a dashboard can re-derive or re-denominate."""
        from deepspeed_tpu.observability import mfu, peak_flops_per_device

        peak = peak_flops_per_device(self._config.peak_tflops)
        n_dev = int(self.mesh.devices.size)
        flops = self._step_flops()
        step_s = self.tput_timer.last_duration
        return {
            "model_flops_per_step": flops,
            "last_step_seconds": step_s,
            "peak_flops_per_device": peak["flops"],
            "peak_source": peak["source"],
            "device_kind": str(peak["device_kind"]),
            "n_devices": n_dev,
            "mfu": mfu(flops, step_s, n_dev, peak["flops"]),
        }

    def capture_profile(self, path: str):
        """Context manager capturing a jax/XLA profiler trace of the
        enclosed steps into ``path`` (loads in TensorBoard's profile
        plugin / xprof) — the on-demand deep dive under the always-on
        registry telemetry (docs/OBSERVABILITY.md)."""
        from deepspeed_tpu.observability import capture_profile

        return capture_profile(path)

    # --- dsttrain (docs/OBSERVABILITY.md "Training") --------------------------
    def _profiling_section(self) -> dict:
        """``profiling`` registry pull section: the flops-profiler's
        cost-analysis output (empty until ``flops_profiler.profile_step``
        fires) — so `dst prof --train`, the monitor sinks and the
        Prometheus exporter see the profile instead of only a log line."""
        if self._flops_prof is None:
            return {}
        return self._flops_prof.registry_section()

    def _publish_pending_train_stats(self) -> None:
        with self._train_stats_lock:
            pending = self._pending_train_stats
            self._pending_train_stats = None
        if pending is None:
            return
        step, stats, finite, scale, loss = pending
        publish_train_stats(
            self.metrics, stats if stats else None, step=step,
            tracer=self.train_tracer, finite=finite, loss_scale=scale,
            dynamic_scale=self.fp16_enabled and self._dynamic_scale,
            loss=loss, logger=logger)

    def flush_train_telemetry(self) -> None:
        """Publish the pending (lag-one) step's health stats now. Called
        automatically at monitor drains and by :meth:`train_metrics`;
        call it manually before reading ``engine.metrics`` right after a
        step."""
        if self._telemetry_on:
            self._publish_pending_train_stats()

    def _trace_step_lanes(self, t_step0, t_data1, t_prog0, t_prog1) -> None:
        """Step-phase histograms + STEP/DATA/FWD_BWD spans for the step
        that just completed (and pipeline microbatch lanes on 1F1B
        engines). All host arithmetic; span boundaries are the engine's
        real host boundaries — under async dispatch FWD_BWD is the
        program's dispatch window, not its device occupancy (the
        profiler capture is the escape hatch for that)."""
        if not self._telemetry_on:
            return
        t_step1 = time.monotonic()
        self.metrics.observe("train.phase.data_s",
                             max(t_data1 - t_step0, 0.0))
        self.metrics.observe("train.phase.fwd_bwd_s",
                             max(t_prog1 - t_prog0, 0.0))
        tr = self.train_tracer
        if tr is None:
            return
        step = self.global_steps
        tr.span("DATA", t_step0, t_data1, cat="train", tid=0, step=step)
        tr.span("FWD_BWD", t_prog0, t_prog1, cat="train", tid=0, step=step)
        tr.span("STEP", t_step0, t_step1, cat="train", tid=0, step=step)
        if self._pipe_lane_info is not None:
            pipeline_lane_spans(tr, t_prog0, t_prog1,
                                *self._pipe_lane_info, step=step)

    def train_metrics(self, format: str = "dict", fleet: bool = False):
        """The training registry, in one of two shapes (the training
        twin of ``InferenceEngine.serve_metrics``):

        - ``format="dict"``: the plain ``snapshot()`` — step/phase
          histograms, grad-norm health, throughput, MFU, ZeRO reduction
          bytes, compile/memory/efficiency/profiling/comm sections.
        - ``format="prometheus"``: the same registry as exposition text
          (real ``_bucket/_sum/_count`` histograms), the payload the
          ``metrics_port`` endpoint scrapes.

        Flushes the pending lag-one step first, so the rendering always
        reflects every completed step.

        ``fleet=True`` (requires the ``fleet.dir`` config) publishes
        this rank's snapshot into the exchange and renders the MERGED
        fleet registry instead — counters summed, gauges per-host
        labeled + min/mean/max, histograms merged losslessly."""
        self.flush_train_telemetry()
        registry = self.metrics
        if fleet:
            if self.fleet_monitor is None:
                raise ValueError(
                    "train_metrics(fleet=True) needs the fleet.dir "
                    "config (the shared snapshot-exchange directory)")
            self.fleet_monitor.publish()
            registry = self.fleet_monitor.aggregate()
        if format == "dict":
            return registry.snapshot()
        if format == "prometheus":
            from deepspeed_tpu.observability import prometheus_text

            return prometheus_text(registry)
        raise ValueError(
            f"train_metrics(format={format!r}): expected 'dict' or "
            f"'prometheus'")

    def start_metrics_server(self, port: Optional[int] = None,
                             extra_registries: Optional[dict] = None
                             ) -> int:
        """Start the stdlib HTTP scrape endpoint (``/metrics``
        Prometheus text, ``/metrics.json`` raw snapshot) over the
        training registry on ``port`` (default: the ``metrics_port``
        config knob; 0 binds an ephemeral port). Idempotent; returns
        the bound port.

        ``extra_registries`` ({section: registry-or-callable}) merges
        more registries into the SAME ``/metrics`` exposition — one
        port for a process that also runs a serving engine
        (``{"serve": inf_engine.metrics}``); the tier-1 suite pins the
        two engines' metric names collision-free."""
        if self._metrics_server is not None:
            return self._metrics_server.port
        from deepspeed_tpu.observability import (
            MetricsHTTPServer, prometheus_text,
        )

        if port is None:
            port = int(getattr(self._config, "metrics_port", 0))

        def flushed():
            self.flush_train_telemetry()
            return self.metrics

        if extra_registries:
            named = dict(extra_registries)
            named["train"] = flushed
            self._metrics_server = MetricsHTTPServer.for_registries(
                named, port=port)
        else:
            self._metrics_server = MetricsHTTPServer(
                lambda: prometheus_text(flushed()),
                json_fn=self.metrics.snapshot, port=port)
        bound = self._metrics_server.start()
        log_dist(f"dsttrain metrics endpoint on :{bound}/metrics",
                 ranks=[0])
        return bound

    def stop_metrics_server(self) -> None:
        if getattr(self, "_metrics_server", None) is not None:
            self._metrics_server.stop()
            self._metrics_server = None

    def _trace_ckpt(self, op: str, tag: str, t0: float) -> None:
        """CKPT span + phase histogram for a save/load that just ran."""
        if not getattr(self, "_telemetry_on", False):
            return
        t1 = time.monotonic()
        self.metrics.observe("train.phase.ckpt_s", t1 - t0)
        if self.train_tracer is not None:
            self.train_tracer.span("CKPT", t0, t1, cat="train", tid=0,
                                   op=op, tag=str(tag))

    def export_train_trace(self, path: Optional[str] = None) -> dict:
        """The accumulated training-step trace as a Chrome/Perfetto
        trace-event JSON object (STEP/DATA/FWD_BWD/OPTIM/CKPT spans,
        OVERFLOW/SCALE instants, pipeline microbatch lanes); written to
        ``path`` when given. Raises when tracing is off."""
        if self.train_tracer is None:
            raise RuntimeError(
                "no training trace recorded: train_telemetry.trace is "
                "off (or train_telemetry.enabled is false)")
        if path:
            return self.train_tracer.export(path)
        return self.train_tracer.chrome()

    def _after_step(self, finite, loss=None, stats=None):
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self._account_zero_reduction()
        if self.compression_scheduler is not None:
            self.compression_scheduler.step(self.global_steps)
        if self.progressive_layer_drop is not None:
            theta = self.progressive_layer_drop.update_state(self.global_steps)
            if (self.monitor is not None
                    and self.global_steps % self._config.steps_per_print == 0):
                self.monitor.write_events([
                    ("Train/Samples/pld_theta", theta, self.global_samples)])
        if self.fp16_enabled:
            if not bool(finite):
                self.skipped_steps += 1
                log_dist(f"[loss scaling] overflow, skipping step "
                         f"(scale now {float(self.scaler_state.scale)})", ranks=[0])
        self.tput_timer.stop(global_step=True)
        if self.tput_timer.last_duration > 0:
            # per-host step-time gauge: the fleet merge's straggler
            # signal (fleet.step_time.skew reads each rank's value)
            self.metrics.set_gauge("train.step_time_s",
                                   self.tput_timer.last_duration)
        # step MFU: exact program FLOPs (compile-time cost analysis) over
        # measured step wall clock and the platform peak — the headline
        # achieved-vs-peak number (PAPERS.md: DeepSpeed-Inference /
        # Gemma-on-TPU report efficiency exactly this way). Host
        # arithmetic on already-recorded numbers; no device sync.
        flops = self._step_flops()
        if flops and self.tput_timer.last_duration > 0:
            from deepspeed_tpu.observability import mfu, \
                peak_flops_per_device

            peak = peak_flops_per_device(self._config.peak_tflops)
            mfu_v = mfu(flops, self.tput_timer.last_duration,
                        int(self.mesh.devices.size), peak["flops"])
            self.metrics.set_gauge("train.mfu", mfu_v)
            self.metrics.set_gauge(
                "train.model_flops_per_sec",
                flops / self.tput_timer.last_duration)
            if peak["flops"]:
                # measured per-step COMM ENVELOPE: in-graph collectives
                # have no host-visible wall time, but (step time − AOT-
                # costed ideal compute time) bounds everything that is
                # not pure compute — communication, schedule bubbles,
                # dispatch gaps. An upper bound on comm, not a
                # measurement of it; trend + fleet skew is the signal.
                ideal_s = flops / (peak["flops"]
                                   * int(self.mesh.devices.size))
                self.metrics.set_gauge(
                    "train.comm_fraction",
                    min(max(1.0 - ideal_s
                            / self.tput_timer.last_duration, 0.0), 1.0))
            if self._pipe_bubble is not None:
                # measured-step-vs-ideal: the fraction of the schedule-
                # adjusted ceiling achieved (MFU / (1 - bubble)) — next
                # to MFU so dashboards separate "schedule overhead" from
                # "kernel efficiency" (docs/OBSERVABILITY.md)
                self.metrics.set_gauge(
                    "train.pipeline.schedule_efficiency",
                    schedule_efficiency(mfu_v, self._pipe_bubble))
        # dsttrain lag-one publication: push the PREVIOUS step's health
        # stats out (its scalars materialized while this step ran — the
        # host reads below never drain the dispatch queue), then bank
        # this step's. flush_train_telemetry() forces the pending one.
        if self._telemetry_on:
            self._publish_pending_train_stats()
            scale = None
            if self.fp16_enabled:
                # fused path: the scale snapshot inside the stats pytree
                # (the live scaler buffer is donated next step); non-fused
                # tiers update the scaler host-side, so the live value is
                # stable
                scale = (stats["loss_scale"]
                         if stats and "loss_scale" in stats
                         else self.scaler_state.scale)
            # banked under the same lock the scrape-thread flush takes:
            # the pair (publish previous, bank current) must never let a
            # concurrent flush observe-and-clear a half-swapped tuple
            with self._train_stats_lock:
                self._pending_train_stats = (
                    self.global_steps, stats, finite, scale, loss)
        if (self.monitor is not None
                and self.global_steps % self._config.steps_per_print == 0):
            # print boundary: the registry is about to be drained into
            # sinks — publish the pending step so the drain is current
            self.flush_train_telemetry()
            # the reference's event contract (SURVEY §8.6; engine.py:
            # 1826-1834, 2045-2067). Emitted at steps_per_print boundaries:
            # float(loss) is a device sync, and syncing every step would
            # serialize the async dispatch the fused train program relies on.
            events = []
            if loss is not None:
                self.losses = float(loss)
                events.append(("Train/Samples/train_loss", self.losses,
                               self.global_samples))
            events.append(("Train/Samples/lr", self.get_lr()[0],
                           self.global_samples))
            if self.fp16_enabled and self._dynamic_scale:
                events.append(("Train/Samples/loss_scale",
                               float(self.scaler_state.scale),
                               self.global_samples))
            self.monitor.write_events(events)
            # drain the dstrace registry (timers, throughput, ZeRO
            # reduction bytes, comms wire totals) into the same sinks
            self.monitor.write_registry(self.metrics, self.global_samples)
        if (self.fleet_monitor is not None
                and self.global_steps % self._config.steps_per_print == 0):
            # fleet snapshot exchange at the same drain cadence: every
            # rank publishes its rank<k>.json; rank 0 merges + refreshes
            # the fleet.* skew gauges (they then ride THIS registry's
            # monitor/scrape pipeline like any other gauge)
            self.flush_train_telemetry()
            self.fleet_monitor.publish_and_aggregate()

    def destroy(self):
        """Release engine-held native resources (AIO thread pools, pending
        async checkpoint, metrics endpoint). Idempotent; also runs at GC
        via finalizers."""
        self.stop_metrics_server()
        if getattr(self, "_nvme", None) is not None:
            self._nvme_finalizer()      # weakref.finalize: at-most-once
            self._nvme = None
        if getattr(self, "_pnvme", None) is not None:
            self._pnvme_finalizer()
            self._pnvme = None
        if hasattr(self, "_ckpt_engine"):
            self._ckpt_engine.wait()

    def eval_loss(self, batch: Dict[str, Any]):
        """Forward-only loss (no gradient program)."""
        if self._compressor is not None:
            batch = {**batch, STEP_KEY: jnp.asarray(self.global_steps, jnp.int32)}
        batch = self._shard_batch(batch)
        with self._ctx():
            if self._pnvme is not None:
                return self._pnvme.loss_eval(batch)
            return self._jit_loss(self.params, batch)

    def consolidated_state_dict(self, dtype=None):
        """Full (replicated) parameter pytree as numpy — the live analogue of
        the reference's ``_zero3_consolidated_16bit_state_dict``
        (engine.py:3230): gathers every ZeRO shard."""
        if self._pnvme is not None:
            tree = self._pnvme.materialize()
            return (jax.tree_util.tree_map(lambda a: a.astype(dtype), tree)
                    if dtype is not None else tree)
        rep = NamedSharding(self.mesh, PartitionSpec())

        def gather(p):
            arr = jax.device_put(p, rep)
            out = np.asarray(arr)
            return out.astype(dtype) if dtype is not None else out

        return jax.tree_util.tree_map(gather, self.params)

    # --- checkpointing --------------------------------------------------------
    @property
    def checkpoint_engine(self):
        """One engine instance per training engine so async saves
        (checkpoint.async_save, the Nebula analogue) overlap training and
        are fenced before the next save/load."""
        if not hasattr(self, "_ckpt_engine"):
            from deepspeed_tpu.runtime.checkpoint_engine.orbax_engine import (
                OrbaxCheckpointEngine,
            )

            self._ckpt_engine = OrbaxCheckpointEngine(
                async_save=self._config.checkpoint_config.async_save)
        return self._ckpt_engine

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None, save_latest: bool = True):
        t_ckpt0 = time.monotonic()
        engine = self.checkpoint_engine
        tag = tag or f"global_step{self.global_steps}"
        nvme_count = (self._pnvme.count if self._pnvme is not None
                      else self._nvme.count if self._nvme is not None
                      else None)
        state = {
            # param-NVMe: params checkpoint by FILE COPY below too
            "params": {} if self._pnvme is not None else self.params,
            # NVMe states checkpoint by FILE COPY below (streaming, never
            # gathered) — the pytree carries only the update count
            "opt_state": ({"count": np.asarray(nvme_count)}
                          if nvme_count is not None else self.opt_state),
            "scaler": self.scaler_state,
        }
        meta = {
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "client_state": client_state or {},
        }
        engine.save(save_dir, tag, state, meta, save_latest=save_latest)
        if self._nvme is not None:
            import os as _os

            self._nvme.save_files(_os.path.join(save_dir, tag, "nvme_opt"))
        if self._pnvme is not None:
            import os as _os

            self._pnvme.save_files(
                _os.path.join(save_dir, tag, "nvme_params"))
        log_dist(f"saved checkpoint {save_dir}/{tag}", ranks=[0])
        self._trace_ckpt("save", tag, t_ckpt0)
        return True

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True):
        import os as _os

        t_ckpt0 = time.monotonic()
        engine = self.checkpoint_engine
        engine.wait()   # a pending async save must land before 'latest'
        tag = engine.resolve_tag(load_dir, tag)
        if self._pnvme is not None:
            pdir = _os.path.join(load_dir, tag, "nvme_params")
            if not _os.path.isdir(pdir):
                raise NotImplementedError(
                    f"{load_dir}/{tag} is a dense checkpoint; restoring it "
                    f"into a {self._interpreter_tier} engine requires "
                    "materializing the full tree — load it with a dense "
                    "engine and pass engine.consolidated_state_dict() as "
                    "initialize(params=...) instead")
            template = {"params": {},
                        "opt_state": {"count": np.asarray(0)},
                        "scaler": self.scaler_state}
            state, meta = engine.load(load_dir, tag, template)
            self._pnvme.load_files(
                pdir, load_optimizer_states=load_optimizer_states)
            if load_optimizer_states:
                self.scaler_state = state["scaler"]
            self.global_steps = meta.get("global_steps", 0)
            self.global_samples = meta.get("global_samples", 0)
            self.micro_steps = meta.get("micro_steps", 0)
            self.skipped_steps = meta.get("skipped_steps", 0)
            log_dist(f"loaded {self._interpreter_tier} checkpoint from "
                     f"{load_dir} (tag={tag})", ranks=[0])
            self._trace_ckpt("load", tag, t_ckpt0)
            return load_dir, meta.get("client_state", {})
        nvme_dir = _os.path.join(load_dir, tag, "nvme_opt")
        ckpt_is_nvme = _os.path.isdir(nvme_dir)
        if self._nvme is not None and not ckpt_is_nvme:
            # dense checkpoint into an NVMe engine: restore the optax
            # state (host zeros template) and convert to swapped groups
            abstract = jax.eval_shape(self.optimizer.init, self.params)
            opt_template = jax.tree_util.tree_map(
                lambda x: np.zeros(x.shape, x.dtype), abstract)
        elif ckpt_is_nvme:
            opt_template = {"count": np.asarray(0)}
        else:
            opt_template = self.opt_state
        template = {
            "params": self.params,
            "opt_state": opt_template,
            "scaler": self.scaler_state,
        }
        state, meta = engine.load(load_dir, tag, template)
        self.params = state["params"]
        if load_optimizer_states:
            from deepspeed_tpu.runtime.zero.infinity import (
                extract_adam_state, inject_adam_state, read_nvme_opt_dir,
            )

            params_treedef = jax.tree_util.tree_structure(self.params)
            if self._nvme is not None and ckpt_is_nvme:
                self._nvme.load_files(nvme_dir,
                                      int(state["opt_state"]["count"]))
            elif self._nvme is not None:
                self._nvme.load_state(
                    extract_adam_state(state["opt_state"]))
            elif ckpt_is_nvme:
                self.opt_state = inject_adam_state(
                    self.opt_state, read_nvme_opt_dir(nvme_dir),
                    params_treedef)
            else:
                self.opt_state = state["opt_state"]
            self.scaler_state = state["scaler"]
        self.global_steps = meta.get("global_steps", 0)
        self.global_samples = meta.get("global_samples", 0)
        self.micro_steps = meta.get("micro_steps", 0)
        self.skipped_steps = meta.get("skipped_steps", 0)
        log_dist(f"loaded checkpoint from {load_dir} (tag={tag})", ranks=[0])
        self._trace_ckpt("load", tag, t_ckpt0)
        return load_dir, meta.get("client_state", {})

    def load_universal_checkpoint(self, load_dir: str,
                                  tag: Optional[str] = None,
                                  load_optimizer_states: bool = True):
        """Cross-topology resume (reference ``load_universal_checkpoint``,
        engine.py:772 + checkpoint/universal_checkpoint.py:12): load a
        checkpoint saved on ANY mesh shape into this engine's mesh.

        The reference re-chunks per-param fp32 fragments by recorded
        ``cat_dim`` to re-layout flat partitions for a new TP/PP/DP world.
        Here checkpoints store logical (unsharded) arrays + sharding
        metadata, so resharding happens at restore: the load template
        carries THIS engine's shardings and orbax re-lays every array out
        to them — the per-fragment address arithmetic is unnecessary by
        construction. This method is therefore ``load_checkpoint`` with the
        contract made explicit (and tested across dp↔tp↔zero-stage
        changes, tests/unit/checkpoint/test_universal.py)."""
        return self.load_checkpoint(
            load_dir, tag, load_optimizer_states=load_optimizer_states)
