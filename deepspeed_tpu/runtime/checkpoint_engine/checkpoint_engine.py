"""Pluggable checkpoint backend ABC
(reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``)."""

import abc
from typing import Any, Dict, Optional, Tuple


class CheckpointEngine(abc.ABC):
    @abc.abstractmethod
    def save(self, save_dir: str, tag: str, state: Dict[str, Any],
             meta: Dict[str, Any], save_latest: bool = True) -> None:
        ...

    @abc.abstractmethod
    def load(self, load_dir: str, tag: Optional[str],
             template: Dict[str, Any]) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        ...

    def commit(self, tag: str) -> bool:
        return True

    def wait(self) -> None:
        """Fence any pending async save. Engines without async saving
        inherit this no-op (the training engine calls wait() before every
        load and at destroy())."""

    def resolve_tag(self, load_dir: str, tag: Optional[str]) -> str:
        """Resolve the tag to load: explicit tag wins, else the ``latest``
        file written beside the checkpoints (reference engine.py
        ``_get_ckpt_name`` latest-tag convention)."""
        if tag is not None:
            return tag
        import os

        latest = os.path.join(load_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                return f.read().strip()
        raise FileNotFoundError(
            f"no tag given and no 'latest' file in {load_dir}")
