"""Pluggable checkpoint backend ABC
(reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``)."""

import abc
from typing import Any, Dict, Optional, Tuple


class CheckpointEngine(abc.ABC):
    @abc.abstractmethod
    def save(self, save_dir: str, tag: str, state: Dict[str, Any],
             meta: Dict[str, Any], save_latest: bool = True) -> None:
        ...

    @abc.abstractmethod
    def load(self, load_dir: str, tag: Optional[str],
             template: Dict[str, Any]) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        ...

    def commit(self, tag: str) -> bool:
        return True
