"""Orbax-backed sharding-aware checkpointing.

Replaces the reference's torch checkpoint engine + Nebula async engine
(runtime/checkpoint_engine/). Arrays are saved with their shard layout and
restored to the *current* sharding — so resuming on a different mesh
(changed dp/tp world) is metadata-only resharding, which is what the
reference's elastic checkpointing and universal checkpoint machinery
(stage_1_and_2.py:2014, checkpoint/universal_checkpoint.py) do with explicit
re-chunking code.
"""

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import CheckpointEngine

LATEST_FILE = "latest"


class OrbaxCheckpointEngine(CheckpointEngine):
    """``async_save=True`` is the Nebula analogue
    (nebula_checkpoint_engine.py: persist in the background, training
    continues): ``save`` returns after scheduling the write; call
    ``wait()`` (or start another save/load) to block until durable. The
    ``latest`` tag is only written once the snapshot is finished."""

    def __init__(self, async_save: bool = False):
        self.async_save = async_save
        self._ckptr = ocp.StandardCheckpointer()
        self._pending = None      # (save_dir, path, tag, meta, save_latest)
        if async_save:
            # a process exiting right after its last save must still land
            # that snapshot (meta.json + latest tag)
            import atexit

            atexit.register(self._finalize)

    def _finalize(self):
        if self._pending is None:
            return
        self._ckptr.wait_until_finished()
        save_dir, path, tag, meta, save_latest = self._pending
        self._pending = None
        if jax.process_index() == 0:
            with open(os.path.join(path, "meta.json"), "w") as f:
                json.dump(meta, f)
            if save_latest:
                with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                    f.write(tag)

    def wait(self) -> None:
        """Block until the scheduled async save is durable (the reference's
        commit() barrier, checkpoint_engine.py:9)."""
        self._finalize()

    def commit(self, tag: str = "") -> bool:
        self._finalize()
        return True

    def save(self, save_dir: str, tag: str, state: Dict[str, Any],
             meta: Dict[str, Any], save_latest: bool = True) -> None:
        self._finalize()          # at most one in-flight snapshot
        path = os.path.abspath(os.path.join(save_dir, tag))
        self._ckptr.save(os.path.join(path, "state"), state, force=True)
        self._pending = (save_dir, path, tag, meta, save_latest)
        if not self.async_save:
            self._finalize()

    @staticmethod
    def resolve_tag(load_dir: str, tag: Optional[str]) -> str:
        """The single source of tag resolution (callers that need the
        resolved tag — e.g. for sibling files in the snapshot dir — use
        this instead of re-reading ``latest`` themselves)."""
        if tag is not None:
            return tag
        with open(os.path.join(load_dir, LATEST_FILE)) as f:
            return f.read().strip()

    def load(self, load_dir: str, tag: Optional[str],
             template: Dict[str, Any]) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        self._finalize()          # a pending async save must land first
        tag = self.resolve_tag(load_dir, tag)
        path = os.path.abspath(os.path.join(load_dir, tag))
        ckptr = self._ckptr
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            template)
        state = ckptr.restore(os.path.join(path, "state"), abstract)
        meta_path = os.path.join(path, "meta.json")
        meta = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        return state, meta
