from deepspeed_tpu.runtime.swap_tensor.swapper import (
    AsyncTensorSwapper,
    PartitionedOptimizerSwapper,
    PipelinedOptimizerSwapper,
)
