"""Tensor swapping to host disk (ZeRO-Infinity analogue).

Reference ``runtime/swap_tensor/`` (``AsyncPartitionedParameterSwapper``
partitioned_param_swapper.py:36, ``PartitionedOptimizerSwapper``
partitioned_optimizer_swapper.py:28) over ``csrc/aio``. Here: pytrees of
jax arrays swap to per-leaf files through the C++ AIO thread pool
(deepspeed_tpu/ops/native.py), overlapping disk traffic with device work.
The device→host hop is explicit (np.asarray) because on TPU-VM the host RAM
*is* the first offload tier; disk is the second.
"""

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.ops.native import AsyncIOHandle
from deepspeed_tpu.utils.logging import logger


class AsyncTensorSwapper:
    def __init__(self, swap_dir: str, block_size: int = 1 << 20,
                 queue_depth: int = 8, thread_count: int = 4):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.aio = AsyncIOHandle(block_size, queue_depth, thread_count)
        # name -> (treedef, [(shape, dtype), ...])
        self._meta: Dict[str, Tuple] = {}
        # names with writes submitted but not yet waited on; the AIO thread
        # pool does not order a queued read after a queued write of the same
        # file, so reads of these names must drain writes first
        self._pending_writes: set = set()

    def _leaf_path(self, name: str, i: int) -> str:
        return os.path.join(self.swap_dir, f"{name}.{i}.bin")

    def _drain_writes_for(self, name: str) -> None:
        if name in self._pending_writes:
            failures = self.wait()
            if failures:
                raise IOError(f"drain before read of {name}: "
                              f"{failures} write failures")

    def swap_out(self, name: str, tree: Any, blocking: bool = True) -> None:
        """Write a pytree to disk (async submit; optional wait)."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        shapes = []
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            shapes.append((arr.shape, arr.dtype))
            self.aio.pwrite(self._leaf_path(name, i), arr)
        self._meta[name] = (treedef, shapes)
        if blocking:
            failures = self.wait()
            if failures:
                raise IOError(f"swap_out({name}): {failures} write failures")
        else:
            self._pending_writes.add(name)

    def submit_reads(self, name: str, aio) -> Tuple[Any, list]:
        """Allocate buffers for ``name`` and submit its preads on ``aio``
        (shared by blocking swap_in and pipelined prefetch). Drains any
        in-flight write of the same name first."""
        assert name in self._meta, f"nothing swapped out under {name}"
        self._drain_writes_for(name)
        treedef, shapes = self._meta[name]
        buffers = [np.empty(shape, dtype) for shape, dtype in shapes]
        for i, buf in enumerate(buffers):
            aio.pread(self._leaf_path(name, i), buf)
        return treedef, buffers

    def swap_in(self, name: str, device_put: bool = True,
                sharding=None) -> Any:
        """Read a previously swapped pytree back (blocking)."""
        treedef, buffers = self.submit_reads(name, self.aio)
        failures = self.wait()
        if failures:
            raise IOError(f"swap_in({name}): {failures} read failures")
        if device_put:
            buffers = [jax.device_put(b, sharding) for b in buffers]
        return jax.tree_util.tree_unflatten(treedef, buffers)

    def wait(self) -> int:
        """Wait-all on the queue; returns the failure count."""
        failures = self.aio.wait()
        self._pending_writes.clear()
        return failures

    def remove(self, name: str) -> None:
        if name in self._meta:
            _, shapes = self._meta.pop(name)
            for i in range(len(shapes)):
                try:
                    os.remove(self._leaf_path(name, i))
                except OSError:
                    pass

    def close(self) -> None:
        self.aio.close()


class PartitionedOptimizerSwapper:
    """Swap optimizer state between steps (reference
    partitioned_optimizer_swapper.py:28): swap_in before the update,
    swap_out after, so only one sub-group's state occupies memory at once."""

    def __init__(self, swap_dir: str, **aio_kwargs):
        self.swapper = AsyncTensorSwapper(swap_dir, **aio_kwargs)
        self._resident: Optional[str] = None

    def offload(self, name: str, opt_state: Any) -> None:
        self.swapper.swap_out(name, opt_state, blocking=True)
        self._resident = None

    def fetch(self, name: str, sharding=None) -> Any:
        state = self.swapper.swap_in(name, device_put=True, sharding=sharding)
        self._resident = name
        return state

    def close(self):
        self.swapper.close()


class PipelinedOptimizerSwapper(PartitionedOptimizerSwapper):
    """Double-buffered variant (reference pipelined_optimizer_swapper.py):
    while sub-group i's update runs on device, sub-group i+1's state is
    already being read from disk and i-1's updated state is being written —
    the AIO thread pool overlaps both directions with compute.

    Usage per step over an ordered list of sub-group names::

        sw.prefetch(names[0])
        for i, name in enumerate(names):
            state = sw.acquire(name)                    # waits if needed
            if i + 1 < len(names):
                sw.prefetch(names[i + 1])               # overlap next read
            state = update(state)                       # device compute
            sw.release(name, state)                     # async write-back
        sw.flush()
    """

    def __init__(self, swap_dir: str, **aio_kwargs):
        super().__init__(swap_dir, **aio_kwargs)
        # reads get their OWN queue: AsyncIOHandle.wait() is wait-ALL, so
        # sharing one queue would make acquire() block on the previous
        # release()'s writes (serializing the overlap this class exists for)
        # and misattribute write failures to reads
        self._read_aio = AsyncIOHandle(
            aio_kwargs.get("block_size", 1 << 20),
            aio_kwargs.get("queue_depth", 8),
            aio_kwargs.get("thread_count", 4))
        self._prefetched: Dict[str, Any] = {}

    def prefetch(self, name: str) -> None:
        """Submit the reads for ``name`` without blocking on them.
        ``submit_reads`` drains any in-flight ``release()`` write of the same
        name first, so release→prefetch→acquire returns the new state."""
        if name in self._prefetched:
            return
        self._prefetched[name] = self.swapper.submit_reads(name,
                                                           self._read_aio)

    def acquire(self, name: str, sharding=None) -> Any:
        """Finish the prefetched reads (or read synchronously) and return
        the device-resident state."""
        if name not in self._prefetched:
            return self.fetch(name, sharding=sharding)
        treedef, buffers = self._prefetched.pop(name)
        failures = self._read_aio.wait()
        if failures:
            raise IOError(f"acquire({name}): {failures} read failures")
        arrs = [jax.device_put(b, sharding) for b in buffers]
        return jax.tree_util.tree_unflatten(treedef, arrs)

    def release(self, name: str, opt_state: Any) -> None:
        """Write the updated state back without blocking."""
        # a new write invalidates any not-yet-acquired prefetch of this name
        self._prefetched.pop(name, None)
        self.swapper.swap_out(name, opt_state, blocking=False)

    def offload(self, name: str, opt_state: Any) -> None:
        self._prefetched.pop(name, None)
        super().offload(name, opt_state)

    def flush(self) -> None:
        """Barrier for all outstanding I/O; drops unconsumed prefetches so
        a later prefetch rereads current on-disk state."""
        self._prefetched.clear()
        failures = self.swapper.wait() + self._read_aio.wait()
        if failures:
            raise IOError(f"flush: {failures} I/O failures")

    def close(self):
        self._read_aio.close()
        super().close()
