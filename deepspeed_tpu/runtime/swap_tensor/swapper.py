"""Tensor swapping to host disk (ZeRO-Infinity analogue).

Reference ``runtime/swap_tensor/`` (``AsyncPartitionedParameterSwapper``
partitioned_param_swapper.py:36, ``PartitionedOptimizerSwapper``
partitioned_optimizer_swapper.py:28) over ``csrc/aio``. Here: pytrees of
jax arrays swap to per-leaf files through the C++ AIO thread pool
(deepspeed_tpu/ops/native.py), overlapping disk traffic with device work.
The device→host hop is explicit (np.asarray) because on TPU-VM the host RAM
*is* the first offload tier; disk is the second.
"""

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.ops.native import AsyncIOHandle
from deepspeed_tpu.utils.logging import logger


class AsyncTensorSwapper:
    def __init__(self, swap_dir: str, block_size: int = 1 << 20,
                 queue_depth: int = 8, thread_count: int = 4,
                 staging_mb: int = 0):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.aio = AsyncIOHandle(block_size, queue_depth, thread_count)
        # optional contiguous staging arena for read buffers (reference
        # swap-buffer pools, runtime/swap_tensor/utils.py, over the
        # zero/contiguous_memory_allocator.py arena): stable host addresses,
        # no per-swap allocator churn. Oversized/overflow requests fall back
        # to plain numpy allocation.
        self._arena = None
        if staging_mb > 0:
            from deepspeed_tpu.runtime.zero.contiguous_memory_allocator \
                import ContiguousMemoryAllocator

            self._arena = ContiguousMemoryAllocator(staging_mb << 20,
                                                    np.uint8)
        # name -> (treedef, [(shape, dtype), ...])
        self._meta: Dict[str, Tuple] = {}
        # name -> last submitted write request id; the AIO thread pool does
        # not order a queued read after a queued write of the same file, so
        # reads of these names drain THEIR writes first (wait_upto — other
        # names' in-flight I/O keeps overlapping)
        self._pending_writes: Dict[str, int] = {}

    def _alloc_staging(self, shape, dtype):
        """Return (array, handle|None): an arena view when possible."""
        if self._arena is None:
            return np.empty(shape, dtype), None
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        padded = max(64, -(-nbytes // 64) * 64)   # keep offsets 64B-aligned
        try:
            # never defrag here: sibling buffers may have reads in flight
            handle = self._arena.allocate(padded, allow_defrag=False)
        except MemoryError:
            return np.empty(shape, dtype), None
        view = handle.view()[:nbytes].view(dtype).reshape(shape)
        return view, handle

    def _free_staging(self, handles) -> None:
        if self._arena is not None:
            for h in handles:
                if h is not None:
                    self._arena.release(h)

    def _to_device(self, buffers, handles, sharding):
        """device_put staging buffers safely: the transfer must complete
        before the arena slots can be reused (block_until_ready), and on
        CPU backends jax.device_put may zero-copy ALIAS a 64B-aligned host
        buffer — arena views are exactly that — so those are copied first."""
        aliasing_backend = jax.default_backend() == "cpu"
        arrs = []
        for b, h in zip(buffers, handles):
            if h is not None and aliasing_backend:
                b = np.array(b)
            arrs.append(jax.device_put(b, sharding))
        jax.block_until_ready(arrs)
        return arrs

    def _leaf_path(self, name: str, i: int) -> str:
        return os.path.join(self.swap_dir, f"{name}.{i}.bin")

    def _drain_writes_for(self, name: str, context: str = "read") -> None:
        last_id = self._pending_writes.pop(name, None)
        if last_id is not None:
            failures = self.aio.wait_upto(last_id)
            # every pending write submitted at-or-before last_id is drained
            self._pending_writes = {n: i for n, i in
                                    self._pending_writes.items()
                                    if i > last_id}
            if failures:
                raise IOError(f"drain before {context} of {name}: "
                              f"{failures} write failures")

    def swap_out(self, name: str, tree: Any, blocking: bool = True) -> None:
        """Write a pytree to disk (async submit; optional wait)."""
        # write-after-write: a still-in-flight non-blocking swap_out of the
        # same name would race these pwrites into the same files with no
        # ordering guarantee from the AIO pool — drain it first
        self._drain_writes_for(name, context="rewrite")
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        shapes = []
        last_id = 0
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            shapes.append((arr.shape, arr.dtype))
            last_id = self.aio.pwrite(self._leaf_path(name, i), arr)
        self._meta[name] = (treedef, shapes)
        if blocking:
            failures = self.aio.wait_upto(last_id)
            if failures:
                raise IOError(f"swap_out({name}): {failures} write failures")
        else:
            self._pending_writes[name] = last_id

    def submit_reads(self, name: str, aio) -> Tuple[Any, list, list]:
        """Allocate buffers for ``name`` and submit its preads on ``aio``
        (shared by blocking swap_in and pipelined prefetch). Drains any
        in-flight write of the same name first. Returns
        (treedef, buffers, staging_handles) — pass the handles to
        ``_free_staging`` once the data has been consumed."""
        assert name in self._meta, f"nothing swapped out under {name}"
        self._drain_writes_for(name)
        treedef, shapes = self._meta[name]
        buffers, handles = [], []
        for shape, dtype in shapes:
            buf, h = self._alloc_staging(shape, dtype)
            buffers.append(buf)
            handles.append(h)
        for i, buf in enumerate(buffers):
            aio.pread(self._leaf_path(name, i), buf)
        return treedef, buffers, handles

    def swap_in(self, name: str, device_put: bool = True,
                sharding=None) -> Any:
        """Read a previously swapped pytree back (blocking)."""
        treedef, buffers, handles = self.submit_reads(name, self.aio)
        failures = self.wait()
        if failures:
            self._free_staging(handles)
            raise IOError(f"swap_in({name}): {failures} read failures")
        if device_put:
            buffers = self._to_device(buffers, handles, sharding)
            self._free_staging(handles)
        elif self._arena is not None:
            # hand out copies so arena views don't escape the pool
            buffers = [np.array(b) if h is not None else b
                       for b, h in zip(buffers, handles)]
            self._free_staging(handles)
        return jax.tree_util.tree_unflatten(treedef, buffers)

    def wait(self) -> int:
        """Wait-all on the queue; returns the failure count."""
        failures = self.aio.wait()
        self._pending_writes.clear()
        return failures

    def copy_files(self, name: str, dst_dir: str) -> None:
        """File-level copy of ``name``'s swapped leaves into ``dst_dir`` —
        O(io-buffer) host RAM, never materializing the state (checkpoint
        save for states too big to gather)."""
        import shutil

        assert name in self._meta, f"nothing swapped out under {name}"
        self._drain_writes_for(name, context="copy")
        os.makedirs(dst_dir, exist_ok=True)
        _, shapes = self._meta[name]
        for i in range(len(shapes)):
            shutil.copyfile(self._leaf_path(name, i),
                            os.path.join(dst_dir, f"{name}.{i}.bin"))

    def adopt_files(self, name: str, src_dir: str, template: Any) -> None:
        """Inverse of :meth:`copy_files`: copy leaf files from ``src_dir``
        into the swap dir and register ``template``'s structure/shapes as
        ``name``'s metadata (checkpoint load without materializing)."""
        import shutil

        leaves, treedef = jax.tree_util.tree_flatten(template)
        shapes = [(np.asarray(l).shape if not hasattr(l, "shape")
                   else tuple(l.shape),
                   np.dtype(getattr(l, "dtype", np.float32)))
                  for l in leaves]
        # validate EVERY file first: a mismatch found mid-copy would leave
        # the live swap state half-overwritten with checkpoint data
        for i, (shape, dtype) in enumerate(shapes):
            src = os.path.join(src_dir, f"{name}.{i}.bin")
            expect = int(np.prod(shape)) * dtype.itemsize
            got = os.path.getsize(src)
            if got != expect:
                raise ValueError(
                    f"adopt_files({name}): {src} is {got} bytes, template "
                    f"leaf {i} ({shape}, {dtype}) needs {expect}")
        for i in range(len(shapes)):
            shutil.copyfile(os.path.join(src_dir, f"{name}.{i}.bin"),
                            self._leaf_path(name, i))
        self._meta[name] = (treedef, shapes)

    def remove(self, name: str) -> None:
        if name in self._meta:
            _, shapes = self._meta.pop(name)
            for i in range(len(shapes)):
                try:
                    os.remove(self._leaf_path(name, i))
                except OSError:
                    pass

    def close(self) -> None:
        self.aio.close()


class PartitionedOptimizerSwapper:
    """Swap optimizer state between steps (reference
    partitioned_optimizer_swapper.py:28): swap_in before the update,
    swap_out after, so only one sub-group's state occupies memory at once."""

    def __init__(self, swap_dir: str, **aio_kwargs):
        self.swapper = AsyncTensorSwapper(swap_dir, **aio_kwargs)

    def offload(self, name: str, opt_state: Any) -> None:
        self.swapper.swap_out(name, opt_state, blocking=True)

    def fetch(self, name: str, sharding=None) -> Any:
        return self.swapper.swap_in(name, device_put=True, sharding=sharding)

    def close(self):
        self.swapper.close()


class PipelinedOptimizerSwapper(PartitionedOptimizerSwapper):
    """Double-buffered variant (reference pipelined_optimizer_swapper.py):
    while sub-group i's update runs on device, sub-group i+1's state is
    already being read from disk and i-1's updated state is being written —
    the AIO thread pool overlaps both directions with compute.

    Usage per step over an ordered list of sub-group names::

        sw.prefetch(names[0])
        for i, name in enumerate(names):
            state = sw.acquire(name)                    # waits if needed
            if i + 1 < len(names):
                sw.prefetch(names[i + 1])               # overlap next read
            state = update(state)                       # device compute
            sw.release(name, state)                     # async write-back
        sw.flush()
    """

    def __init__(self, swap_dir: str, **aio_kwargs):
        super().__init__(swap_dir, **aio_kwargs)
        # reads get their OWN queue: AsyncIOHandle.wait() is wait-ALL, so
        # sharing one queue would make acquire() block on the previous
        # release()'s writes (serializing the overlap this class exists for)
        # and misattribute write failures to reads
        self._read_aio = AsyncIOHandle(
            aio_kwargs.get("block_size", 1 << 20),
            aio_kwargs.get("queue_depth", 8),
            aio_kwargs.get("thread_count", 4))
        self._prefetched: Dict[str, Any] = {}
        self._stale_handles: list = []

    def prefetch(self, name: str) -> None:
        """Submit the reads for ``name`` without blocking on them.
        ``submit_reads`` drains any in-flight ``release()`` write of the same
        name first, so release→prefetch→acquire returns the new state."""
        if name in self._prefetched:
            return
        self._prefetched[name] = self.swapper.submit_reads(name,
                                                           self._read_aio)

    def acquire(self, name: str, sharding=None, device_put: bool = True) -> Any:
        """Finish the prefetched reads (or read synchronously) and return
        the state — device-resident, or host copies with
        ``device_put=False`` (callers owning per-leaf shardings transfer
        once themselves instead of staging through the default device)."""
        if name not in self._prefetched:
            return self.swapper.swap_in(name, device_put=device_put,
                                        sharding=sharding)
        treedef, buffers, handles = self._prefetched.pop(name)
        failures = self._read_aio.wait()
        self._reap_stale()          # discarded prefetches are now quiesced
        if failures:
            self.swapper._free_staging(handles)
            raise IOError(f"acquire({name}): {failures} read failures")
        if device_put:
            arrs = self.swapper._to_device(buffers, handles, sharding)
        else:
            arrs = [np.array(b) if h is not None else b
                    for b, h in zip(buffers, handles)]
        self.swapper._free_staging(handles)
        return jax.tree_util.tree_unflatten(treedef, arrs)

    def _discard_prefetch(self, name: str) -> None:
        """Invalidate a not-yet-acquired prefetch. Its staging buffers may
        still be read targets of in-flight I/O, so they are parked and only
        returned to the arena after the next read-queue barrier."""
        entry = self._prefetched.pop(name, None)
        if entry is not None:
            self._stale_handles.append(entry[2])

    def _reap_stale(self) -> None:
        for handles in self._stale_handles:
            self.swapper._free_staging(handles)
        self._stale_handles.clear()

    def release(self, name: str, opt_state: Any) -> None:
        """Write the updated state back without blocking."""
        # a new write invalidates any not-yet-acquired prefetch of this name
        self._discard_prefetch(name)
        self.swapper.swap_out(name, opt_state, blocking=False)

    def offload(self, name: str, opt_state: Any) -> None:
        self._discard_prefetch(name)
        super().offload(name, opt_state)

    def flush(self) -> None:
        """Barrier for all outstanding I/O; drops unconsumed prefetches so
        a later prefetch rereads current on-disk state."""
        failures = self.swapper.wait() + self._read_aio.wait()
        self._reap_stale()
        for _, _, handles in self._prefetched.values():
            self.swapper._free_staging(handles)
        self._prefetched.clear()
        if failures:
            raise IOError(f"flush: {failures} I/O failures")

    def close(self):
        self._read_aio.close()
        super().close()
