"""Tensor swapping to host disk (ZeRO-Infinity analogue).

Reference ``runtime/swap_tensor/`` (``AsyncPartitionedParameterSwapper``
partitioned_param_swapper.py:36, ``PartitionedOptimizerSwapper``
partitioned_optimizer_swapper.py:28) over ``csrc/aio``. Here: pytrees of
jax arrays swap to per-leaf files through the C++ AIO thread pool
(deepspeed_tpu/ops/native.py), overlapping disk traffic with device work.
The device→host hop is explicit (np.asarray) because on TPU-VM the host RAM
*is* the first offload tier; disk is the second.
"""

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.ops.native import AsyncIOHandle
from deepspeed_tpu.utils.logging import logger


class AsyncTensorSwapper:
    def __init__(self, swap_dir: str, block_size: int = 1 << 20,
                 queue_depth: int = 8, thread_count: int = 4):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.aio = AsyncIOHandle(block_size, queue_depth, thread_count)
        # name -> (treedef, [(shape, dtype), ...])
        self._meta: Dict[str, Tuple] = {}

    def _leaf_path(self, name: str, i: int) -> str:
        return os.path.join(self.swap_dir, f"{name}.{i}.bin")

    def swap_out(self, name: str, tree: Any, blocking: bool = True) -> None:
        """Write a pytree to disk (async submit; optional wait)."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        shapes = []
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            shapes.append((arr.shape, arr.dtype))
            self.aio.pwrite(self._leaf_path(name, i), arr)
        self._meta[name] = (treedef, shapes)
        if blocking:
            failures = self.aio.wait()
            if failures:
                raise IOError(f"swap_out({name}): {failures} write failures")

    def swap_in(self, name: str, device_put: bool = True,
                sharding=None) -> Any:
        """Read a previously swapped pytree back (blocking)."""
        assert name in self._meta, f"nothing swapped out under {name}"
        treedef, shapes = self._meta[name]
        buffers = [np.empty(shape, dtype) for shape, dtype in shapes]
        for i, buf in enumerate(buffers):
            self.aio.pread(self._leaf_path(name, i), buf)
        failures = self.aio.wait()
        if failures:
            raise IOError(f"swap_in({name}): {failures} read failures")
        if device_put:
            buffers = [jax.device_put(b, sharding) for b in buffers]
        return jax.tree_util.tree_unflatten(treedef, buffers)

    def wait(self) -> None:
        self.aio.wait()

    def remove(self, name: str) -> None:
        if name in self._meta:
            _, shapes = self._meta.pop(name)
            for i in range(len(shapes)):
                try:
                    os.remove(self._leaf_path(name, i))
                except OSError:
                    pass

    def close(self) -> None:
        self.aio.close()


class PartitionedOptimizerSwapper:
    """Swap optimizer state between steps (reference
    partitioned_optimizer_swapper.py:28): swap_in before the update,
    swap_out after, so only one sub-group's state occupies memory at once."""

    def __init__(self, swap_dir: str, **aio_kwargs):
        self.swapper = AsyncTensorSwapper(swap_dir, **aio_kwargs)
        self._resident: Optional[str] = None

    def offload(self, name: str, opt_state: Any) -> None:
        self.swapper.swap_out(name, opt_state, blocking=True)
        self._resident = None

    def fetch(self, name: str, sharding=None) -> Any:
        state = self.swapper.swap_in(name, device_put=True, sharding=sharding)
        self._resident = name
        return state

    def close(self):
        self.swapper.close()
