"""DS-Chat-shaped RLHF: actor (hybrid engine) + critic + frozen reward
model in one PPO loop.

TPU-native analogue of DeepSpeed-Chat's ``DeepSpeedPPOTrainer`` (the loop
the hybrid engine exists for — reference ``runtime/hybrid_engine.py:178-282``
serves its rollout phase; the trainer shape follows DeepSpeedExamples
step3 ``ppo_trainer.py``): generate_experience → compute advantages →
actor PPO-clip step + critic value step, each through its own
DeepSpeedEngine so every ZeRO/offload/LoRA feature composes per model.

All three forward paths (rollout logprobs, values, reward) are single
jitted programs; the PPO losses run through the engines' fused
``train_batch`` with the extra per-token arrays riding in the batch dict.
"""

from typing import Any, Callable, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
from deepspeed_tpu.utils.logging import log_dist


class CriticModel(nn.Module):
    """Value model: ANY hidden-state backbone + scalar value head per token
    (the DS-Chat critic/reward architecture — an LM with ``v_head``).

    The backbone must yield per-token hidden states: modules exposing
    ``return_hidden`` (LlamaModel) are called with it; others (the unified
    ``TransformerLM`` with ``lm_head=False`` — OPT/GPT-2/BLOOM-shaped
    critics, the reference DS-Chat workload is OPT,
    blogs/deepspeed-chat/README.md:57) must return hidden states directly.
    A backbone that would return VOCAB LOGITS raises instead of silently
    fitting a value head over the vocabulary axis."""

    backbone: nn.Module

    @nn.compact
    def __call__(self, input_ids, positions=None):
        import inspect

        bk = self.backbone
        bcfg = getattr(bk, "cfg", None)
        if getattr(bcfg, "lm_head", False):
            raise ValueError(
                f"CriticModel backbone {type(bk).__name__} has lm_head=True "
                f"— it returns vocab logits, not hidden states; build it "
                f"with lm_head=False (encoder output) for the value head")
        call = type(bk).__call__
        if "return_hidden" in inspect.signature(call).parameters:
            h = bk(input_ids, positions=positions, return_hidden=True)
        else:
            h = bk(input_ids, positions=positions)
        v = nn.Dense(1, use_bias=False, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="v_head")(
            h.astype(jnp.float32))
        return v[..., 0]                      # [B, T]


class LlamaCriticModel(nn.Module):
    """Llama-backbone critic (param tree {"base", "v_head"} — the round-3
    layout, kept so existing checkpoints and the bench path load
    unchanged). New code should prefer :class:`CriticModel`, which takes
    any backbone."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        h = LlamaModel(self.cfg, name="base")(
            input_ids, positions=positions, return_hidden=True)
        v = nn.Dense(1, use_bias=False, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="v_head")(
            h.astype(jnp.float32))
        return v[..., 0]                      # [B, T]


def _gather_logp(logits, actions):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, actions[..., None], axis=-1)[..., 0]


def make_actor_ppo_loss(model, clip_eps: float = 0.2):
    """PPO-clip policy loss over the generated span. Batch keys:
    input_ids [B,T], labels (= next-token actions) [B,T], old_logp [B,T],
    advantages [B,T], loss_mask [B,T] (1 on generated positions)."""

    def loss_fn(params, batch, rngs=None):
        logits = model.apply({"params": params}, batch["input_ids"],
                             rngs=rngs)
        logp = _gather_logp(logits, batch["labels"])
        ratio = jnp.exp(logp - batch["old_logp"])
        adv = batch["advantages"]
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps) * adv)
        mask = batch["loss_mask"].astype(jnp.float32)
        return -(surr * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    return loss_fn


def make_critic_value_loss(model, clip_eps: float = 0.2):
    """Clipped value loss (DS-Chat critic_loss_fn). Batch keys: input_ids,
    returns [B,T], old_values [B,T], loss_mask [B,T]."""

    def loss_fn(params, batch, rngs=None):
        v = model.apply({"params": params}, batch["input_ids"], rngs=rngs)
        old_v = batch["old_values"]
        clipped = old_v + jnp.clip(v - old_v, -clip_eps, clip_eps)
        err = jnp.maximum(jnp.square(v - batch["returns"]),
                          jnp.square(clipped - batch["returns"]))
        mask = batch["loss_mask"].astype(jnp.float32)
        return 0.5 * (err * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    return loss_fn


class DeepSpeedPPOTrainer:
    """Owns the three models of the DS-Chat loop.

    actor_engine:  DeepSpeedHybridEngine over the policy LM (train +
                   generate on one sharded pytree).
    critic_engine: DeepSpeedEngine over :class:`LlamaCriticModel`.
    reward_fn:     frozen scorer ``(seq_ids) -> [B] rewards`` — built from
                   a reward-model params pytree via :meth:`reward_from_params`,
                   or any callable (rule-based shaping in tests).
    ref_logp_fn:   optional frozen REFERENCE policy ``(seq) -> [B, T-1]``
                   per-token logprobs (:meth:`ref_logp_from_params`); with
                   it, per-token rewards carry the DS-Chat KL penalty
                   ``-kl_ctl * (logp - ref_logp)`` (compute_rewards).
    PPO/value clip epsilons live on the loss factories
    (:func:`make_actor_ppo_loss` / :func:`make_critic_value_loss`) that the
    engines were built with.
    """

    def __init__(self, actor_engine, critic_engine,
                 reward_fn: Callable[[Any], Any],
                 gamma: float = 1.0, lam: float = 0.95,
                 kl_ctl: float = 0.1,
                 ref_logp_fn: Optional[Callable[[Any], Any]] = None):
        self.actor = actor_engine
        self.critic = critic_engine
        self.reward_fn = reward_fn
        self.ref_logp_fn = ref_logp_fn
        self.gamma = gamma
        self.lam = lam
        self.kl_ctl = kl_ctl if ref_logp_fn is not None else 0.0
        actor_model = self.actor.module
        critic_model = self.critic.module

        @jax.jit
        def rollout_stats(actor_params, critic_params, seq):
            inputs, actions = seq[:, :-1], seq[:, 1:]
            logits = actor_model.apply({"params": actor_params}, inputs)
            logp = _gather_logp(logits, actions)
            values = critic_model.apply({"params": critic_params}, inputs)
            return logp, values

        self._rollout_stats = rollout_stats
        self.generate_time = 0.0
        self.actor_step_time = 0.0
        self.critic_step_time = 0.0

    @staticmethod
    def ref_logp_from_params(ref_model, ref_params):
        """Frozen reference-policy logprob scorer from an actor-architecture
        params pytree (the DS-Chat actor-ref model)."""

        @jax.jit
        def ref_logp(seq):
            logits = ref_model.apply({"params": ref_params}, seq[:, :-1])
            return _gather_logp(logits, seq[:, 1:])

        return ref_logp

    @staticmethod
    def reward_from_params(reward_model, reward_params):
        """Frozen reward scorer from a critic-architecture params pytree:
        the value at the final token is the sequence reward (DS-Chat
        reward_model forward_value(..., return_value_only=False))."""

        @jax.jit
        def score(seq):
            v = reward_model.apply({"params": reward_params}, seq)
            return v[:, -1]

        return score

    # --- experience ------------------------------------------------------
    def generate_experience(self, prompts, max_new_tokens: int,
                            rng: Optional[jax.Array] = None,
                            temperature: float = 1.0) -> Dict[str, Any]:
        """Rollout + per-token stats (reference ppo loop phase 1)."""
        import time

        t0 = time.time()
        seq = self.actor.generate(prompts, max_new_tokens=max_new_tokens,
                                  temperature=temperature, rng=rng)
        seq = jax.block_until_ready(seq)
        self.generate_time = time.time() - t0
        logp, values = self._rollout_stats(self.actor.params,
                                           self.critic.params, seq)
        rewards = self.reward_fn(seq)
        B, Tm1 = logp.shape
        prompt_len = prompts.shape[1]
        # mask: positions whose ACTION (next token) was generated
        pos = jnp.arange(Tm1)[None, :]
        mask = jnp.broadcast_to(pos >= prompt_len - 1,
                                (B, Tm1)).astype(jnp.float32)
        ref_logp = (self.ref_logp_fn(seq)
                    if self.ref_logp_fn is not None else None)
        return {"seq": seq, "old_logp": logp, "old_values": values,
                "rewards": rewards, "loss_mask": mask,
                "ref_logp": ref_logp, "prompt_len": prompt_len}

    def _advantages(self, exp):
        """GAE over the generated span; the sequence reward lands on the
        final step, per-token KL penalty against the reference policy when
        one is attached (DS-Chat compute_rewards +
        get_advantages_and_returns)."""
        values = np.asarray(exp["old_values"], np.float32)
        mask = np.asarray(exp["loss_mask"], np.float32)
        B, T = values.shape
        rewards = np.zeros((B, T), np.float32)
        if self.kl_ctl and exp.get("ref_logp") is not None:
            kl = (np.asarray(exp["old_logp"], np.float32)
                  - np.asarray(exp["ref_logp"], np.float32))
            rewards -= self.kl_ctl * kl * mask
        last = mask.cumsum(1).argmax(1)               # final generated pos
        rewards[np.arange(B), last] += np.asarray(exp["rewards"], np.float32)
        adv = np.zeros((B, T), np.float32)
        gae = np.zeros((B,), np.float32)
        for t in range(T - 1, -1, -1):
            next_v = values[:, t + 1] if t + 1 < T else 0.0
            delta = rewards[:, t] + self.gamma * next_v - values[:, t]
            gae = delta + self.gamma * self.lam * gae * mask[:, t]
            adv[:, t] = gae
        returns = adv + values
        # per-batch advantage whitening over generated positions
        m = mask.sum() or 1.0
        mean = (adv * mask).sum() / m
        std = np.sqrt((np.square(adv - mean) * mask).sum() / m) + 1e-6
        adv = (adv - mean) / std
        return adv, returns

    # --- one PPO step -----------------------------------------------------
    def train_rlhf(self, exp: Dict[str, Any]) -> Dict[str, float]:
        """One actor step + one critic step from an experience batch
        (reference DeepSpeedPPOTrainer.train_rlhf)."""
        import time

        adv, returns = self._advantages(exp)
        seq = exp["seq"]
        inputs, actions = seq[:, :-1], seq[:, 1:]
        actor_batch = {"input_ids": inputs, "labels": actions,
                       "old_logp": exp["old_logp"], "advantages": adv,
                       "loss_mask": exp["loss_mask"]}
        critic_batch = {"input_ids": inputs, "returns": returns,
                        "old_values": exp["old_values"],
                        "loss_mask": exp["loss_mask"]}
        t0 = time.time()
        actor_loss = float(self.actor.train_batch(actor_batch))
        self.actor_step_time = time.time() - t0
        t0 = time.time()
        critic_loss = float(self.critic.train_batch(critic_batch))
        self.critic_step_time = time.time() - t0
        return {"actor_loss": actor_loss, "critic_loss": critic_loss,
                "reward_mean": float(np.asarray(exp["rewards"]).mean())}

    def step(self, prompts, max_new_tokens: int,
             rng: Optional[jax.Array] = None) -> Dict[str, float]:
        exp = self.generate_experience(prompts, max_new_tokens, rng=rng)
        return self.train_rlhf(exp)

    # --- checkpointing (both models — reference DS-Chat save_model) -------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None):
        import os

        self.actor.save_checkpoint(os.path.join(save_dir, "actor"), tag)
        self.critic.save_checkpoint(os.path.join(save_dir, "critic"), tag)
        log_dist(f"PPO checkpoint saved to {save_dir}", ranks=[0])

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None):
        import os

        self.actor.load_checkpoint(os.path.join(load_dir, "actor"), tag)
        self.critic.load_checkpoint(os.path.join(load_dir, "critic"), tag)
