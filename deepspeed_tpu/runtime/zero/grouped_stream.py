"""Grouped streaming offload: layer-group programs over pinned-host state.

Why this tier exists: the single-program streamed offload
(``offload_param: cpu`` + StreamedLlamaModel) keeps HBM residency at one
LAYER of weights — but XLA still accumulates the full fp32 gradient tree
on device during the backward scan, so the design caps where grads fit
HBM (~3.5B fp32 on a 15.75 GB v5e; the 7B step compile-refuses at
25.5 GB, tools/probe_7b_step_memory.py). The reference has no such cap:
its hook-driven eager backward frees each grad as it is reduced
(``runtime/zero/stage3.py:1081`` IPG reduce + partition_grads).

This tier restores that scaling: the step becomes a host-driven loop of
per-GROUP jitted programs (groups of ``grouped_stream`` layers), where

- master params, Adam moments, and gradient accumulators live as
  PINNED-HOST jax arrays — on a TPU VM that is the accelerator host's
  RAM, reached over PCIe in-graph; the orchestrating client only ever
  moves scalars,
- each group's forward/backward fetches that group's fp32 weights
  host→HBM inside the program (cast to the compute dtype in-graph),
  recomputes the group forward (block remat), runs the VJP, and writes
  the group's fp32 grads straight back to host outputs,
- boundary activations between groups are stashed in pinned host memory
  (``param_nvme``'s stash, at group granularity),
- the update is a per-leaf swapped AdamW: params+m+v+grads make one
  host→HBM→host round trip per leaf slice, so device residency during
  the whole step is ONE group's weights + grads + activations.

Same loud scope as the NVMe tier: scanned-Llama models, Adam family,
bf16/fp32, single process. Reference analogues:
``runtime/zero/parameter_offload.py:201`` (fetch/release around
submodules), ``stage_1_and_2.py:1037`` (grads accumulated in pinned CPU
buffers), ``stage3.py:1775-1835`` (per-sub-group swapped step).
"""

import json
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.zero.param_nvme import ADAM_FAMILY
from deepspeed_tpu.utils.logging import log_dist


def validate_grouped_stream_config(config, mesh) -> None:
    """Loud errors for unsupported grouped_stream combinations."""
    zc = config.zero_config
    opt = config.optimizer
    opt_name = (opt.type if opt is not None else "adamw").lower()
    if zc.stage < 3:
        raise ValueError(
            f"offload_param.grouped_stream requires zero_optimization."
            f"stage=3 (got stage={zc.stage})")
    if zc.offload_optimizer_device != "cpu":
        raise ValueError(
            "offload_param.grouped_stream requires offload_optimizer."
            "device=cpu (moments live in pinned host memory; an in-HBM "
            "optimizer would defeat the tier, and the NVMe tier has its "
            "own interpreter — zero/param_nvme.py)")
    if opt_name not in ADAM_FAMILY:
        raise ValueError(
            f"offload_param.grouped_stream uses the per-leaf swapped Adam "
            f"step and supports Adam-family optimizers only "
            f"({'/'.join(ADAM_FAMILY)}); got {opt_name!r}")
    if config.fp16.enabled:
        raise NotImplementedError(
            "offload_param.grouped_stream does not support fp16 loss "
            "scaling; use bf16 (TPU-native) or fp32")
    if jax.process_count() > 1:
        raise NotImplementedError(
            "offload_param.grouped_stream is single-host only "
            f"(jax.process_count()={jax.process_count()})")
    if mesh is not None and any(
            mesh.shape.get(ax, 1) > 1
            for ax in ("pipe", "tensor", "sequence", "expert")):
        raise NotImplementedError(
            "offload_param.grouped_stream composes with plain data-parallel "
            f"meshes only (got {dict(mesh.shape)})")
    from deepspeed_tpu.runtime.zero.param_nvme import reject_loss_rewriters

    reject_loss_rewriters(config, "offload_param.grouped_stream")


class GroupedStreamTrainer:
    """Owns pinned-host parameters/moments and the grouped streamed step.

    Duck-typed to the engine's interpreter surface (``zero/param_nvme.py``
    NVMeParamTrainer): train_batch / loss_eval / materialize / ingest /
    save_files / load_files / count / close.
    """

    def __init__(self, cfg, config, mesh, rng):
        from deepspeed_tpu.models.llama import LlamaBlock, LlamaConfig

        assert isinstance(cfg, LlamaConfig), (
            "offload_param.grouped_stream streams the scanned-Llama layer "
            f"loop; model config must be a LlamaConfig (got {type(cfg)})")
        assert cfg.scan_layers, (
            "offload_param.grouped_stream requires scan_layers=True")
        self.cfg = cfg
        self.mesh = mesh
        zc = config.zero_config
        self.L = cfg.num_layers
        self.G = int(zc.offload_param.grouped_stream)
        assert self.G >= 1, "grouped_stream must be >= 1 layer per group"
        self.bounds = [(lo, min(lo + self.G, self.L))
                       for lo in range(0, self.L, self.G)]
        self.gas = config.gradient_accumulation_steps
        self.grad_clip = float(config.gradient_clipping or 0.0)
        self.numerics = config.numerics_check_enabled
        # double-buffered group fetch (config.stream_prefetch): device
        # copies of current+next group ride the group programs; costs one
        # extra group of fp32 weights in HBM
        self.prefetch = bool(zc.offload_param.stream_prefetch)
        self._wdev: Dict[int, Any] = {}

        opt_cfg = config.optimizer
        p = dict(opt_cfg.params) if opt_cfg is not None else {}
        betas = p.get("betas", (p.get("beta1", 0.9), p.get("beta2", 0.999)))
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(p.get("eps", 1e-8))
        self.weight_decay = float(p.get("weight_decay", 0.0))
        self.base_lr = float(p.get("lr", 1e-3))
        self.count = 0
        # typed moment STORAGE (update math stays fp32 — the same contract
        # as ops/optimizers.scale_by_adam_typed); at 7B this is the knob
        # that brings host state from 108 GB (fp32 m/v) to 81 GB
        from deepspeed_tpu.ops.optimizers import _moment_dtypes

        mu_dt, nu_dt = _moment_dtypes(p)
        if nu_dt == "factored":
            raise NotImplementedError(
                "offload_param.grouped_stream stores dense per-leaf moment "
                "files; nu_dtype='factored' is a fused-engine HBM knob — "
                "host moments are already off-chip (use moment_dtype: "
                "bfloat16 to halve host state instead)")
        self.mu_dtype = mu_dt or jnp.float32
        self.nu_dtype = nu_dt or jnp.float32
        # grad STORAGE dtype between backward and the group update
        # (data_types.grad_accum_dtype — same contract as the fused
        # engine): bf16 halves the grad leg of the tier's host traffic
        # (device→host writeback after each group vjp, host→device fetch
        # into the update program, and the gas accumulation round trips);
        # update math upcasts to fp32. At gas>1 the accumulator also
        # runs at this dtype — the documented fidelity trade.
        self.grad_dtype = (jnp.bfloat16
                           if config.grad_accum_dtype == "bfloat16"
                           else jnp.float32)

        from deepspeed_tpu.runtime.zero.stages import _supports_host_memory

        host_ok = _supports_host_memory(mesh)
        kind = "pinned_host" if host_ok else "device"
        self._host = NamedSharding(mesh, PartitionSpec(), memory_kind=kind)
        self._dev = NamedSharding(mesh, PartitionSpec())
        # jit with host-annotated OUTPUTS works on TPU; the virtual CPU
        # backend rejects it (same RAM either way) — mirror _sharded_init
        self._out_host = self._host if (host_ok and
                                        mesh.devices.flat[0].platform
                                        == "tpu") else self._dev

        self.block = LlamaBlock(cfg)
        self._build_programs()
        self._init_state(rng)
        log_dist(
            f"grouped-stream offload: {self.L} layers in "
            f"{len(self.bounds)} groups of <= {self.G} "
            f"(host kind: {kind}; moments "
            f"{self.mu_dtype.__name__}/{self.nu_dtype.__name__})",
            ranks=[0])

    # --- programs --------------------------------------------------------
    def _build_programs(self) -> None:
        cfg = self.cfg
        from deepspeed_tpu.models.llama import _remat_policy
        from deepspeed_tpu.models.llama import loss_fn as lm_loss
        from deepspeed_tpu.models.transformer import RMSNorm, make_causal_mask

        block = self.block
        norm = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype)
        dev = self._dev
        out_host = self._out_host

        def fetch(tree):
            return jax.tree_util.tree_map(
                lambda w: jax.device_put(w, dev), tree)

        def emb_fwd(rest, ids):
            r = fetch(rest)
            return r["embed_tokens"]["embedding"][ids].astype(cfg.dtype)

        def group_chain(wg_dev, x, pos):
            mask = make_causal_mask(x.shape[-2])

            def body(h, wslice):
                return block.apply({"params": wslice}, h, mask, pos), None

            if cfg.remat:
                body = jax.checkpoint(
                    body, policy=_remat_policy(cfg.remat_policy))
            x, _ = jax.lax.scan(body, x, wg_dev)
            return x

        def group_fwd(wg, x, pos):
            return group_chain(fetch(wg), x, pos)

        # --- prefetch variants (offload_param.stream_prefetch) ----------
        # The compute weights arrive ALREADY device-resident (wg_dev) and
        # the program additionally returns a device copy of the NEXT
        # group's host weights. That copy has no data dependence on the
        # compute, so XLA's latency-hiding scheduler runs the host→HBM
        # DMA underneath the group's scan — the overlapped sub-group
        # pipeline of the reference (stage3.py:1775-1835), expressed as
        # program outputs instead of CUDA streams.
        def group_fwd_dev(wg_dev, x, pos):
            return group_chain(wg_dev, x, pos)

        def group_fwd_dev_pf(wg_dev, wg_next, x, pos):
            return group_chain(wg_dev, x, pos), fetch(wg_next)

        def head_loss(rest, x, labels):
            r = fetch(rest)
            xn = norm.apply({"params": r["final_norm"]}, x)
            if cfg.tie_embeddings:
                emb = r["embed_tokens"]["embedding"].astype(cfg.dtype)
                logits = jnp.dot(xn.astype(jnp.float32).astype(cfg.dtype),
                                 emb.T)
            else:
                k = r["lm_head"]["kernel"].astype(cfg.dtype)
                logits = jnp.dot(xn.astype(cfg.dtype), k)
            return lm_loss(logits.astype(jnp.float32), labels)

        gdt = self.grad_dtype

        def to_gdt(tree):
            # grad storage dtype (data_types.grad_accum_dtype): applied at
            # the vjp output, BEFORE the device→host writeback — the cast
            # is what halves the grad leg of the host traffic
            if gdt == jnp.float32:
                return tree
            return jax.tree_util.tree_map(lambda g: g.astype(gdt), tree)

        def head_vjp(rest, x, labels):
            loss, pull = jax.vjp(
                lambda r, h: head_loss(r, h, labels), rest, x)
            drest, dx = pull(jnp.ones((), jnp.float32))
            return loss, dx, to_gdt(drest)

        def group_vjp(wg, x, pos, dy):
            _, pull = jax.vjp(
                lambda w, h: group_chain(fetch(w), h, pos), wg, x)
            dw, dx = pull(dy)
            return dx, to_gdt(dw)

        def acc_tree(prev, new):
            # in-graph host fetch + add; result back to host
            return jax.tree_util.tree_map(
                lambda a, b: jax.device_put(a, dev) + b, prev, new)

        def group_vjp_acc(wg, x, pos, dy, gprev):
            dx, dw = group_vjp(wg, x, pos, dy)
            return dx, acc_tree(gprev, dw)

        def head_vjp_acc(rest, x, labels, gprev):
            loss, dx, drest = head_vjp(rest, x, labels)
            return loss, dx, acc_tree(gprev, drest)

        # prefetch-path backward: vjp w.r.t. the DEVICE weight copy (same
        # math — the fetch is a pure copy outside the differentiated
        # function), plus the next group's prefetch riding alongside
        def group_vjp_dev(wg_dev, x, pos, dy):
            _, pull = jax.vjp(
                lambda w, h: group_chain(w, h, pos), wg_dev, x)
            dw, dx = pull(dy)
            return dx, to_gdt(dw)

        def group_vjp_dev_pf(wg_dev, x, pos, dy, wg_next):
            dx, dw = group_vjp_dev(wg_dev, x, pos, dy)
            return dx, dw, fetch(wg_next)

        def group_vjp_dev_acc(wg_dev, x, pos, dy, gprev):
            dx, dw = group_vjp_dev(wg_dev, x, pos, dy)
            return dx, acc_tree(gprev, dw)

        def group_vjp_dev_acc_pf(wg_dev, x, pos, dy, gprev, wg_next):
            dx, dw = group_vjp_dev_acc(wg_dev, x, pos, dy, gprev)
            return dx, dw, fetch(wg_next)

        b1, b2, eps, wd = self.b1, self.b2, self.eps, self.weight_decay

        def adam_leaf(pv, m, v, g, lr, clip_scale, t, inv_gas):
            pv, m, v, g = (jax.device_put(a, dev) for a in (pv, m, v, g))
            mdt, vdt = m.dtype, v.dtype
            g = g.astype(jnp.float32) * inv_gas * clip_scale
            m = b1 * m.astype(jnp.float32) + (1 - b1) * g
            v = b2 * v.astype(jnp.float32) + (1 - b2) * jnp.square(g)
            mhat = m / (1 - b1 ** t)
            vhat = v / (1 - b2 ** t)
            step = mhat / (jnp.sqrt(vhat) + eps)
            if wd:
                step = step + wd * pv.astype(jnp.float32)
            new_p = (pv.astype(jnp.float32) - lr * step).astype(pv.dtype)
            return new_p, m.astype(mdt), v.astype(vdt)

        def upd_group(wtree, mtree, vtree, gtree, lr, clip_scale, t,
                      inv_gas):
            """Whole-group Adam step as ONE program: the per-leaf
            fetch→update→writeback chains are independent, so XLA's
            scheduler overlaps leaf i+1's host→HBM transfer with leaf i's
            update math — where the old per-leaf jit paid a serialized
            round trip per leaf (VERDICT r4 #3). Device residency stays
            one leaf's worth per in-flight chain; inputs live in host
            memory until their chain fetches them."""
            wl, tdef = jax.tree_util.tree_flatten(wtree)
            ml = jax.tree_util.tree_leaves(mtree)
            vl = jax.tree_util.tree_leaves(vtree)
            gl = jax.tree_util.tree_leaves(gtree)
            outs = [adam_leaf(pw, pm, pv, pg, lr, clip_scale, t, inv_gas)
                    for pw, pm, pv, pg in zip(wl, ml, vl, gl)]
            unf = jax.tree_util.tree_unflatten
            return (unf(tdef, [o[0] for o in outs]),
                    unf(tdef, [o[1] for o in outs]),
                    unf(tdef, [o[2] for o in outs]))

        host3 = (out_host, out_host, out_host)
        self._jit_emb_fwd = jax.jit(emb_fwd)
        self._jit_group_fwd = jax.jit(group_fwd)
        self._jit_head_loss = jax.jit(head_loss)
        self._jit_head_vjp = jax.jit(
            head_vjp, out_shardings=(dev, dev, out_host))
        self._jit_group_vjp = jax.jit(
            group_vjp, out_shardings=(dev, out_host))
        self._jit_group_vjp_acc = jax.jit(
            group_vjp_acc, out_shardings=(dev, out_host))
        self._jit_head_vjp_acc = jax.jit(
            head_vjp_acc, out_shardings=(dev, dev, out_host))
        self._jit_upd_group = jax.jit(upd_group, out_shardings=host3)
        self._jit_fetch = jax.jit(fetch, out_shardings=dev)
        self._jit_group_fwd_dev = jax.jit(group_fwd_dev)
        self._jit_group_fwd_dev_pf = jax.jit(
            group_fwd_dev_pf, out_shardings=(dev, dev))
        self._jit_group_vjp_dev = jax.jit(
            group_vjp_dev, out_shardings=(dev, out_host))
        self._jit_group_vjp_dev_pf = jax.jit(
            group_vjp_dev_pf, out_shardings=(dev, out_host, dev))
        self._jit_group_vjp_dev_acc = jax.jit(
            group_vjp_dev_acc, out_shardings=(dev, out_host))
        self._jit_group_vjp_dev_acc_pf = jax.jit(
            group_vjp_dev_acc_pf, out_shardings=(dev, out_host, dev))

        def emb_vjp_acc(rest, ids, dx, gprev):
            _, pull = jax.vjp(lambda r: emb_fwd(r, ids), rest)
            (drest,) = pull(dx)
            return acc_tree(gprev, to_gdt(drest))

        self._jit_emb_vjp_acc = jax.jit(emb_vjp_acc, out_shardings=out_host)

    # --- state -----------------------------------------------------------
    def _init_state(self, rng) -> None:
        """Per-group streamed init: each group's params materialize on
        device ([G, ...] — fits), land pinned-host, and are freed before
        the next group exists. The full tree never exists in HBM (the
        single-program init is exactly what OOMs at 7B)."""
        from deepspeed_tpu.models.transformer import make_causal_mask

        cfg = self.cfg
        S0 = min(4, cfg.max_seq_len)
        x0 = jnp.zeros((1, S0, cfg.hidden_size), cfg.dtype)
        pos0 = jnp.arange(S0, dtype=jnp.int32)[None, :]
        mask0 = make_causal_mask(S0)

        group_init = jax.jit(
            lambda ks: jax.vmap(
                lambda k: self.block.init(k, x0, mask0, pos0)["params"])(ks),
            out_shardings=self._out_host)
        keys = jax.random.split(rng, self.L + 1)
        self._w: List[Any] = []
        self._mu: List[Any] = []
        self._nu: List[Any] = []
        mu_dt, nu_dt = self.mu_dtype, self.nu_dtype
        zeros_mu = jax.jit(
            lambda t: jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape, mu_dt), t),
            out_shardings=self._out_host)
        zeros_nu = jax.jit(
            lambda t: jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape, nu_dt), t),
            out_shardings=self._out_host)
        for lo, hi in self.bounds:
            wg = group_init(keys[lo:hi])
            self._w.append(wg)
            self._mu.append(zeros_mu(wg))
            self._nu.append(zeros_nu(wg))

        def init_rest(k):
            import flax.linen as nn

            k1, k2 = jax.random.split(k)
            embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                             param_dtype=jnp.float32, dtype=cfg.dtype)
            rest = {
                "embed_tokens": embed.init(
                    k1, jnp.zeros((1, 1), jnp.int32))["params"],
                "final_norm": {"scale": jnp.ones((cfg.hidden_size,),
                                                 jnp.float32)},
            }
            if not cfg.tie_embeddings:
                head = nn.Dense(cfg.vocab_size, use_bias=False,
                                dtype=cfg.dtype, param_dtype=jnp.float32)
                rest["lm_head"] = head.init(
                    k2, jnp.zeros((1, 1, cfg.hidden_size), cfg.dtype)
                )["params"]
            return rest

        self._rest = jax.jit(init_rest, out_shardings=self._out_host)(
            keys[self.L])
        self._mu_rest = zeros_mu(self._rest)
        self._nu_rest = zeros_nu(self._rest)

    # --- stash (shared with the NVMe tier) --------------------------------
    from deepspeed_tpu.runtime.zero.param_nvme import (
        stash_to_host as _stash_fn, unstash_from_host as _unstash_fn,
    )
    _stash = staticmethod(_stash_fn)
    _unstash = staticmethod(_unstash_fn)

    # --- step ------------------------------------------------------------
    def train_batch(self, batch: Dict[str, Any], lr: Optional[float] = None):
        ids_all, labels_all = batch["input_ids"], batch["labels"]
        gas = int(ids_all.shape[0])
        pos_all = batch.get("positions")
        nG = len(self.bounds)

        g_groups: List[Any] = [None] * nG
        g_rest = None
        loss_acc = None
        # prefetch live-set: gi -> device copy of group gi's weights. At
        # most TWO groups live (current + next); entries outlive their
        # pop() until the consuming program completes (XLA holds buffer
        # refs), so eviction here is about not keeping a THIRD group
        wdev = self._wdev if self.prefetch else None

        for g in range(gas):
            ids, labels = jnp.asarray(ids_all[g]), jnp.asarray(labels_all[g])
            S = int(ids.shape[-1])
            pos = (jnp.asarray(pos_all[g]) if pos_all is not None
                   else jnp.arange(S, dtype=jnp.int32)[None, :])
            x = self._jit_emb_fwd(self._rest, ids)
            stash = []
            if not self.prefetch:
                for gi in range(nG):
                    stash.append(self._stash(x))
                    x = self._jit_group_fwd(self._w[gi], x, pos)
            else:
                if 0 not in wdev:           # cold start, unoverlapped
                    wdev[0] = self._jit_fetch(self._w[0])
                for gi in range(nG):
                    stash.append(self._stash(x))
                    nxt = gi + 1
                    if nxt < nG and nxt not in wdev:
                        x, wdev[nxt] = self._jit_group_fwd_dev_pf(
                            wdev[gi], self._w[nxt], x, pos)
                    else:
                        x = self._jit_group_fwd_dev(wdev[gi], x, pos)
                    if gi != nG - 1:
                        # backward re-prefetches in reverse order; keep
                        # only the LAST group across the turn-around
                        wdev.pop(gi, None)
            if g_rest is None:
                loss, dx, g_rest = self._jit_head_vjp(self._rest, x, labels)
            else:
                loss, dx, g_rest = self._jit_head_vjp_acc(
                    self._rest, x, labels, g_rest)
            loss_acc = loss if loss_acc is None else loss_acc + loss
            for gi in reversed(range(nG)):
                x_in = self._unstash(stash[gi])
                if not self.prefetch:
                    if g_groups[gi] is None:
                        dx, g_groups[gi] = self._jit_group_vjp(
                            self._w[gi], x_in, pos, dx)
                    else:
                        dx, g_groups[gi] = self._jit_group_vjp_acc(
                            self._w[gi], x_in, pos, dx, g_groups[gi])
                    continue
                prv = gi - 1
                pf = prv >= 0 and prv not in wdev
                if g_groups[gi] is None:
                    if pf:
                        dx, g_groups[gi], wdev[prv] = \
                            self._jit_group_vjp_dev_pf(
                                wdev[gi], x_in, pos, dx, self._w[prv])
                    else:
                        dx, g_groups[gi] = self._jit_group_vjp_dev(
                            wdev[gi], x_in, pos, dx)
                else:
                    if pf:
                        dx, g_groups[gi], wdev[prv] = \
                            self._jit_group_vjp_dev_acc_pf(
                                wdev[gi], x_in, pos, dx, g_groups[gi],
                                self._w[prv])
                    else:
                        dx, g_groups[gi] = self._jit_group_vjp_dev_acc(
                            wdev[gi], x_in, pos, dx, g_groups[gi])
                if gi != 0:
                    # group 0 stays live for the next micro-batch's fwd
                    wdev.pop(gi, None)
            # embedding grads accumulate into the same rest tree the head
            # already populated (zeros elsewhere from the vjp)
            g_rest = self._jit_emb_vjp_acc(self._rest, ids, dx, g_rest)

        # global norm over ACCUMULATED grads (scaled by 1/gas to match the
        # fused engine's mean-over-micro-batches semantics)
        inv = 1.0 / gas
        sq_total = 0.0
        finite = True
        sqfn = getattr(self, "_jit_sq", None)
        if sqfn is None:
            dev = self._dev

            def sq_and_finite(tree):
                leaves = [jax.device_put(l, dev).astype(jnp.float32)
                          for l in jax.tree_util.tree_leaves(tree)]
                sq = sum(jnp.sum(jnp.square(l)) for l in leaves)
                ok = jnp.asarray(True)
                for l in leaves:
                    ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(l)))
                return sq, ok

            sqfn = self._jit_sq = jax.jit(sq_and_finite)
        for tree in g_groups + [g_rest]:
            sq, ok = sqfn(tree)
            sq_total += float(sq)
            if self.numerics:
                finite = finite and bool(ok)
        gnorm = float(np.sqrt(sq_total)) * inv
        loss = float(np.asarray(loss_acc)) / gas
        if self.numerics:
            finite = finite and bool(np.isfinite(loss)) \
                and bool(np.isfinite(gnorm))
        else:
            finite = True
        if finite:
            clip = (min(1.0, self.grad_clip / (gnorm + 1e-6))
                    if self.grad_clip > 0 else 1.0)
            self._apply_updates(g_groups, g_rest, clip, lr, inv)
        return jnp.asarray(loss, jnp.float32), jnp.asarray(finite)

    def _apply_updates(self, g_groups, g_rest, clip_scale, lr, inv) -> None:
        self.count += 1
        # weights are about to change: any prefetched device copies from
        # the step are stale
        self._wdev.clear()
        t = jnp.asarray(self.count, jnp.float32)
        lr_v = jnp.asarray(self.base_lr if lr is None else lr, jnp.float32)
        cs = jnp.asarray(clip_scale, jnp.float32)
        inv_v = jnp.asarray(inv, jnp.float32)

        def upd(wtree, mtree, vtree, gtree):
            # one program per GROUP (not per leaf): XLA overlaps the
            # independent leaf fetch→update→writeback chains
            return self._jit_upd_group(wtree, mtree, vtree, gtree,
                                       lr_v, cs, t, inv_v)

        for gi in range(len(self.bounds)):
            self._w[gi], self._mu[gi], self._nu[gi] = upd(
                self._w[gi], self._mu[gi], self._nu[gi], g_groups[gi])
        self._rest, self._mu_rest, self._nu_rest = upd(
            self._rest, self._mu_rest, self._nu_rest, g_rest)

    # --- eval / interop ---------------------------------------------------
    def loss_eval(self, batch: Dict[str, Any]):
        ids, labels = jnp.asarray(batch["input_ids"]), \
            jnp.asarray(batch["labels"])
        S = int(ids.shape[-1])
        pos = batch.get("positions")
        pos = (jnp.asarray(pos) if pos is not None
               else jnp.arange(S, dtype=jnp.int32)[None, :])
        x = self._jit_emb_fwd(self._rest, ids)
        for gi in range(len(self.bounds)):
            x = self._jit_group_fwd(self._w[gi], x, pos)
        return self._jit_head_loss(self._rest, x, labels)

    def materialize(self) -> Dict[str, Any]:
        """Full host-numpy parameter pytree in the engine's stacked layout
        (pulls everything to the client — tests/export only)."""
        slices = [jax.tree_util.tree_map(np.asarray, w) for w in self._w]
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *slices)
        out = {k: jax.tree_util.tree_map(np.asarray, v)
               for k, v in self._rest.items()}
        out["blocks"] = {"block": stacked}
        return out

    def ingest(self, params: Dict[str, Any]) -> None:
        self._wdev.clear()
        stacked = params["blocks"]["block"]
        for gi, (lo, hi) in enumerate(self.bounds):
            self._w[gi] = jax.tree_util.tree_map(
                lambda a, cur: jax.device_put(
                    np.asarray(a)[lo:hi], cur.sharding),
                stacked, self._w[gi])
        self._rest = jax.tree_util.tree_map(
            lambda a, cur: jax.device_put(np.asarray(a), cur.sharding),
            {k: v for k, v in params.items() if k != "blocks"}, self._rest)

    # --- checkpoint -------------------------------------------------------
    def save_files(self, dst_dir: str) -> None:
        os.makedirs(dst_dir, exist_ok=True)

        def dump(name, tree):
            for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
                np.asarray(leaf, np.float32).tofile(
                    os.path.join(dst_dir, f"{name}.{i}.bin"))

        for gi in range(len(self.bounds)):
            dump(f"gs_w{gi:03d}", self._w[gi])
            dump(f"gs_m{gi:03d}", self._mu[gi])
            dump(f"gs_v{gi:03d}", self._nu[gi])
        dump("gs_rest_w", self._rest)
        dump("gs_rest_m", self._mu_rest)
        dump("gs_rest_v", self._nu_rest)
        with open(os.path.join(dst_dir, "grouped_stream_meta.json"),
                  "w") as f:
            json.dump({"num_layers": self.L, "group": self.G,
                       "count": self.count,
                       "tie_embeddings": self.cfg.tie_embeddings}, f)

    def load_files(self, src_dir: str,
                   load_optimizer_states: bool = True) -> None:
        self._wdev.clear()
        with open(os.path.join(src_dir, "grouped_stream_meta.json")) as f:
            meta = json.load(f)
        if meta["num_layers"] != self.L or meta["group"] != self.G:
            raise ValueError(
                f"grouped-stream checkpoint is {meta['num_layers']} layers "
                f"/ group {meta['group']}; engine has {self.L}/{self.G}")
        if ("tie_embeddings" in meta
                and meta["tie_embeddings"] != self.cfg.tie_embeddings):
            # without this the mismatch surfaces later as an obscure
            # np.fromfile/reshape or missing-file error on the rest-tree
            raise ValueError(
                f"grouped-stream checkpoint was saved with tie_embeddings="
                f"{meta['tie_embeddings']}; engine config has "
                f"tie_embeddings={self.cfg.tie_embeddings}")

        def adopt(name, tree):
            leaves, tdef = jax.tree_util.tree_flatten(tree)
            out = []
            for i, leaf in enumerate(leaves):
                arr = np.fromfile(
                    os.path.join(src_dir, f"{name}.{i}.bin"),
                    dtype=np.float32).reshape(leaf.shape)
                arr = arr.astype(leaf.dtype)    # typed-moment storage
                out.append(jax.device_put(arr, leaf.sharding))
            return jax.tree_util.tree_unflatten(tdef, out)

        for gi in range(len(self.bounds)):
            self._w[gi] = adopt(f"gs_w{gi:03d}", self._w[gi])
            if load_optimizer_states:
                self._mu[gi] = adopt(f"gs_m{gi:03d}", self._mu[gi])
                self._nu[gi] = adopt(f"gs_v{gi:03d}", self._nu[gi])
        self._rest = adopt("gs_rest_w", self._rest)
        if load_optimizer_states:
            self._mu_rest = adopt("gs_rest_m", self._mu_rest)
            self._nu_rest = adopt("gs_rest_v", self._nu_rest)
            self.count = int(meta["count"])

    def close(self) -> None:
        self._w = self._mu = self._nu = []
        self._wdev.clear()
