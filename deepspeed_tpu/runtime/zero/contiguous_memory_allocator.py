"""Contiguous host-memory arena with defragmentation.

Reference: ``deepspeed/runtime/zero/contiguous_memory_allocator.py`` (a
torch-tensor arena that hands out narrowed views of one flat buffer,
tracks assignments, and compacts live tensors when fragmentation blocks an
allocation) and the swap-buffer pools of ``runtime/swap_tensor/utils.py``.

On TPU, device memory belongs to XLA — a user-level device allocator would
fight the compiler. What still needs explicit contiguous management is the
*host* side: staging buffers for NVMe swap (AIO wants stable, ideally
pinned, addresses) and host-RAM offload tiers. This arena provides that:

    arena = ContiguousMemoryAllocator(2 << 30, np.dtype("float32"))
    h = arena.allocate(numel)        # Allocation handle
    h.view()[:] = ...                # numpy view into the flat buffer
    arena.release(h)

``allocate`` compacts live allocations toward offset 0 when free space is
sufficient but fragmented (the reference's defragmentation pass). Handles
stay valid across compaction — ``view()`` re-resolves the current offset;
data is memmove'd by the compactor.
"""

import threading
from typing import Dict, List, Optional

import numpy as np


class Allocation:
    """A live region of the arena. ``view()`` re-resolves after defrag."""

    __slots__ = ("_arena", "id", "numel")

    def __init__(self, arena: "ContiguousMemoryAllocator", alloc_id: int,
                 numel: int):
        self._arena = arena
        self.id = alloc_id
        self.numel = numel

    def view(self) -> np.ndarray:
        return self._arena._view(self.id)

    @property
    def offset(self) -> int:
        return self._arena._offset(self.id)


class ContiguousMemoryAllocator:
    def __init__(self, size: int, dtype=np.float32):
        """size: capacity in elements of ``dtype``."""
        self.dtype = np.dtype(dtype)
        self.buffer = np.empty(size, self.dtype)
        self.size = size
        self._lock = threading.Lock()
        self._next_id = 0
        # id -> (offset, numel), kept sorted by offset on compaction
        self._live: Dict[int, List[int]] = {}
        self.total_free = size
        self.largest_contiguous = size
        self.max_allocated = 0

    # -- public ----------------------------------------------------------

    def allocate(self, numel: int, allow_defrag: bool = True) -> Allocation:
        """Reserve ``numel`` elements; defragments if free-but-fragmented
        (reference ``allocate_tensor`` semantics, incl. the assert that
        total free space suffices). Callers with async I/O in flight into
        existing views pass ``allow_defrag=False`` — compaction memmoves
        live data, which would race the DMA."""
        with self._lock:
            if numel > self.total_free:
                raise MemoryError(
                    f"arena exhausted: need {numel}, free {self.total_free} "
                    f"of {self.size}")
            if self._largest_hole() < numel:
                if not allow_defrag:
                    raise MemoryError(
                        f"arena fragmented: need {numel} contiguous, largest "
                        f"hole {self._largest_hole()} (defrag disallowed)")
                self._defragment()
            off = self._find_hole(numel)
            assert off is not None, "defragment failed to open a hole"
            alloc_id = self._next_id
            self._next_id += 1
            self._live[alloc_id] = [off, numel]
            self.total_free -= numel
            self.max_allocated = max(self.max_allocated,
                                     self.size - self.total_free)
            self.largest_contiguous = self._largest_hole()
            return Allocation(self, alloc_id, numel)

    def release(self, alloc: Allocation) -> None:
        with self._lock:
            entry = self._live.pop(alloc.id, None)
            if entry is None:
                return
            self.total_free += entry[1]
            self.largest_contiguous = self._largest_hole()

    def release_all(self) -> None:
        with self._lock:
            self._live.clear()
            self.total_free = self.size
            self.largest_contiguous = self.size

    def print_allocation(self, resolution: int = 200) -> str:
        """Occupancy map string (reference ``print_allocation``).
        Locked: iterating ``_live`` against a concurrent
        defrag/allocate would raise (dict mutated mid-iteration) or
        render torn offsets."""
        cells = ["."] * resolution
        with self._lock:
            live = list(self._live.values())
        for off, numel in live:
            lo = off * resolution // self.size
            hi = max(lo + 1, (off + numel) * resolution // self.size)
            for i in range(lo, min(hi, resolution)):
                cells[i] = "#"
        return "".join(cells)

    # -- internals -------------------------------------------------------

    def _view(self, alloc_id: int) -> np.ndarray:
        # under the lock: a concurrent allocate(allow_defrag=True) may
        # memmove live regions, so the offset must be read atomically with
        # respect to compaction. NOTE the returned view's base can still be
        # invalidated by a LATER defrag — threads holding views across
        # allocate() calls must use allow_defrag=False (the swapper does).
        with self._lock:
            off, numel = self._live[alloc_id]
            return self.buffer[off:off + numel]

    def _offset(self, alloc_id: int) -> int:
        with self._lock:
            return self._live[alloc_id][0]

    def _holes(self):
        """Yield (offset, length) free runs in offset order."""
        pos = 0
        for off, numel in sorted(self._live.values()):
            if off > pos:
                yield pos, off - pos
            pos = max(pos, off + numel)
        if pos < self.size:
            yield pos, self.size - pos

    def _largest_hole(self) -> int:
        return max((ln for _, ln in self._holes()), default=0)

    def _find_hole(self, numel: int) -> Optional[int]:
        for off, ln in self._holes():
            if ln >= numel:
                return off
        return None

    def _defragment(self) -> None:
        """Compact live regions toward offset 0 (stable order). Handle
        views re-resolve, so callers are unaffected."""
        pos = 0
        for alloc_id, (off, numel) in sorted(self._live.items(),
                                             key=lambda kv: kv[1][0]):
            if off != pos:
                # overlapping-safe: destination is always <= source
                self.buffer[pos:pos + numel] = self.buffer[off:off + numel]
                self._live[alloc_id][0] = pos
            pos += numel
