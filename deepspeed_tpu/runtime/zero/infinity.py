"""ZeRO-Infinity: optimizer states live on NVMe between (and during) steps.

TPU-native analogue of the reference's per-sub-group swapped optimizer step
(``deepspeed/runtime/zero/stage3.py:1775-1835``: swap-in sub-group i →
unscale/clip → ``_optimizer_step`` → swap-out), built on
:class:`~deepspeed_tpu.runtime.swap_tensor.swapper.PipelinedOptimizerSwapper`
so sub-group i+1's read and i-1's write-back overlap sub-group i's device
update — the reference's pipelined_optimizer_swapper.py behavior.

The fused single-program train step cannot read disk mid-program, so the
NVMe path splits the step: one jitted grads program (all GAS micro-batches,
global-norm + finiteness in-graph), then a host loop of jitted per-sub-group
Adam updates whose m/v arrive from and return to NVMe. Only one sub-group's
fp32 state is device-resident at a time (``sub_group_size`` elements), which
is the whole point: HBM holds params + grads + one group's m/v instead of
the full optimizer state.

Like the reference (which pairs ZeRO-Infinity with DeepSpeedCPUAdam /
FusedAdam), the swapped update is Adam-family only; other optimizers raise
at engine init instead of silently ignoring the offload config.
"""

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.swap_tensor.swapper import PipelinedOptimizerSwapper
from deepspeed_tpu.utils.logging import log_dist

ADAM_FAMILY = ("adam", "adamw", "fusedadam")


def validate_nvme_config(config) -> None:
    """Loud errors for unsupported ZeRO-Infinity combinations (the reference
    silently requires these; VERDICT r1 flagged silent no-ops as worse than
    errors)."""
    zc = config.zero_config
    if zc.offload_param_device == "nvme":
        raise NotImplementedError(
            "offload_param.device=nvme (parameter NVMe offload) is not "
            "implemented; optimizer-state NVMe offload "
            "(offload_optimizer.device=nvme) is")
    if zc.offload_optimizer_device != "nvme":
        return
    if zc.stage < 1:
        raise ValueError(
            "offload_optimizer.device=nvme requires zero_optimization.stage "
            f">= 1 (got stage={zc.stage})")
    if zc.offload_optimizer.nvme_path is None:
        raise ValueError(
            "offload_optimizer.device=nvme requires offload_optimizer."
            "nvme_path (the swap directory)")
    opt = config.optimizer
    name = (opt.type if opt is not None else "adamw").lower()
    if name not in ADAM_FAMILY:
        raise ValueError(
            f"offload_optimizer.device=nvme supports Adam-family optimizers "
            f"only ({'/'.join(ADAM_FAMILY)}) — the reference pairs "
            f"ZeRO-Infinity with DeepSpeedCPUAdam/FusedAdam; got {name!r}")


class NVMeOptimizerStates:
    """Owns grouping, the swapper, and the per-group jitted AdamW update.

    Parameters/gradients stay device-resident; m/v stream NVMe→HBM→NVMe per
    sub-group. State files hold the gathered (unsharded) arrays — per-shard
    files are a multi-host extension.
    """

    def __init__(self, params, plan, mesh, config):
        zc = config.zero_config
        opt_cfg = config.optimizer
        p = dict(opt_cfg.params) if opt_cfg is not None else {}
        betas = p.get("betas", (p.get("beta1", 0.9), p.get("beta2", 0.999)))
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(p.get("eps", 1e-8))
        self.weight_decay = float(p.get("weight_decay", 0.0))
        self.base_lr = float(p.get("lr", 1e-3))
        self.count = 0
        self.mesh = mesh

        flat, self.treedef = jax.tree_util.tree_flatten(params)
        self.n_leaves = len(flat)
        self._shapes = [tuple(l.shape) for l in flat]
        self._param_shardings = jax.tree_util.tree_leaves(
            plan.param_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        opt_spec_leaves = jax.tree_util.tree_leaves(
            plan.opt_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        self._opt_shardings = [NamedSharding(mesh, s) for s in opt_spec_leaves]

        # greedy size-bounded grouping (reference sub_group_size semantics,
        # zero/config.py: sub_group_size elements per swap/step granule)
        limit = max(int(zc.sub_group_size), 1)
        self.groups: List[List[int]] = []
        cur, cur_size = [], 0
        for i, leaf in enumerate(flat):
            n = int(np.prod(leaf.shape)) if hasattr(leaf, "shape") else 1
            if cur and cur_size + n > limit:
                self.groups.append(cur)
                cur, cur_size = [], 0
            cur.append(i)
            cur_size += n
        if cur:
            self.groups.append(cur)

        swap_dir = zc.offload_optimizer.nvme_path
        self.swapper = PipelinedOptimizerSwapper(str(swap_dir))
        for gi, idxs in enumerate(self.groups):
            zeros = {str(i): np.zeros(flat[i].shape, np.float32)
                     for i in idxs}
            self.swapper.offload(self._name(gi), {"mu": zeros,
                                                  "nu": dict(zeros)})
        log_dist(
            f"ZeRO-Infinity: {self.n_leaves} param tensors in "
            f"{len(self.groups)} NVMe sub-groups (sub_group_size={limit}) "
            f"at {swap_dir}", ranks=[0])

        b1, b2, eps, wd = self.b1, self.b2, self.eps, self.weight_decay

        # Decoupled weight decay matching the fused path exactly: both the
        # optax adamw chain AND build_optimizer's plain-adam chain
        # (scale_by_adam → add_decayed_weights → lr) keep wd OUT of the
        # moment estimates — so the NVMe and fused engines produce the same
        # trajectory for the same config. No donation: the inputs are the
        # engine's live param leaves, and a mid-step swap IOError must not
        # leave self.params referencing deleted buffers.
        @jax.jit
        def group_update(params_g, mu_g, nu_g, grads_g, lr, clip_scale, t):
            def upd(p, mu, nu, g):
                g = g.astype(jnp.float32) * clip_scale
                mu = b1 * mu + (1 - b1) * g
                nu = b2 * nu + (1 - b2) * jnp.square(g)
                mhat = mu / (1 - b1 ** t)
                nhat = nu / (1 - b2 ** t)
                step = mhat / (jnp.sqrt(nhat) + eps)
                if wd:
                    step = step + wd * p.astype(jnp.float32)
                return (p.astype(jnp.float32) - lr * step).astype(p.dtype), \
                    mu, nu

            out = {k: upd(params_g[k], mu_g[k], nu_g[k], grads_g[k])
                   for k in params_g}
            return ({k: v[0] for k, v in out.items()},
                    {k: v[1] for k, v in out.items()},
                    {k: v[2] for k, v in out.items()})

        self._group_update = group_update

    def _name(self, gi: int) -> str:
        return f"opt_group{gi}"

    def step(self, params, grads, clip_scale, lr: Optional[float] = None):
        """One optimizer step: pipelined swap-in → jitted update → swap-out
        per sub-group (reference stage3.py:1799-1815 loop). Returns updated
        params (same sharded pytree).

        A swap IOError mid-loop aborts the step with the caller's params
        intact (nothing is donated), but already-released groups keep their
        updated on-disk m/v — recovery after a disk failure is checkpoint
        reload, as in the reference."""
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        assert len(flat_p) == self.n_leaves, "param tree changed shape"
        self.count += 1
        t = jnp.asarray(self.count, jnp.float32)
        lr = jnp.asarray(self.base_lr if lr is None else lr, jnp.float32)
        clip_scale = jnp.asarray(clip_scale, jnp.float32)

        sw = self.swapper
        sw.prefetch(self._name(0))
        for gi, idxs in enumerate(self.groups):
            # host copies; the ONE host→device transfer below places each
            # leaf directly in its sharded layout (no unsharded staging
            # replica on the default device)
            state = sw.acquire(self._name(gi), device_put=False)
            if gi + 1 < len(self.groups):
                sw.prefetch(self._name(gi + 1))
            keys = [str(i) for i in idxs]
            params_g = {k: flat_p[int(k)] for k in keys}
            grads_g = {k: flat_g[int(k)] for k in keys}
            mu_g = {k: jax.device_put(state["mu"][k],
                                      self._opt_shardings[int(k)])
                    for k in keys}
            nu_g = {k: jax.device_put(state["nu"][k],
                                      self._opt_shardings[int(k)])
                    for k in keys}
            new_p, new_mu, new_nu = self._group_update(
                params_g, mu_g, nu_g, grads_g, lr, clip_scale, t)
            for k in keys:
                flat_p[int(k)] = new_p[k]
            sw.release(self._name(gi),
                       {"mu": {k: np.asarray(v) for k, v in new_mu.items()},
                        "nu": {k: np.asarray(v) for k, v in new_nu.items()}})
        sw.flush()
        return jax.tree_util.tree_unflatten(treedef, flat_p)

    # --- checkpoint integration ------------------------------------------
    def _group_template(self, groups, gi: int, shapes) -> Dict[str, Any]:
        keys = [str(i) for i in groups[gi]]
        z = {k: np.empty(tuple(shapes[int(k)]), np.float32) for k in keys}
        return {"mu": z, "nu": dict(z)}

    def save_files(self, dst_dir: str) -> None:
        """Checkpoint the on-disk state by file copy — O(io-buffer) host
        RAM, never gathering (at the scales NVMe offload targets, a full
        gather can exhaust host memory). Writes ``nvme_meta.json`` (group
        layout + shapes + count) so any engine — different sub_group_size,
        or no NVMe offload at all — can read the checkpoint back."""
        import json
        import os

        self.swapper.flush()
        for gi in range(len(self.groups)):
            self.swapper.swapper.copy_files(self._name(gi), dst_dir)
        with open(os.path.join(dst_dir, "nvme_meta.json"), "w") as f:
            json.dump({"groups": self.groups,
                       "shapes": [list(s) for s in self._shapes],
                       "count": self.count}, f)

    def load_files(self, src_dir: str, count: int) -> None:
        import json
        import os

        self.swapper.flush()      # drop prefetches of the old state
        meta_path = os.path.join(src_dir, "nvme_meta.json")
        if not os.path.exists(meta_path):
            # checkpoint predates the meta file: only same-layout adoption
            # is possible (the old format's implicit contract)
            for gi in range(len(self.groups)):
                self.swapper.swapper.adopt_files(
                    self._name(gi), src_dir,
                    self._group_template(self.groups, gi, self._shapes))
            self.count = int(count)
            return
        with open(meta_path) as f:
            meta = json.load(f)
        saved_groups = [list(g) for g in meta["groups"]]
        if saved_groups == [list(g) for g in self.groups]:
            # same group layout → pure file adoption, no materialization
            for gi in range(len(self.groups)):
                self.swapper.swapper.adopt_files(
                    self._name(gi), src_dir,
                    self._group_template(self.groups, gi, self._shapes))
        else:
            log_dist(
                "ZeRO-Infinity resume across a sub_group_size change: "
                "re-binning optimizer state (materializes the full m/v on "
                "host once)", ranks=[0])
            full = read_nvme_opt_dir(src_dir)
            self.load_state(full)
        self.count = int(count)

    def load_state(self, state: Dict[str, Any]) -> None:
        """Distribute a full {mu, nu, count} host state into this engine's
        on-disk groups (cross-format / cross-grouping resume path)."""
        self.count = int(state["count"])
        for gi, idxs in enumerate(self.groups):
            keys = [str(i) for i in idxs]
            self.swapper.offload(
                self._name(gi),
                {"mu": {k: np.asarray(state["mu"][k], np.float32)
                        for k in keys},
                 "nu": {k: np.asarray(state["nu"][k], np.float32)
                        for k in keys}})

    def close(self):
        self.swapper.close()


def read_nvme_opt_dir(src_dir: str) -> Dict[str, Any]:
    """Materialize a saved NVMe optimizer-state dir as {mu, nu, count}
    host dicts keyed by flat param index — the bridge that lets a
    non-NVMe engine load an NVMe checkpoint (and vice-versa re-binning)."""
    import json
    import os

    with open(os.path.join(src_dir, "nvme_meta.json")) as f:
        meta = json.load(f)
    mu: Dict[str, Any] = {}
    nu: Dict[str, Any] = {}
    for gi, idxs in enumerate(meta["groups"]):
        keys = [str(i) for i in idxs]
        template = {"mu": {k: np.empty(tuple(meta["shapes"][int(k)]),
                                       np.float32) for k in keys},
                    "nu": {k: np.empty(tuple(meta["shapes"][int(k)]),
                                       np.float32) for k in keys}}
        leaves, treedef = jax.tree_util.tree_flatten(template)
        read = []
        for i, leaf in enumerate(leaves):
            path = os.path.join(src_dir, f"opt_group{gi}.{i}.bin")
            arr = np.fromfile(path, dtype=np.float32)
            if arr.size != leaf.size:
                raise ValueError(
                    f"{path}: {arr.size} elements, expected {leaf.size}")
            read.append(arr.reshape(leaf.shape))
        group = jax.tree_util.tree_unflatten(treedef, read)
        mu.update(group["mu"])
        nu.update(group["nu"])
    return {"mu": mu, "nu": nu, "count": meta["count"]}


def locate_adam_state(opt_state):
    """Find the (first) ScaleByAdamState-shaped node in an optax state tree
    (a namedtuple with mu/nu/count fields)."""
    if hasattr(opt_state, "_fields") and "mu" in opt_state._fields \
            and "nu" in opt_state._fields:
        return opt_state
    if isinstance(opt_state, (tuple, list)):
        for x in opt_state:
            found = locate_adam_state(x)
            if found is not None:
                return found
    return None


def extract_adam_state(opt_state) -> Dict[str, Any]:
    """optax state → the NVMe {mu, nu, count} format (dense checkpoint
    loaded into an NVMe engine)."""
    node = locate_adam_state(opt_state)
    if node is None:
        raise ValueError(
            "checkpoint's optimizer state has no Adam moments (mu/nu) — "
            "cannot convert it for NVMe offload")
    mu_leaves = jax.tree_util.tree_leaves(node.mu)
    nu_leaves = jax.tree_util.tree_leaves(node.nu)
    return {"mu": {str(i): np.asarray(l, np.float32)
                   for i, l in enumerate(mu_leaves)},
            "nu": {str(i): np.asarray(l, np.float32)
                   for i, l in enumerate(nu_leaves)},
            "count": int(np.asarray(node.count))}


def inject_adam_state(opt_state, nvme_state, params_treedef):
    """NVMe {mu, nu, count} → the engine's existing optax state structure
    (NVMe checkpoint loaded into a dense engine). Arrays are placed with
    the current state's shardings."""
    n = len(nvme_state["mu"])
    mu_tree = jax.tree_util.tree_unflatten(
        params_treedef, [nvme_state["mu"][str(i)] for i in range(n)])
    nu_tree = jax.tree_util.tree_unflatten(
        params_treedef, [nvme_state["nu"][str(i)] for i in range(n)])

    replaced = [False]

    def walk(node):
        if not replaced[0] and hasattr(node, "_fields") \
                and "mu" in node._fields and "nu" in node._fields:
            replaced[0] = True
            new_mu = jax.tree_util.tree_map(
                lambda new, old: jax.device_put(new, old.sharding)
                if isinstance(old, jax.Array) else new, mu_tree, node.mu)
            new_nu = jax.tree_util.tree_map(
                lambda new, old: jax.device_put(new, old.sharding)
                if isinstance(old, jax.Array) else new, nu_tree, node.nu)
            count = np.asarray(nvme_state["count"],
                               np.asarray(node.count).dtype)
            if isinstance(node.count, jax.Array):
                count = jax.device_put(count, node.count.sharding)
            return node._replace(mu=new_mu, nu=new_nu, count=count)
        if isinstance(node, tuple) and type(node) is not tuple:
            return type(node)(*[walk(x) for x in node])
        if isinstance(node, (tuple, list)):
            return type(node)(walk(x) for x in node)
        return node

    out = walk(opt_state)
    if not replaced[0]:
        raise ValueError(
            "engine's optimizer state has no Adam moments (mu/nu) — an "
            "NVMe checkpoint only restores into Adam-family optimizers")
    return out
