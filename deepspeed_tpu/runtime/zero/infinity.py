"""ZeRO-Infinity: optimizer states live on NVMe between (and during) steps.

TPU-native analogue of the reference's per-sub-group swapped optimizer step
(``deepspeed/runtime/zero/stage3.py:1775-1835``: swap-in sub-group i →
unscale/clip → ``_optimizer_step`` → swap-out), built on
:class:`~deepspeed_tpu.runtime.swap_tensor.swapper.PipelinedOptimizerSwapper`
so sub-group i+1's read and i-1's write-back overlap sub-group i's device
update — the reference's pipelined_optimizer_swapper.py behavior.

The fused single-program train step cannot read disk mid-program, so the
NVMe path splits the step: one jitted grads program (all GAS micro-batches,
global-norm + finiteness in-graph), then a host loop of jitted per-sub-group
Adam updates whose m/v arrive from and return to NVMe. Only one sub-group's
fp32 state is device-resident at a time (``sub_group_size`` elements), which
is the whole point: HBM holds params + grads + one group's m/v instead of
the full optimizer state.

Like the reference (which pairs ZeRO-Infinity with DeepSpeedCPUAdam /
FusedAdam), the swapped update is Adam-family only; other optimizers raise
at engine init instead of silently ignoring the offload config.
"""

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.swap_tensor.swapper import PipelinedOptimizerSwapper
from deepspeed_tpu.utils.logging import log_dist

ADAM_FAMILY = ("adam", "adamw", "fusedadam")


def validate_offload_config(config) -> None:
    """Loud errors for unsupported ZeRO-Offload/Infinity combinations (the
    reference silently requires these; VERDICT r1 flagged silent no-ops as
    worse than errors)."""
    zc = config.zero_config
    opt = config.optimizer
    opt_name = (opt.type if opt is not None else "adamw").lower()
    if (zc.offload_optimizer_device == "nvme"
            or zc.offload_param_device == "cpu") and jax.process_count() > 1:
        # the sub-group store holds gathered (unsharded) state in per-process
        # local files/arrays; running it multi-host would keep divergent
        # local copies and silently corrupt resume semantics
        raise NotImplementedError(
            "offloaded optimizer/param state is single-host only: the "
            "sub-group store keeps gathered state per process "
            f"(jax.process_count()={jax.process_count()}); shard-local swap "
            "files are the multi-host extension")
    if zc.offload_param_device == "nvme":
        # handled by the host-interpreter trainer (zero/param_nvme.py); the
        # engine branches to it before reaching this validator, but direct
        # callers get the same loud checks
        from deepspeed_tpu.runtime.zero.param_nvme import (
            validate_param_nvme_config,
        )

        validate_param_nvme_config(config, mesh=None)
        return
    opt_params = dict(opt.params) if opt is not None else {}
    if zc.offload_optimizer_device in ("cpu", "nvme") or \
            zc.offload_param_device == "cpu":
        typed = [k for k in ("moment_dtype", "mu_dtype", "nu_dtype")
                 if opt_params.get(k) is not None
                 and str(opt_params[k]).lower() not in ("float32", "fp32")]
        if typed:
            raise NotImplementedError(
                f"offloaded optimizer states are dense fp32 (the swapped "
                f"per-sub-group Adam step, zero/infinity.py group_update); "
                f"optimizer.params {typed} would be silently ignored — "
                f"unset them (moment precision is an HBM-residency knob; "
                f"offloaded moments never occupy HBM between steps). The "
                f"grouped-stream tier (offload_param.grouped_stream) does "
                f"support bf16 moment storage")
    if zc.offload_param_device == "cpu":
        # stage-3 requirement raises in stages.plan_zero_shardings; here the
        # cross-feature contracts
        if zc.offload_optimizer_device not in ("cpu", "nvme"):
            raise ValueError(
                "offload_param.device=cpu requires offload_optimizer.device "
                "cpu or nvme: with the optimizer in HBM the update would "
                "re-materialize the full parameter+state set on device, "
                "undoing the offload (the reference pairs param offload "
                "with DeepSpeedCPUAdam the same way)")
        if opt_name not in ADAM_FAMILY:
            raise ValueError(
                f"offload_param.device=cpu uses the per-sub-group swapped "
                f"Adam step and supports Adam-family optimizers only "
                f"({'/'.join(ADAM_FAMILY)}); got {opt_name!r}")
    if zc.offload_optimizer_device != "nvme":
        return
    if zc.stage < 1:
        raise ValueError(
            "offload_optimizer.device=nvme requires zero_optimization.stage "
            f">= 1 (got stage={zc.stage})")
    if zc.offload_optimizer.nvme_path is None:
        raise ValueError(
            "offload_optimizer.device=nvme requires offload_optimizer."
            "nvme_path (the swap directory)")
    if opt_name not in ADAM_FAMILY:
        raise ValueError(
            f"offload_optimizer.device=nvme supports Adam-family optimizers "
            f"only ({'/'.join(ADAM_FAMILY)}) — the reference pairs "
            f"ZeRO-Infinity with DeepSpeedCPUAdam/FusedAdam; got {opt_name!r}")


# engine.py imported the original name; both remain valid
validate_nvme_config = validate_offload_config


class HostRAMOptimizerStore:
    """RAM tier of the offloaded optimizer step — the ZeRO-Offload analogue
    of the NVMe swapper (reference pairs ``offload_optimizer.device=cpu``
    with DeepSpeedCPUAdam's pinned CPU buffers, zero/stage_1_and_2.py:1037).
    Same contract as :class:`PipelinedOptimizerSwapper`, but sub-group state
    lives in host numpy arrays: acquire/release are dictionary moves, and
    the checkpoint file format matches the NVMe store bit-for-bit so either
    backing restores the other's checkpoints."""

    def __init__(self):
        self._store: Dict[str, Any] = {}
        self.swapper = self     # checkpoint copy/adopt live on .swapper

    def offload(self, name: str, tree: Any) -> None:
        # leaves stored AS-IS: pinned-host jax arrays stay on the
        # accelerator host (no device↔client copies); numpy leaves from
        # checkpoint restore ride along until the next release()
        self._store[name] = tree

    def prefetch(self, name: str) -> None:      # RAM: nothing to overlap
        pass

    def acquire(self, name: str, sharding=None, device_put: bool = False):
        assert name in self._store, f"nothing offloaded under {name}"
        return self._store[name]

    def release(self, name: str, tree: Any) -> None:
        self._store[name] = tree

    def flush(self) -> None:
        pass

    def copy_files(self, name: str, dst_dir: str) -> None:
        import os

        os.makedirs(dst_dir, exist_ok=True)
        leaves = jax.tree_util.tree_leaves(self._store[name])
        for i, leaf in enumerate(leaves):
            np.asarray(leaf, np.float32).tofile(
                os.path.join(dst_dir, f"{name}.{i}.bin"))

    def adopt_files(self, name: str, src_dir: str, template: Any) -> None:
        import os

        leaves, treedef = jax.tree_util.tree_flatten(template)
        read = []
        for i, leaf in enumerate(leaves):
            path = os.path.join(src_dir, f"{name}.{i}.bin")
            arr = np.fromfile(path, dtype=np.float32)
            if arr.size != leaf.size:
                raise ValueError(
                    f"adopt_files({name}): {path} has {arr.size} elements, "
                    f"template leaf {i} needs {leaf.size}")
            read.append(arr.reshape(leaf.shape))
        self._store[name] = jax.tree_util.tree_unflatten(treedef, read)

    def close(self) -> None:
        self._store.clear()


class OffloadedOptimizerStates:
    """Owns grouping, the backing store, and the per-group jitted AdamW
    update for every offloaded optimizer configuration:

    - ``offload_optimizer.device=nvme``: m/v stream NVMe→HBM→NVMe per
      sub-group through the pipelined AIO swapper.
    - ``offload_param.device=cpu`` (+ optimizer cpu or nvme): parameters are
      ALSO host-resident (plan.offload_param) — each sub-group's params make
      one host→HBM→host round trip inside the jitted update, so HBM never
      holds more than ``sub_group_size`` elements of params+m+v at once
      (reference stage3.py:1775 + parameter_offload.py release semantics).

    State files hold the gathered (unsharded) arrays — per-shard files are a
    multi-host extension (and validate_offload_config rejects multi-process
    meshes).
    """

    def __init__(self, params, plan, mesh, config):
        zc = config.zero_config
        opt_cfg = config.optimizer
        p = dict(opt_cfg.params) if opt_cfg is not None else {}
        betas = p.get("betas", (p.get("beta1", 0.9), p.get("beta2", 0.999)))
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(p.get("eps", 1e-8))
        self.weight_decay = float(p.get("weight_decay", 0.0))
        self.base_lr = float(p.get("lr", 1e-3))
        self.count = 0
        self.mesh = mesh

        flat, self.treedef = jax.tree_util.tree_flatten(params)
        self.n_leaves = len(flat)
        self._shapes = [tuple(l.shape) for l in flat]
        self._param_shardings = jax.tree_util.tree_leaves(
            plan.param_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        opt_spec_leaves = jax.tree_util.tree_leaves(
            plan.opt_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        self._opt_shardings = [NamedSharding(mesh, s) for s in opt_spec_leaves]
        # host-resident params (offload_param): the update round-trips each
        # group's params host→device→host; on backends without in-graph host
        # placement (virtual CPU mesh) the write-back silently stays in
        # device memory, which is correct there (it IS host RAM)
        self.host_params = bool(getattr(plan, "offload_param", False))
        param_spec_leaves = jax.tree_util.tree_leaves(
            plan.param_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        self._param_dev_shardings = [NamedSharding(mesh, s)
                                     for s in param_spec_leaves]
        grad_spec_leaves = jax.tree_util.tree_leaves(
            plan.grad_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        self._grad_dev_shardings = [NamedSharding(mesh, s)
                                    for s in grad_spec_leaves]

        # greedy size-bounded grouping (reference sub_group_size semantics,
        # zero/config.py: sub_group_size elements per swap/step granule)
        limit = max(int(zc.sub_group_size), 1)
        self.groups: List[List[int]] = []
        cur, cur_size = [], 0
        for i, leaf in enumerate(flat):
            n = int(np.prod(leaf.shape)) if hasattr(leaf, "shape") else 1
            if cur and cur_size + n > limit:
                self.groups.append(cur)
                cur, cur_size = [], 0
            cur.append(i)
            cur_size += n
        if cur:
            self.groups.append(cur)

        # cpu backing keeps m/v as PINNED-HOST JAX ARRAYS (remote host RAM
        # on TPU) rather than client numpy: the per-group update then moves
        # state host↔HBM in-graph over PCIe with no host↔client copies —
        # the pinned-buffer contract of DeepSpeedCPUAdam
        self._pinned_states = zc.offload_optimizer_device == "cpu"
        self._opt_host_shardings = [
            NamedSharding(mesh, s.spec, memory_kind="pinned_host")
            if self._pinned_states else s for s in self._opt_shardings]
        if zc.offload_optimizer_device == "nvme":
            swap_dir = zc.offload_optimizer.nvme_path
            self.swapper = PipelinedOptimizerSwapper(str(swap_dir))
            where = f"NVMe sub-groups at {swap_dir}"
        else:   # offload_param=cpu with optimizer states in host RAM
            self.swapper = HostRAMOptimizerStore()
            where = "pinned-host sub-groups"
        for gi, idxs in enumerate(self.groups):
            if self._pinned_states:
                zeros = {str(i): jax.device_put(
                    np.zeros(flat[i].shape, np.float32),
                    self._opt_host_shardings[i]) for i in idxs}
            else:
                zeros = {str(i): np.zeros(flat[i].shape, np.float32)
                         for i in idxs}
            self.swapper.offload(self._name(gi), {"mu": zeros,
                                                  "nu": dict(zeros)})
        log_dist(
            f"ZeRO-Offload/Infinity: {self.n_leaves} param tensors in "
            f"{len(self.groups)} {where} (sub_group_size={limit}, "
            f"host_params={self.host_params})", ranks=[0])

        b1, b2, eps, wd = self.b1, self.b2, self.eps, self.weight_decay

        # Decoupled weight decay matching the fused path exactly: both the
        # optax adamw chain AND build_optimizer's plain-adam chain
        # (scale_by_adam → add_decayed_weights → lr) keep wd OUT of the
        # moment estimates — so the NVMe and fused engines produce the same
        # trajectory for the same config. No donation: the inputs are the
        # engine's live param leaves, and a mid-step swap IOError must not
        # leave self.params referencing deleted buffers.
        host_params = self.host_params
        pinned_states = self._pinned_states
        dev_sh, host_sh = self._param_dev_shardings, self._param_shardings
        gdev_sh = self._grad_dev_shardings
        odev_sh, ohost_sh = self._opt_shardings, self._opt_host_shardings

        @jax.jit
        def group_update(params_g, mu_g, nu_g, grads_g, lr, clip_scale, t):
            def upd(k, p, mu, nu, g):
                if host_params:
                    # fetch: this group's param+grad shards host→HBM (the
                    # only ones resident on device during the update — the
                    # grads program lands the full grad tree in host memory)
                    p = jax.device_put(p, dev_sh[int(k)])
                    g = jax.device_put(g, gdev_sh[int(k)])
                if pinned_states:
                    mu = jax.device_put(mu, odev_sh[int(k)])
                    nu = jax.device_put(nu, odev_sh[int(k)])
                g = g.astype(jnp.float32) * clip_scale
                mu = b1 * mu + (1 - b1) * g
                nu = b2 * nu + (1 - b2) * jnp.square(g)
                mhat = mu / (1 - b1 ** t)
                nhat = nu / (1 - b2 ** t)
                step = mhat / (jnp.sqrt(nhat) + eps)
                if wd:
                    step = step + wd * p.astype(jnp.float32)
                new_p = (p.astype(jnp.float32) - lr * step).astype(p.dtype)
                if host_params:
                    new_p = jax.device_put(new_p, host_sh[int(k)])
                if pinned_states:
                    mu = jax.device_put(mu, ohost_sh[int(k)])
                    nu = jax.device_put(nu, ohost_sh[int(k)])
                return new_p, mu, nu

            out = {k: upd(k, params_g[k], mu_g[k], nu_g[k], grads_g[k])
                   for k in params_g}
            return ({k: v[0] for k, v in out.items()},
                    {k: v[1] for k, v in out.items()},
                    {k: v[2] for k, v in out.items()})

        self._group_update = group_update

    def _name(self, gi: int) -> str:
        return f"opt_group{gi}"

    def step(self, params, grads, clip_scale, lr: Optional[float] = None):
        """One optimizer step: pipelined swap-in → jitted update → swap-out
        per sub-group (reference stage3.py:1799-1815 loop). Returns updated
        params (same sharded pytree).

        A swap IOError mid-loop aborts the step with the caller's params
        intact (nothing is donated), but already-released groups keep their
        updated on-disk m/v — recovery after a disk failure is checkpoint
        reload, as in the reference."""
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        assert len(flat_p) == self.n_leaves, "param tree changed shape"
        self.count += 1
        t = jnp.asarray(self.count, jnp.float32)
        lr = jnp.asarray(self.base_lr if lr is None else lr, jnp.float32)
        clip_scale = jnp.asarray(clip_scale, jnp.float32)

        sw = self.swapper
        sw.prefetch(self._name(0))
        for gi, idxs in enumerate(self.groups):
            # host copies; the ONE host→device transfer below places each
            # leaf directly in its sharded layout (no unsharded staging
            # replica on the default device)
            state = sw.acquire(self._name(gi), device_put=False)
            if gi + 1 < len(self.groups):
                sw.prefetch(self._name(gi + 1))
            keys = [str(i) for i in idxs]
            params_g = {k: flat_p[int(k)] for k in keys}
            grads_g = {k: flat_g[int(k)] for k in keys}
            if self._pinned_states:
                # pinned-host jax arrays go straight into the jitted update
                # (in-graph host→HBM fetch); a numpy leaf (post-restore)
                # rides along as an ordinary replicated arg
                mu_g = {k: state["mu"][k] for k in keys}
                nu_g = {k: state["nu"][k] for k in keys}
            else:
                mu_g = {k: jax.device_put(state["mu"][k],
                                          self._opt_shardings[int(k)])
                        for k in keys}
                nu_g = {k: jax.device_put(state["nu"][k],
                                          self._opt_shardings[int(k)])
                        for k in keys}
            new_p, new_mu, new_nu = self._group_update(
                params_g, mu_g, nu_g, grads_g, lr, clip_scale, t)
            for k in keys:
                flat_p[int(k)] = new_p[k]
            if self._pinned_states:
                sw.release(self._name(gi), {"mu": new_mu, "nu": new_nu})
            else:
                sw.release(
                    self._name(gi),
                    {"mu": {k: np.asarray(v) for k, v in new_mu.items()},
                     "nu": {k: np.asarray(v) for k, v in new_nu.items()}})
        sw.flush()
        return jax.tree_util.tree_unflatten(treedef, flat_p)

    # --- checkpoint integration ------------------------------------------
    def _group_template(self, groups, gi: int, shapes) -> Dict[str, Any]:
        keys = [str(i) for i in groups[gi]]
        z = {k: np.empty(tuple(shapes[int(k)]), np.float32) for k in keys}
        return {"mu": z, "nu": dict(z)}

    def save_files(self, dst_dir: str) -> None:
        """Checkpoint the on-disk state by file copy — O(io-buffer) host
        RAM, never gathering (at the scales NVMe offload targets, a full
        gather can exhaust host memory). Writes ``nvme_meta.json`` (group
        layout + shapes + count) so any engine — different sub_group_size,
        or no NVMe offload at all — can read the checkpoint back."""
        import json
        import os

        self.swapper.flush()
        for gi in range(len(self.groups)):
            self.swapper.swapper.copy_files(self._name(gi), dst_dir)
        with open(os.path.join(dst_dir, "nvme_meta.json"), "w") as f:
            json.dump({"groups": self.groups,
                       "shapes": [list(s) for s in self._shapes],
                       "count": self.count}, f)

    def load_files(self, src_dir: str, count: int) -> None:
        import json
        import os

        self.swapper.flush()      # drop prefetches of the old state
        meta_path = os.path.join(src_dir, "nvme_meta.json")
        if not os.path.exists(meta_path):
            # checkpoint predates the meta file: only same-layout adoption
            # is possible (the old format's implicit contract)
            for gi in range(len(self.groups)):
                self.swapper.swapper.adopt_files(
                    self._name(gi), src_dir,
                    self._group_template(self.groups, gi, self._shapes))
            self.count = int(count)
            return
        with open(meta_path) as f:
            meta = json.load(f)
        saved_groups = [list(g) for g in meta["groups"]]
        if saved_groups == [list(g) for g in self.groups]:
            # same group layout → pure file adoption, no materialization
            for gi in range(len(self.groups)):
                self.swapper.swapper.adopt_files(
                    self._name(gi), src_dir,
                    self._group_template(self.groups, gi, self._shapes))
        else:
            log_dist(
                "ZeRO-Infinity resume across a sub_group_size change: "
                "re-binning optimizer state (materializes the full m/v on "
                "host once)", ranks=[0])
            full = read_nvme_opt_dir(src_dir)
            self.load_state(full)
        self.count = int(count)

    def load_state(self, state: Dict[str, Any]) -> None:
        """Distribute a full {mu, nu, count} host state into this engine's
        on-disk groups (cross-format / cross-grouping resume path)."""
        self.count = int(state["count"])
        for gi, idxs in enumerate(self.groups):
            keys = [str(i) for i in idxs]
            self.swapper.offload(
                self._name(gi),
                {"mu": {k: np.asarray(state["mu"][k], np.float32)
                        for k in keys},
                 "nu": {k: np.asarray(state["nu"][k], np.float32)
                        for k in keys}})

    def close(self):
        self.swapper.close()


# original (round-1) name for the NVMe-only configuration
NVMeOptimizerStates = OffloadedOptimizerStates


def read_nvme_opt_dir(src_dir: str) -> Dict[str, Any]:
    """Materialize a saved NVMe optimizer-state dir as {mu, nu, count}
    host dicts keyed by flat param index — the bridge that lets a
    non-NVMe engine load an NVMe checkpoint (and vice-versa re-binning)."""
    import json
    import os

    with open(os.path.join(src_dir, "nvme_meta.json")) as f:
        meta = json.load(f)
    mu: Dict[str, Any] = {}
    nu: Dict[str, Any] = {}
    for gi, idxs in enumerate(meta["groups"]):
        keys = [str(i) for i in idxs]
        template = {"mu": {k: np.empty(tuple(meta["shapes"][int(k)]),
                                       np.float32) for k in keys},
                    "nu": {k: np.empty(tuple(meta["shapes"][int(k)]),
                                       np.float32) for k in keys}}
        leaves, treedef = jax.tree_util.tree_flatten(template)
        read = []
        for i, leaf in enumerate(leaves):
            path = os.path.join(src_dir, f"opt_group{gi}.{i}.bin")
            arr = np.fromfile(path, dtype=np.float32)
            if arr.size != leaf.size:
                raise ValueError(
                    f"{path}: {arr.size} elements, expected {leaf.size}")
            read.append(arr.reshape(leaf.shape))
        group = jax.tree_util.tree_unflatten(treedef, read)
        mu.update(group["mu"])
        nu.update(group["nu"])
    return {"mu": mu, "nu": nu, "count": meta["count"]}


def locate_adam_state(opt_state):
    """Find the (first) ScaleByAdamState-shaped node in an optax state tree
    (a namedtuple with mu/nu/count fields)."""
    if hasattr(opt_state, "_fields") and "mu" in opt_state._fields \
            and "nu" in opt_state._fields:
        return opt_state
    if isinstance(opt_state, (tuple, list)):
        for x in opt_state:
            found = locate_adam_state(x)
            if found is not None:
                return found
    return None


def extract_adam_state(opt_state) -> Dict[str, Any]:
    """optax state → the NVMe {mu, nu, count} format (dense checkpoint
    loaded into an NVMe engine)."""
    node = locate_adam_state(opt_state)
    if node is None:
        raise ValueError(
            "checkpoint's optimizer state has no Adam moments (mu/nu) — "
            "cannot convert it for NVMe offload")
    mu_leaves = jax.tree_util.tree_leaves(node.mu)
    nu_leaves = jax.tree_util.tree_leaves(node.nu)
    return {"mu": {str(i): np.asarray(l, np.float32)
                   for i, l in enumerate(mu_leaves)},
            "nu": {str(i): np.asarray(l, np.float32)
                   for i, l in enumerate(nu_leaves)},
            "count": int(np.asarray(node.count))}


def inject_adam_state(opt_state, nvme_state, params_treedef):
    """NVMe {mu, nu, count} → the engine's existing optax state structure
    (NVMe checkpoint loaded into a dense engine). Arrays are placed with
    the current state's shardings."""
    n = len(nvme_state["mu"])
    mu_tree = jax.tree_util.tree_unflatten(
        params_treedef, [nvme_state["mu"][str(i)] for i in range(n)])
    nu_tree = jax.tree_util.tree_unflatten(
        params_treedef, [nvme_state["nu"][str(i)] for i in range(n)])

    replaced = [False]

    def walk(node):
        if not replaced[0] and hasattr(node, "_fields") \
                and "mu" in node._fields and "nu" in node._fields:
            replaced[0] = True
            def place(new, old):
                # honor the live state's dtype too (typed bf16 moments,
                # ops/optimizers.scale_by_adam_typed): NVMe files are
                # always fp32, and restoring them as fp32 would silently
                # double moment memory and retrace the step
                new = np.asarray(new, getattr(old, "dtype", np.float32))
                if isinstance(old, jax.Array):
                    return jax.device_put(new, old.sharding)
                return new

            new_mu = jax.tree_util.tree_map(place, mu_tree, node.mu)
            new_nu = jax.tree_util.tree_map(place, nu_tree, node.nu)
            count = np.asarray(nvme_state["count"],
                               np.asarray(node.count).dtype)
            if isinstance(node.count, jax.Array):
                count = jax.device_put(count, node.count.sharding)
            return node._replace(mu=new_mu, nu=new_nu, count=count)
        if isinstance(node, tuple) and type(node) is not tuple:
            return type(node)(*[walk(x) for x in node])
        if isinstance(node, (tuple, list)):
            return type(node)(walk(x) for x in node)
        return node

    out = walk(opt_state)
    if not replaced[0]:
        raise ValueError(
            "engine's optimizer state has no Adam moments (mu/nu) — an "
            "NVMe checkpoint only restores into Adam-family optimizers")
    return out
