"""ZeRO stages as a sharding plan.

The reference implements ZeRO with imperative machinery — flat fp16 buffers,
IPG buckets, grad hooks, param gather/release hooks
(stage_1_and_2.py, stage3.py, partition_parameters.py). On TPU the same
memory behavior falls out of *where each pytree lives*:

- stage 1: optimizer states sharded over the ``data`` axis. XLA turns the
  grad reduction into reduce_scatter for the shard each rank updates and
  all_gathers updated params — exactly the reference's
  ``all_gather_dp_groups`` epilogue (runtime/utils.py:923).
- stage 2: + gradients constrained to data-sharded, so the full-grad buffer
  never materializes (the IPG bucket analogue; XLA overlaps the
  reduce_scatter with backward compute like ``overlap_comm``).
- stage 3: + parameters sharded over ``data``; XLA inserts per-layer
  all_gathers during fwd/bwd — the coordinator's fetch/release with compiler
  scheduling instead of Python trace machinery. With scan-over-layers models
  the gather is per-block, bounding live memory like
  ``max_live_parameters``.

Offload: optimizer-state shardings get ``memory_kind='pinned_host'`` —
the analogue of ZeRO-Offload's pinned CPU buffers + DeepSpeedCPUAdam; XLA
streams shards HBM<->host around the update.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.partition import path_str, infer_param_spec
from deepspeed_tpu.utils.logging import logger

#: communication_data_type spellings → collective boundary dtypes
#: (reference engine.py:776 communication_data_type knob). "int8" is
#: the quantized-collective arm (comm.quantize_dequant_int8): the
#: gradient crosses the reduce boundary through the EQuARX per-chunk
#: int8 wire transform rather than a plain cast.
COMM_DTYPES = {"fp16": jnp.float16, "float16": jnp.float16,
               "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
               "fp32": jnp.float32, "float32": jnp.float32,
               "int8": "int8"}


class ZeroShardingPlan(NamedTuple):
    """Shardings for every training-state pytree."""

    param_specs: Any        # pytree of PartitionSpec for model params
    grad_specs: Any         # pytree of PartitionSpec gradients are constrained to
    opt_specs: Any          # pytree-spec applied to each optimizer-state leaf
    param_shardings: Any    # NamedShardings (host memory when offload_param)
    opt_sharding_fn: Any    # leaf-path -> NamedSharding for optimizer state
    offload_optimizer: bool
    offload_param: bool = False


def _specs(params: Any, mesh: Mesh, rules, shard_data: bool,
           zero_axis: str = "data") -> Any:
    def spec_for(path, leaf):
        if not hasattr(leaf, "shape") or getattr(leaf, "ndim", 0) == 0:
            return PartitionSpec()
        return infer_param_spec(path_str(path), leaf.shape, mesh, rules,
                                shard_data, zero_axis=zero_axis)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def _supports_host_memory(mesh: Mesh) -> bool:
    try:
        dev = mesh.devices.flat[0]
        kinds = {m.kind for m in dev.addressable_memories()}
        return "pinned_host" in kinds
    except Exception:   # dstlint: disable=no-silent-except (capability probe: abstract/virtual meshes have no devices; False IS the outcome)
        return False


def plan_zero_shardings(params: Any, mesh: Mesh, zero_config, rules=None) -> ZeroShardingPlan:
    stage = zero_config.stage
    mics = getattr(zero_config, "mics_shard_size", -1)
    zero_axis = "data"
    if mics and mics > 0:
        # MiCS (reference zero/mics.py): partitions are bounded to sub-groups
        # of mics_shard_size ranks (the "mics" mesh axis carved out of data);
        # state replicates across sub-groups, so gathers stay inside a group
        # (intra-node ICI) and only gradient reduction crosses groups —
        # XLA's psum over ("data","mics") does the hierarchical reduction.
        if "mics" in mesh.axis_names and mesh.shape["mics"] > 1:
            zero_axis = "mics"
        else:
            logger.warning(
                "mics_shard_size set but the mesh has no mics axis; build "
                "the mesh with make_mesh(..., mics_shard_size=N) — falling "
                "back to full data-axis sharding")

    param_specs = _specs(params, mesh, rules, shard_data=(stage >= 3),
                         zero_axis=zero_axis)
    grad_specs = _specs(params, mesh, rules, shard_data=(stage >= 2),
                        zero_axis=zero_axis)
    opt_specs = _specs(params, mesh, rules, shard_data=(stage >= 1),
                       zero_axis=zero_axis)

    offload = zero_config.offload_optimizer_device == "cpu"
    host_ok = offload and _supports_host_memory(mesh)
    if offload and not host_ok:
        logger.warning("offload_optimizer=cpu requested but this backend lacks "
                       "pinned_host memory; keeping optimizer states in HBM")

    # ZeRO-3 parameter offload (reference partition_parameters.py:603 Init
    # with remote_device='cpu' + parameter_offload.py:201): the master param
    # pytree is RESIDENT in pinned host memory; the train step streams each
    # scan-block's weights into HBM inside the layer loop (models/llama.py
    # StreamedLlamaModel) so HBM never holds the full parameter set.
    offp = zero_config.offload_param_device == "cpu"
    if offp and stage < 3:
        raise ValueError(
            f"offload_param.device=cpu requires zero_optimization.stage=3 "
            f"(got stage={stage}) — parameter offload partitions parameters, "
            f"which only stage 3 does (reference zero/config.py contract)")
    param_host_ok = offp and _supports_host_memory(mesh)
    if offp and not param_host_ok:
        logger.warning("offload_param=cpu requested but this backend lacks "
                       "pinned_host memory; keeping parameters in HBM")

    def param_sharding(spec: PartitionSpec) -> NamedSharding:
        if param_host_ok:
            return NamedSharding(mesh, spec, memory_kind="pinned_host")
        return NamedSharding(mesh, spec)

    param_shardings = jax.tree_util.tree_map(
        param_sharding, param_specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec))

    def opt_sharding(spec: PartitionSpec) -> NamedSharding:
        if host_ok:
            return NamedSharding(mesh, spec, memory_kind="pinned_host")
        return NamedSharding(mesh, spec)

    return ZeroShardingPlan(
        param_specs=param_specs,
        grad_specs=grad_specs,
        opt_specs=opt_specs,
        param_shardings=param_shardings,
        opt_sharding_fn=opt_sharding,
        offload_optimizer=host_ok,
        offload_param=param_host_ok,
    )


def grad_shardings_for(plan: ZeroShardingPlan, mesh: Mesh) -> Any:
    """NamedShardings for the gradient tree (the reduce boundary specs)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), plan.grad_specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec))


def constrain_gradients(grads: Any, grad_shardings: Any,
                        comm_dtype=None, predivide: float = 1.0) -> Any:
    """The gradient reduction boundary — THE seam where XLA places the
    cross-replica reduction for data/mics-sharded gradients (reference
    engine.py:776-788 reduction knobs). ``communication_data_type`` casts
    at this boundary so the synthesized collective moves the configured
    dtype; ``gradient_predivide_factor`` stages the averaging (1/f before
    the boundary, f after) so fp16 partial sums cannot overflow. Shared
    by the training engine's step programs and the dstlint SPMD pass's
    abstract traces, so what the linter budgets is what the engine runs.
    """
    def c(g, s):
        orig = g.dtype
        if predivide != 1.0:
            g = g / predivide
        if comm_dtype == "int8":
            # quantized collective arm: the per-chunk int8 round-trip
            # (scale + payload) IS the wire transform the EQuARX ring
            # applies — numerics match an int8 reduction while XLA still
            # synthesizes the collective from the sharding constraint
            from deepspeed_tpu.comm.comm import quantize_dequant_int8

            g = quantize_dequant_int8(g)
        elif comm_dtype is not None:
            g = g.astype(comm_dtype)
        g = jax.lax.with_sharding_constraint(g, s)
        if comm_dtype is not None:
            g = g.astype(orig)
        if predivide != 1.0:
            g = g * predivide
        return g

    return jax.tree_util.tree_map(c, grads, grad_shardings)


def build_zero_train_step(loss_fn, optimizer, plan: ZeroShardingPlan,
                          mesh, *, communication_data_type: Optional[str] = None,
                          gradient_predivide_factor: float = 1.0,
                          with_stats: bool = False):
    """A minimal ZeRO train step over a sharding plan: value_and_grad →
    the :func:`constrain_gradients` reduce boundary → optimizer update.

    This is the abstract-traceable distillation of the engine's fused
    step (runtime/engine.py ``_build_step_functions``) sharing the real
    boundary code — the dstlint SPMD pass traces it per stage under an
    AbstractMesh to budget the collectives XLA will synthesize (stage 1:
    param all-gather epilogue; stage 2/3: grad reduce-scatter). The
    engine itself keeps its richer program (loss scaling, finite guards,
    offload transfers) built on the same ``constrain_gradients`` seam.

    ``with_stats`` mirrors the engine's dsttrain telemetry default: the
    step additionally returns the in-graph health-stats pytree
    (observability/train.train_health_stats). Stats are computed on the
    raw gradients BEFORE the reduce boundary — semantically they are
    the global values either way (the constraint is an identity modulo
    the communication-dtype round-trip), and keeping the norm reduce
    off the constrained (provably sharded) tree is what lets the SPMD
    comms pin prove the stats pytree adds ZERO new collective keys to
    the budgeted train-step programs (tests/unit/test_dsttrain.py).
    """
    import optax

    gshard = grad_shardings_for(plan, mesh)
    comm_dtype = (COMM_DTYPES[communication_data_type.lower()]
                  if communication_data_type else None)

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        stats = None
        if with_stats:
            from deepspeed_tpu.observability.train import train_health_stats

            stats = train_health_stats(grads)
        grads = constrain_gradients(grads, gshard, comm_dtype,
                                    float(gradient_predivide_factor))
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if with_stats:
            return loss, new_params, new_opt, stats
        return loss, new_params, new_opt

    return train_step


def opt_state_shardings(opt_state: Any, params: Any, plan: ZeroShardingPlan,
                        mesh: Mesh) -> Any:
    """Shardings for an optax opt_state: leaves shaped like a param pytree get
    that param's (stage>=1 data-sharded) spec; scalars/steps are replicated."""
    flat_params, params_treedef = jax.tree_util.tree_flatten(params)
    flat_specs = jax.tree_util.tree_leaves(
        plan.opt_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))

    def sharding_for(leaf):
        if hasattr(leaf, "shape") and leaf.ndim > 0:
            # match param-shaped leaves by shape identity walk
            for p, s in zip(flat_params, flat_specs):
                if p.shape == leaf.shape:
                    return plan.opt_sharding_fn(s)
        return NamedSharding(mesh, PartitionSpec())

    def map_subtree(subtree):
        # If this subtree has the same structure AND leaf shapes as params,
        # map spec-wise. (Structure alone is not enough: e.g. the 1-bit
        # optimizers carry flat error buffers in a params-shaped tree.)
        try:
            sub_flat, sub_def = jax.tree_util.tree_flatten(subtree)
            if sub_def == params_treedef and all(
                    getattr(l, "shape", None) == p.shape
                    for l, p in zip(sub_flat, flat_params)):
                return jax.tree_util.tree_unflatten(
                    sub_def, [plan.opt_sharding_fn(s) for s in flat_specs])
        except Exception:   # dstlint: disable=no-silent-except (structural probe: non-params-shaped subtrees are expected; None routes them to the scalar walk)
            pass
        return None

    # optax states are tuples/namedtuples whose fields are either param-shaped
    # pytrees (mu, nu, trace...) or scalars (count).
    def walk(node):
        mapped = map_subtree(node)
        if mapped is not None:
            return mapped
        if isinstance(node, tuple) and type(node) is not tuple:  # NamedTuple
            return type(node)(*[walk(x) for x in node])
        if isinstance(node, (tuple, list)):
            return type(node)(walk(x) for x in node)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return sharding_for(node)

    return walk(opt_state)
