"""ZeRO configuration.

Mirrors reference ``deepspeed/runtime/zero/config.py:81-255`` (stage, bucket
sizes, overlap_comm, offload_param/optimizer, sub_group_size, stage3_*
thresholds, mics_shard_size) reinterpreted for a sharded-pytree runtime:

- stage 0: optimizer states, gradients and params replicated over the data axis
- stage 1: optimizer states sharded over the data axis
- stage 2: + gradients reduce-scattered (sharded) over the data axis
- stage 3: + parameters sharded over the data axis (FSDP-style), gathered
  per-layer by XLA

On TPU the IPG bucketing / hook machinery of the reference becomes sharding
constraints under jit — XLA inserts and overlaps reduce_scatter/all_gather —
so bucket-size knobs are accepted for config-surface parity and used as hints.
"""

from enum import Enum
from typing import Optional

from pydantic import Field, model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """Parameter offload (reference zero/offload_config.py:21)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(100_000_000, ge=0)
    max_in_cpu: int = Field(1_000_000_000, ge=0)
    pin_memory: bool = False
    # accept the whole-tree fetch for models without a streamed twin (the
    # full parameter set transiently re-materializes in HBM each step,
    # forfeiting the capacity the offload exists for) — without this flag
    # such models RAISE instead of silently degrading
    fallback_whole_tree: bool = False
    # >0: the grouped streaming interpreter (zero/grouped_stream.py) —
    # N layers per host-driven program, gradients accumulate in pinned
    # host memory. Needed when the fp32 grad tree alone exceeds HBM
    # (~3.5B fp32 on v5e), where the single-program streamed step
    # compile-refuses
    grouped_stream: int = Field(0, ge=0)
    # land the grad tree in pinned host memory as backward produces it
    # (capacity default). At scales where the grads fit HBM comfortably,
    # false skips the host round-trip — faster steps, params/moments stay
    # offloaded either way
    grads_to_host: bool = True
    # grouped_stream only: double-buffer the group weight fetch — each
    # group program also returns a device copy of the NEXT group's
    # weights, so the host→HBM transfer overlaps the current group's
    # compute (the reference's overlapped sub-group pipeline,
    # stage3.py:1775-1835). Costs one extra group of fp32 weights in HBM;
    # disable at sizes where two groups + grads don't fit
    stream_prefetch: bool = True


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """Optimizer-state offload (reference zero/offload_config.py:52)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    @property
    def pipeline(self) -> bool:
        return self.pipeline_read or self.pipeline_write


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """`"zero_optimization": {...}` (reference zero/config.py:81)."""

    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(500_000_000, ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(500_000_000, ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    sub_group_size: int = Field(1_000_000_000, ge=0)
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer"}
    )

    prefetch_bucket_size: int = Field(50_000_000, ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(100_000, ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(2 ** 62, ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(1_000_000_000, ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(1_000_000_000, ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(
        False, alias="stage3_gather_16bit_weights_on_model_save"
    )

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    # MiCS: bound ZeRO sharding to sub-groups of the data axis (reference mics.py)
    mics_shard_size: int = Field(-1, ge=-1)
    mics_hierarchical_params_gather: bool = False

    @model_validator(mode="after")
    def _overlap_comm_default(self):
        if self.overlap_comm is None:
            object.__setattr__(self, "overlap_comm", self.stage == 3)
        return self

    @property
    def offload_optimizer_device(self) -> str:
        if self.offload_optimizer is None:
            return OffloadDeviceEnum.none.value
        return self.offload_optimizer.device.value

    @property
    def offload_param_device(self) -> str:
        if self.offload_param is None:
            return OffloadDeviceEnum.none.value
        return self.offload_param.device.value
