"""zero.Init / GatheredParameters — construction-time partitioning API.

Reference surface: ``deepspeed.zero.Init`` (partition_parameters.py:603)
monkey-patches ``nn.Module.__init__`` so every parameter materializes
pre-sharded, and ``GatheredParameters`` (partition_parameters.py:1304 file)
temporarily re-assembles full params inside a context. On TPU neither needs
module surgery: params are a pytree whose placement is a sharding, so

- ``Init`` wraps a flax ``init`` call and materializes the tree *directly
  into* its ZeRO-3 (data-axis) sharding — no full replica ever exists on any
  chip (``jax.jit`` with ``out_shardings`` streams shards from the sharded
  initializer program);
- ``gathered_parameters`` / ``GatheredParameters`` device_puts a replicated
  view for host-side surgery (weight loading, eyeballing), then re-shards
  when the context exits (``modifier_rank`` semantics: mutation inside the
  context wins).
"""

import contextlib
from typing import Any, Callable, Optional

import jax
from deepspeed_tpu.utils.jax_compat import set_mesh
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.zero.stages import plan_zero_shardings
from deepspeed_tpu.utils.logging import logger


class _ZeroConfigView:
    """Minimal zero-config shim for plan_zero_shardings."""

    def __init__(self, stage: int):
        self.stage = stage
        self.mics_shard_size = -1
        self.offload_optimizer_device = "none"
        self.offload_param_device = "none"


class Init:
    """Construction-time ZeRO-3 partitioning (reference zero.Init).

    Usage::

        with zero.Init(mesh=mesh):
            params = zero.Init.materialize(model.init, rng, sample)

    or functionally::

        params = Init(mesh=mesh).init(model.init, rng, sample)

    Params come out sharded over the data axis; nothing full-size is ever
    resident. (The reference's module-patching has no analogue to perform —
    flax modules are pure, so wrapping the init call is the whole job.)
    """

    _active: Optional["Init"] = None

    def __init__(self, mesh: Optional[Mesh] = None, config_dict_or_path=None,
                 mem_efficient_linear: bool = True, remote_device=None,
                 pin_memory: bool = False, dtype=None, enabled: bool = True,
                 sharding_rules=None):
        if mesh is None:
            import numpy as np
            mesh = Mesh(np.array(jax.devices()), ("data",))
        self.mesh = mesh
        self.enabled = enabled
        self.dtype = dtype
        self.rules = sharding_rules

    def __enter__(self):
        Init._active = self
        return self

    def __exit__(self, *exc):
        Init._active = None
        return False

    def init(self, init_fn: Callable, *args, **kwargs):
        """Run ``init_fn(*args)`` with outputs materialized pre-sharded
        (floating leaves cast to ``dtype`` when given, like the reference's
        ``zero.Init(dtype=…)``)."""
        if not self.enabled:
            return init_fn(*args, **kwargs)

        if self.dtype is None:
            fn = init_fn
        else:
            import jax.numpy as jnp

            def fn(*a, **kw):
                return jax.tree_util.tree_map(
                    lambda x: x.astype(self.dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    init_fn(*a, **kw))

        abstract = jax.eval_shape(fn, *args, **kwargs)
        plan = plan_zero_shardings(abstract, self.mesh, _ZeroConfigView(3),
                                   self.rules)
        with set_mesh(self.mesh):
            return jax.jit(fn,
                           out_shardings=plan.param_shardings)(*args, **kwargs)

    @staticmethod
    def materialize(init_fn: Callable, *args, **kwargs):
        ctx = Init._active
        if ctx is None:
            return init_fn(*args, **kwargs)
        return ctx.init(init_fn, *args, **kwargs)


def shutdown_init_context():
    """reference partition_parameters.py:515 — deactivate a live Init."""
    Init._active = None


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank: Optional[int] = None,
                       fwd_module=None, enabled: bool = True,
                       mesh: Optional[Mesh] = None):
    """Temporarily replicate sharded params (reference GatheredParameters).

    Yields a dict ``{"params": replicated_tree}``; assign back into
    ``view["params"]`` inside the context to mutate (modifier semantics) —
    on exit the (possibly modified) tree is re-sharded to the original
    shardings and written into ``view["resharded"]``.
    """
    if not enabled:
        yield {"params": params, "resharded": params}
        return
    shardings = jax.tree_util.tree_map(lambda p: p.sharding, params)
    if mesh is None:
        first = jax.tree_util.tree_leaves(params)[0]
        mesh = first.sharding.mesh
    rep = NamedSharding(mesh, PartitionSpec())
    gathered = jax.tree_util.tree_map(
        lambda p: jax.device_put(p, rep), params)
    view = {"params": gathered, "resharded": None}
    try:
        yield view
    finally:
        view["resharded"] = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(jax.numpy.asarray(p), s),
            view["params"], shardings)
