"""TiledLinear — memory-bounded huge linear layers.

TPU-native analogue of the reference's ``TiledLinear``
(deepspeed/runtime/zero/tiling.py:32): a linear layer whose weight is stored
as an ``in_splits x out_splits`` grid of tiles so that (a) under ZeRO-3
sharding only one tile needs to be resident/gathered at a time, and (b) the
peak activation memory of the matmul is bounded by one tile-row of the
output. The reference walks the tile grid with Python loops over
``torch.nn.Linear`` children; here the walk is a ``lax.scan`` over stacked
tile arrays so the whole layer stays one XLA program, each scan step touches
exactly one [in_tile, out_tile-row] slice, and ``jax.checkpoint`` on the
scan body gives the inactive-tile memory behavior ZeRO-3 provides in the
reference (tiles outside the active step are never live in HBM when the
params are sharded).

``TiledLinearReturnBias`` (reference tiling.py:259, used by Megatron-style
rows that defer the bias add) is the ``apply_bias=False`` mode: the bias is
returned alongside the product instead of added.
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


def tiled_matmul(x: jnp.ndarray, tiles: jnp.ndarray, *,
                 remat: bool = True) -> jnp.ndarray:
    """y = x @ W where W is given as stacked tiles.

    ``tiles``: [in_splits, out_splits, in_tile, out_tile] — the logical
    weight is the block matrix W[i*in_tile:(i+1)*in_tile,
    j*out_tile:(j+1)*out_tile] = tiles[i, j].

    Scans over the input splits, accumulating partial products into the full
    output row; each step reads one tile-row, so at most
    ``in_tile x out_features`` weight elements are live per step.
    """
    in_splits, out_splits, in_tile, out_tile = tiles.shape
    x_split = x.reshape(x.shape[:-1] + (in_splits, in_tile))
    x_split = jnp.moveaxis(x_split, -2, 0)  # [in_splits, ..., in_tile]

    def body(acc, xw):
        xi, wi = xw  # xi: [..., in_tile]; wi: [out_splits, in_tile, out_tile]
        w_row = jnp.transpose(wi, (1, 0, 2)).reshape(in_tile,
                                                     out_splits * out_tile)
        return acc + xi @ w_row, None

    if remat:
        body = jax.checkpoint(body)
    out_shape = x.shape[:-1] + (out_splits * out_tile,)
    acc0 = jnp.zeros(out_shape, dtype=x.dtype)
    y, _ = jax.lax.scan(body, acc0, (x_split, tiles))
    return y


class TiledLinear(nn.Module):
    """Drop-in linear with a tiled weight grid (reference tiling.py:32).

    Attributes mirror the reference's constructor: ``in_splits``/``out_splits``
    control the grid; ``apply_bias=False`` returns ``(y, bias)`` instead of
    adding it (the ``TiledLinearReturnBias`` behavior, tiling.py:259).
    """

    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    apply_bias: bool = True
    dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    remat: bool = True

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        if in_features % self.in_splits or self.features % self.out_splits:
            raise ValueError(
                f"in_features {in_features} / features {self.features} must "
                f"divide in_splits {self.in_splits} / out_splits "
                f"{self.out_splits}")
        in_tile = in_features // self.in_splits
        out_tile = self.features // self.out_splits

        def init(key, shape, dtype):
            # Initialize as one dense kernel so numerics match an untiled
            # nn.Dense with the same init, then carve into the tile grid.
            full = self.kernel_init(key, (in_features, self.features), dtype)
            grid = full.reshape(self.in_splits, in_tile,
                                self.out_splits, out_tile)
            return jnp.transpose(grid, (0, 2, 1, 3))

        tiles = self.param("tiles", init,
                           (self.in_splits, self.out_splits, in_tile, out_tile),
                           self.dtype)
        y = tiled_matmul(x.astype(self.dtype), tiles, remat=self.remat)
        if not self.use_bias:
            return y
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), self.dtype)
        if self.apply_bias:
            return y + bias
        return y, bias


def tiles_to_dense(tiles: jnp.ndarray) -> jnp.ndarray:
    """Reassemble the logical [in_features, out_features] kernel."""
    in_splits, out_splits, in_tile, out_tile = tiles.shape
    return jnp.transpose(tiles, (0, 2, 1, 3)).reshape(
        in_splits * in_tile, out_splits * out_tile)


def dense_to_tiles(kernel: jnp.ndarray, in_splits: int,
                   out_splits: int) -> jnp.ndarray:
    """Carve an existing dense kernel into the tile grid (the reference's
    ``copy_params_from`` path, tiling.py:222)."""
    in_features, out_features = kernel.shape
    grid = kernel.reshape(in_splits, in_features // in_splits,
                          out_splits, out_features // out_splits)
    return jnp.transpose(grid, (0, 2, 1, 3))
