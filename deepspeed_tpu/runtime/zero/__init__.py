from deepspeed_tpu.runtime.zero.config import (
    DeepSpeedZeroConfig,
    DeepSpeedZeroOffloadOptimizerConfig,
    DeepSpeedZeroOffloadParamConfig,
)
from deepspeed_tpu.runtime.zero.stages import (
    ZeroShardingPlan,
    build_zero_train_step,
    constrain_gradients,
    grad_shardings_for,
    opt_state_shardings,
    plan_zero_shardings,
)
from deepspeed_tpu.runtime.zero.tiling import (
    TiledLinear,
    dense_to_tiles,
    tiled_matmul,
    tiles_to_dense,
)
from deepspeed_tpu.runtime.zero.partition_parameters import (
    GatheredParameters,
    Init,
    shutdown_init_context,
)
