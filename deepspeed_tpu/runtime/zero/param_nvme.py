"""ZeRO-Infinity **parameter** offload: weights live on NVMe between uses.

TPU-native analogue of the reference's NVMe parameter swapper + hook-driven
per-submodule fetch/release (``runtime/swap_tensor/partitioned_param_swapper
.py:36`` ``AsyncPartitionedParameterSwapper``, ``runtime/zero/
parameter_offload.py:201`` ``DeepSpeedZeRoOffload``, ``partition_parameters
.py:603`` ``Init(remote_device='nvme')``). The reference streams partitioned
torch params NVMe→pinned buffer→GPU around each submodule under eager
execution; a jitted TPU program cannot read disk mid-graph, so the step is
an explicit host-driven interpreter over per-layer compiled programs:

- **fwd** (per micro-batch): ``embed`` program, then one ``layer_fwd``
  program per transformer layer whose weights arrive NVMe→host→HBM just
  before use (the AIO pool prefetches layer l+1 while l computes — the
  param-coordinator prefetch, partitioned_param_coordinator.py:262) and are
  dropped after (release = XLA frees the buffer; reads need no write-back).
  Boundary activations are stashed in pinned host memory.
- **bwd**: the mirrored loop — each layer re-fetches its weights, recomputes
  its forward from the stashed input (activation-checkpoint style) and runs
  the VJP; weight gradients accumulate in host-RAM fp32 buffers (the
  reference's pinned grad partitions, stage_1_and_2.py:1037).
- **update**: per-group swapped AdamW exactly like the optimizer-state NVMe
  path (stage3.py:1775-1835): params + m/v stream NVMe→HBM→NVMe one layer
  at a time, so HBM never holds more than one layer of params+grads+states
  and host RAM holds grads + an LRU window of param groups.

The ``max_in_cpu`` window (reference zero/offload_config.py ``max_in_cpu``)
is a host-RAM LRU cache of param groups: at ``max_in_cpu >= total params``
this degenerates to CPU-offload behavior (disk touched only by the update's
write-back); at 0 every fetch hits NVMe.

Scope (all loudly validated): scanned-Llama models, Adam-family optimizers,
bf16/fp32 (no fp16 loss scaling), single process. Tied embeddings supported.
"""

import json
import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.swap_tensor.swapper import PipelinedOptimizerSwapper
from deepspeed_tpu.utils.logging import log_dist, logger

ADAM_FAMILY = ("adam", "adamw", "fusedadam")


def validate_param_nvme_config(config, mesh) -> None:
    """Loud errors for unsupported offload_param=nvme combinations (the
    round-1 standard: never silently ignore the config the framework is
    named for)."""
    zc = config.zero_config
    opt = config.optimizer
    opt_name = (opt.type if opt is not None else "adamw").lower()
    if zc.stage < 3:
        raise ValueError(
            f"offload_param.device=nvme requires zero_optimization.stage=3 "
            f"(got stage={zc.stage}) — parameter offload partitions "
            f"parameters, which only stage 3 does")
    if zc.offload_param.nvme_path is None:
        raise ValueError(
            "offload_param.device=nvme requires offload_param.nvme_path "
            "(the swap directory)")
    if zc.offload_param.grouped_stream:
        raise ValueError(
            "offload_param.grouped_stream composes with device=cpu only "
            "(pinned-host state); the NVMe tier has its own per-layer "
            "interpreter — drop grouped_stream or set device=cpu")
    if zc.offload_optimizer_device not in ("cpu", "nvme"):
        raise ValueError(
            "offload_param.device=nvme requires offload_optimizer.device "
            "cpu or nvme: with the optimizer in HBM the update would "
            "re-materialize the full parameter+state set on device, "
            "undoing the offload")
    if (zc.offload_optimizer_device == "nvme"
            and zc.offload_optimizer.nvme_path is None):
        raise ValueError(
            "offload_optimizer.device=nvme requires "
            "offload_optimizer.nvme_path")
    if opt_name not in ADAM_FAMILY:
        raise ValueError(
            f"offload_param.device=nvme uses the per-group swapped Adam "
            f"step and supports Adam-family optimizers only "
            f"({'/'.join(ADAM_FAMILY)}); got {opt_name!r}")
    opt_params = dict(opt.params) if opt is not None else {}
    typed = [k for k in ("moment_dtype", "mu_dtype", "nu_dtype")
             if opt_params.get(k) is not None
             and str(opt_params[k]).lower() not in ("float32", "fp32")]
    if typed:
        raise NotImplementedError(
            f"offload_param.device=nvme stores optimizer moments as fp32 "
            f"swap files; optimizer.params {typed} would be silently "
            f"ignored — unset them (moment precision is an HBM-residency "
            f"knob; NVMe-tier moments never occupy HBM between steps)")
    if config.fp16.enabled:
        raise NotImplementedError(
            "offload_param.device=nvme does not support fp16 loss scaling; "
            "use bf16 (TPU-native) or fp32")
    if jax.process_count() > 1:
        raise NotImplementedError(
            "offload_param.device=nvme is single-host only: the swap files "
            "hold gathered state per process "
            f"(jax.process_count()={jax.process_count()})")
    if mesh is not None and mesh.shape.get("pipe", 1) > 1:
        raise NotImplementedError(
            "offload_param.device=nvme does not compose with pipeline "
            "parallelism (the pipeline loss owns the layer loop)")
    reject_loss_rewriters(config, "offload_param.device=nvme")


def get_any_compression(config) -> bool:
    from deepspeed_tpu.compression import get_compression_config

    return get_compression_config(config.compression_config).any_enabled


def reject_loss_rewriters(config, tier: str) -> None:
    """Shared gate for the interpreter tiers: features that rewrite the
    loss/step cannot compose with a host-driven layer loop."""
    for feature, enabled in (
            ("compression", get_any_compression(config)),
            ("eigenvalue", config.eigenvalue_enabled),
            ("progressive_layer_drop", config.pld_enabled),
            ("flops_profiler", config.flops_profiler.enabled),
            ("quantize_training", config.quantize_training_enabled)):
        if enabled:
            raise NotImplementedError(
                f"{tier} does not compose with {feature} "
                f"(both rewrite the loss/step)")


def stash_to_host(x):
    """Move an activation to pinned host memory (backends without a host
    space — the virtual CPU mesh — keep it where it is). Shared by the
    interpreter tiers (param-NVMe and grouped-stream)."""
    try:
        return jax.device_put(x, x.sharding.with_memory_kind("pinned_host"))
    except Exception:   # dstlint: disable=no-silent-except (probe: backend without a host memory space — CPU — keeps the array where it is; that IS the outcome)
        return x


def unstash_from_host(x):
    if getattr(getattr(x, "sharding", None), "memory_kind", None) \
            == "pinned_host":
        return jax.device_put(x, x.sharding.with_memory_kind("device"))
    return x


class _HostParamCache:
    """LRU host-RAM window over param groups (reference ``max_in_cpu``,
    zero/offload_config.py:21): groups fetched from NVMe stay in host RAM
    until the element budget forces eviction."""

    def __init__(self, max_elements: int):
        self.max_elements = int(max_elements)
        self._items: "OrderedDict[str, Any]" = OrderedDict()
        self._sizes: Dict[str, int] = {}
        self._used = 0

    def get(self, name: str):
        if name not in self._items:
            return None
        self._items.move_to_end(name)
        return self._items[name]

    def put(self, name: str, tree: Any) -> None:
        n = sum(int(np.prod(np.shape(l)))
                for l in jax.tree_util.tree_leaves(tree))
        if n > self.max_elements:
            self.pop(name)
            return
        if name in self._items:
            self._used -= self._sizes[name]
        self._items[name] = tree
        self._items.move_to_end(name)
        self._sizes[name] = n
        self._used += n
        while self._used > self.max_elements and len(self._items) > 1:
            old, _ = self._items.popitem(last=False)
            self._used -= self._sizes.pop(old)

    def pop(self, name: str) -> None:
        if name in self._items:
            del self._items[name]
            self._used -= self._sizes.pop(name)

    def __contains__(self, name: str) -> bool:
        return name in self._items


class NVMeParamTrainer:
    """Owns NVMe-resident parameters + optimizer states and the streamed
    train step. Construct via the engine (``offload_param.device=nvme``)."""

    def __init__(self, cfg, config, mesh, rng):
        from deepspeed_tpu.models.llama import LlamaBlock, LlamaConfig

        assert isinstance(cfg, LlamaConfig), (
            "offload_param.device=nvme streams the scanned-Llama layer "
            f"loop; model config must be a LlamaConfig (got {type(cfg)})")
        assert cfg.scan_layers, (
            "offload_param.device=nvme requires scan_layers=True (the "
            "stacked block tree is the swap granularity)")
        self.cfg = cfg
        self.mesh = mesh
        zc = config.zero_config
        self.L = cfg.num_layers
        self.gas = config.gradient_accumulation_steps
        self.grad_clip = float(config.gradient_clipping or 0.0)
        self.numerics = config.numerics_check_enabled

        opt_cfg = config.optimizer
        p = dict(opt_cfg.params) if opt_cfg is not None else {}
        betas = p.get("betas", (p.get("beta1", 0.9), p.get("beta2", 0.999)))
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(p.get("eps", 1e-8))
        self.weight_decay = float(p.get("weight_decay", 0.0))
        self.base_lr = float(p.get("lr", 1e-3))
        self.count = 0      # applied updates (LR schedule input)

        # --- stores -------------------------------------------------------
        swap_dir = str(zc.offload_param.nvme_path)
        self._swap = PipelinedOptimizerSwapper(swap_dir)
        if zc.offload_optimizer_device == "nvme":
            opt_dir = str(zc.offload_optimizer.nvme_path)
            self._oswap = (self._swap if os.path.abspath(opt_dir)
                           == os.path.abspath(swap_dir)
                           else PipelinedOptimizerSwapper(opt_dir))
        else:       # optimizer tier = host RAM (offload_optimizer=cpu)
            from deepspeed_tpu.runtime.zero.infinity import (
                HostRAMOptimizerStore,
            )

            self._oswap = HostRAMOptimizerStore()
        self._cache = _HostParamCache(zc.offload_param.max_in_cpu)

        # --- abstract trees & shardings ----------------------------------
        self.block = LlamaBlock(cfg)
        S0 = min(4, cfg.max_seq_len)
        from deepspeed_tpu.models.transformer import make_causal_mask

        x0 = jnp.zeros((1, S0, cfg.hidden_size), cfg.dtype)
        pos0 = jnp.arange(S0, dtype=jnp.int32)[None, :]
        mask0 = make_causal_mask(S0)
        self._abs_layer = jax.eval_shape(
            lambda k: self.block.init(k, x0, mask0, pos0)["params"],
            jax.random.PRNGKey(0))
        self._abs_rest = self._abstract_rest()
        self._plan_shardings(zc)

        self._build_programs()
        self._init_state(rng, zc)
        where = ("NVMe" if zc.offload_optimizer_device == "nvme"
                 else "host-RAM")
        log_dist(
            f"ZeRO-Infinity param offload: {self.L} layer groups + rest on "
            f"NVMe at {swap_dir} (optimizer states: {where}; "
            f"max_in_cpu={zc.offload_param.max_in_cpu:g} elements)",
            ranks=[0])

    # --- construction helpers --------------------------------------------
    def _abstract_rest(self):
        cfg = self.cfg
        import flax.linen as nn

        def init_rest(k):
            k1, k2 = jax.random.split(k)
            embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                             param_dtype=jnp.float32, dtype=cfg.dtype)
            rest = {
                "embed_tokens": embed.init(
                    k1, jnp.zeros((1, 1), jnp.int32))["params"],
                "final_norm": {"scale": jnp.ones((cfg.hidden_size,),
                                                 jnp.float32)},
            }
            if not cfg.tie_embeddings:
                head = nn.Dense(cfg.vocab_size, use_bias=False,
                                dtype=cfg.dtype, param_dtype=jnp.float32)
                rest["lm_head"] = head.init(
                    k2, jnp.zeros((1, 1, cfg.hidden_size), cfg.dtype)
                )["params"]
            return rest

        self._init_rest_fn = init_rest
        return jax.eval_shape(init_rest, jax.random.PRNGKey(0))

    def _plan_shardings(self, zc) -> None:
        """Device shardings for one layer slice / the rest tree, derived
        from the stage-3 plan over the abstract stacked tree (the same specs
        the in-HBM engine would use, runtime/zero/stages.py)."""
        from deepspeed_tpu.runtime.zero.stages import plan_zero_shardings

        stacked = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct((self.L,) + tuple(l.shape),
                                           l.dtype), self._abs_layer)
        abstract = dict(self._abs_rest)
        abstract["blocks"] = {"block": stacked}
        plan = plan_zero_shardings(abstract, self.mesh, zc)
        is_spec = lambda x: isinstance(x, PartitionSpec)

        def sliced(spec):
            return NamedSharding(self.mesh, PartitionSpec(*spec[1:]))

        self._layer_sh = jax.tree_util.tree_map(
            sliced, plan.param_specs["blocks"]["block"], is_leaf=is_spec)
        self._rest_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            {k: v for k, v in plan.param_specs.items() if k != "blocks"},
            is_leaf=is_spec)
        self._rep = NamedSharding(self.mesh, PartitionSpec())

    def _build_programs(self) -> None:
        cfg = self.cfg
        from deepspeed_tpu.models.llama import loss_fn as lm_loss
        from deepspeed_tpu.models.transformer import RMSNorm, make_causal_mask

        block = self.block
        norm = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype)

        def emb_fwd(rest, ids):
            # parity with nn.Embed(dtype=cfg.dtype): cast commutes with take
            return rest["embed_tokens"]["embedding"][ids].astype(cfg.dtype)

        def layer_fwd(w, x, pos):
            mask = make_causal_mask(x.shape[-2])
            return block.apply({"params": w}, x, mask, pos)

        def head_loss(rest, x, labels):
            xn = norm.apply({"params": rest["final_norm"]}, x)
            if cfg.tie_embeddings:
                emb = rest["embed_tokens"]["embedding"].astype(cfg.dtype)
                logits = jnp.dot(xn.astype(jnp.float32).astype(cfg.dtype),
                                 emb.T)
            else:
                k = rest["lm_head"]["kernel"].astype(cfg.dtype)
                logits = jnp.dot(xn.astype(cfg.dtype), k)
            return lm_loss(logits.astype(jnp.float32), labels)

        def head_vjp(rest, x, labels):
            loss, pull = jax.vjp(
                lambda r, h: head_loss(r, h, labels), rest, x)
            drest, dx = pull(jnp.ones((), jnp.float32))
            return loss, dx, drest

        def layer_vjp(w, x, pos, dy):
            _, pull = jax.vjp(lambda w_, x_: layer_fwd(w_, x_, pos), w, x)
            dw, dx = pull(dy)
            return dx, dw

        def emb_vjp(rest, ids, dx):
            _, pull = jax.vjp(lambda r: emb_fwd(r, ids), rest)
            return pull(dx)[0]

        b1, b2, eps, wd = self.b1, self.b2, self.eps, self.weight_decay

        def adam_group(w, mu, nu, g, lr, clip_scale, t):
            """Same math as the fused engines (infinity.group_update /
            ops/optimizers.build_optimizer): decoupled weight decay outside
            the moment estimates, bias correction by applied-update count."""

            def upd(p, m, v, gg):
                gg = gg.astype(jnp.float32) * clip_scale
                m = b1 * m + (1 - b1) * gg
                v = b2 * v + (1 - b2) * jnp.square(gg)
                mhat = m / (1 - b1 ** t)
                vhat = v / (1 - b2 ** t)
                step = mhat / (jnp.sqrt(vhat) + eps)
                if wd:
                    step = step + wd * p.astype(jnp.float32)
                return ((p.astype(jnp.float32) - lr * step).astype(p.dtype),
                        m, v)

            from deepspeed_tpu.ops.optimizers import split3

            out = jax.tree_util.tree_map(upd, w, mu, nu, g)
            return split3(w, out)

        self._jit_emb_fwd = jax.jit(emb_fwd)
        self._jit_layer_fwd = jax.jit(layer_fwd)
        self._jit_head_vjp = jax.jit(head_vjp)
        self._jit_layer_vjp = jax.jit(layer_vjp)
        self._jit_emb_vjp = jax.jit(emb_vjp)
        self._jit_adam = jax.jit(adam_group)
        self._jit_head_loss = jax.jit(head_loss)

    def _init_state(self, rng, zc) -> None:
        """Streamed initialization: each layer's params are initialized in
        their own jitted program and written to NVMe before the next layer
        exists — the full tree is never materialized (zero.Init with
        remote_device, partition_parameters.py:603)."""
        from deepspeed_tpu.models.transformer import make_causal_mask

        cfg = self.cfg
        S0 = min(4, cfg.max_seq_len)
        x0 = jnp.zeros((1, S0, cfg.hidden_size), cfg.dtype)
        pos0 = jnp.arange(S0, dtype=jnp.int32)[None, :]
        mask0 = make_causal_mask(S0)
        layer_init = jax.jit(
            lambda k: self.block.init(k, x0, mask0, pos0)["params"])
        keys = jax.random.split(rng, self.L + 1)
        for l in range(self.L):
            w = jax.tree_util.tree_map(np.asarray, layer_init(keys[l]))
            self._swap.offload(self._wname(l), w)
            self._offload_zeros(self._osname(l), w)
        rest = jax.tree_util.tree_map(
            np.asarray, jax.jit(self._init_rest_fn)(keys[self.L]))
        self._swap.offload(self._wname(None), rest)
        self._offload_zeros(self._osname(None), rest)

    def _offload_zeros(self, name: str, like: Any) -> None:
        z = jax.tree_util.tree_map(
            lambda l: np.zeros(np.shape(l), np.float32), like)
        self._oswap.offload(name, {"mu": z, "nu": jax.tree_util.tree_map(
            np.copy, z)})

    # --- naming -----------------------------------------------------------
    def _wname(self, l: Optional[int]) -> str:
        return "w_rest" if l is None else f"w_l{l:03d}"

    def _osname(self, l: Optional[int]) -> str:
        return "os_rest" if l is None else f"os_l{l:03d}"

    # --- fetch machinery --------------------------------------------------
    def _get_host(self, l: Optional[int], prefetch: Optional[int] = -1):
        """Host tree for group ``l`` (None = rest): LRU cache, else NVMe.
        ``prefetch`` (−1 = nothing) submits the next group's reads."""
        name = self._wname(l)
        tree = self._cache.get(name)
        if tree is None:
            tree = self._swap.acquire(name, device_put=False)
            self._cache.put(name, tree)
        if prefetch != -1:
            pname = self._wname(prefetch)
            if pname not in self._cache:
                self._swap.prefetch(pname)
        return tree

    def _put_dev(self, tree, shardings):
        return jax.tree_util.tree_map(
            lambda w, sh: jax.device_put(w, sh), tree, shardings)

    def _get_layer_dev(self, l: int, prefetch: Optional[int] = -1):
        return self._put_dev(self._get_host(l, prefetch), self._layer_sh)

    def _get_rest_dev(self):
        return self._put_dev(self._get_host(None), self._rest_sh)

    # --- activation stash -------------------------------------------------
    _stash = staticmethod(stash_to_host)
    _unstash = staticmethod(unstash_from_host)

    # --- the streamed step ------------------------------------------------
    def train_batch(self, batch: Dict[str, Any], lr: Optional[float] = None):
        """One global step over a ``(gas, micro_global, S)`` batch. Returns
        ``(loss, finite)`` with the same semantics as the fused engine:
        loss/grads averaged over GAS micro-batches, global-norm clipping,
        numerics-gated update skip."""
        ids_all, labels_all = batch["input_ids"], batch["labels"]
        gas = int(ids_all.shape[0])
        pos_all = batch.get("positions")
        L = self.L

        g_layers: List[Any] = [None] * L
        g_rest: Any = None
        loss_acc = None
        rest_dev = self._get_rest_dev()

        def acc(a, b):
            if a is None:
                # owned writable copies: np.asarray of a jax CPU array can
                # be a read-only zero-copy view
                return jax.tree_util.tree_map(
                    lambda x: np.array(x, np.float32), b)
            jax.tree_util.tree_map(
                lambda h, d: np.add(h, np.asarray(d, np.float32), out=h),
                a, b)
            return a

        for g in range(gas):
            ids, labels = ids_all[g], labels_all[g]
            S = int(ids.shape[-1])
            pos = (pos_all[g] if pos_all is not None
                   else jnp.arange(S, dtype=jnp.int32)[None, :])
            # ForwardPass: fetch layer l (prefetch l+1), stash its input
            x = self._jit_emb_fwd(rest_dev, ids)
            stash = []
            for l in range(L):
                w = self._get_layer_dev(l, prefetch=l + 1 if l + 1 < L
                                        else -1)
                stash.append(self._stash(x))
                x = self._jit_layer_fwd(w, x, pos)
            # head + its VJP seed the backward chain
            loss, dx, drest = self._jit_head_vjp(rest_dev, x, labels)
            g_rest = acc(g_rest, drest)
            loss_acc = loss if loss_acc is None else loss_acc + loss
            # BackwardPass: re-fetch layer l (prefetch l-1), recompute+VJP
            for l in reversed(range(L)):
                w = self._get_layer_dev(l, prefetch=l - 1 if l > 0 else -1)
                dx, dw = self._jit_layer_vjp(w, self._unstash(stash[l]),
                                             pos, dx)
                g_layers[l] = acc(g_layers[l], dw)
            g_rest = acc(g_rest, self._jit_emb_vjp(rest_dev, ids, dx))
        del rest_dev

        inv = np.float32(1.0 / gas)
        sq = 0.0
        finite = True
        for tree in g_layers + [g_rest]:
            for leaf in jax.tree_util.tree_leaves(tree):
                np.multiply(leaf, inv, out=leaf)
                sq += float(np.sum(np.square(leaf, dtype=np.float64)))
                if self.numerics and finite:
                    finite = bool(np.isfinite(leaf).all())
        gnorm = float(np.sqrt(sq))
        loss = float(np.asarray(loss_acc)) / gas
        if self.numerics:
            finite = finite and bool(np.isfinite(loss)) \
                and bool(np.isfinite(gnorm))
        else:
            finite = True
        if finite:
            clip = (min(1.0, self.grad_clip / (gnorm + 1e-6))
                    if self.grad_clip > 0 else 1.0)
            self._apply_updates(g_layers, g_rest, clip, lr)
        return jnp.asarray(loss, jnp.float32), jnp.asarray(finite)

    def _apply_updates(self, g_layers, g_rest, clip_scale, lr) -> None:
        """Per-group swapped AdamW (reference stage3.py:1799-1815): group
        l's params+states stream in while l+1's reads are in flight."""
        self.count += 1
        t = jnp.asarray(self.count, jnp.float32)
        lr_v = jnp.asarray(self.base_lr if lr is None else lr, jnp.float32)
        cs = jnp.asarray(clip_scale, jnp.float32)
        order = list(range(self.L)) + [None]
        self._oswap.prefetch(self._osname(order[0]))
        for i, l in enumerate(order):
            os_state = self._oswap.acquire(self._osname(l),
                                           device_put=False)
            if i + 1 < len(order):
                self._oswap.prefetch(self._osname(order[i + 1]))
            sh = self._layer_sh if l is not None else self._rest_sh
            w = self._put_dev(self._get_host(
                l, prefetch=order[i + 1] if i + 1 < len(order) else -1), sh)
            g = self._put_dev(g_layers[l] if l is not None else g_rest, sh)
            mu = self._put_dev(os_state["mu"], sh)
            nu = self._put_dev(os_state["nu"], sh)
            new_w, new_mu, new_nu = self._jit_adam(w, mu, nu, g, lr_v, cs, t)
            host_w = jax.tree_util.tree_map(np.asarray, new_w)
            self._swap.release(self._wname(l), host_w)
            self._cache.put(self._wname(l), host_w)
            self._oswap.release(
                self._osname(l),
                {"mu": jax.tree_util.tree_map(np.asarray, new_mu),
                 "nu": jax.tree_util.tree_map(np.asarray, new_nu)})
        self._swap.flush()
        if self._oswap is not self._swap:
            self._oswap.flush()

    # --- eval / export ----------------------------------------------------
    def loss_eval(self, batch: Dict[str, Any]):
        """Forward-only streamed loss for one ``(B, S)`` micro-batch."""
        ids, labels = batch["input_ids"], batch["labels"]
        S = int(ids.shape[-1])
        pos = batch.get("positions")
        if pos is None:
            pos = jnp.arange(S, dtype=jnp.int32)[None, :]
        rest_dev = self._get_rest_dev()
        x = self._jit_emb_fwd(rest_dev, ids)
        for l in range(self.L):
            w = self._get_layer_dev(l, prefetch=l + 1 if l + 1 < self.L
                                    else -1)
            x = self._jit_layer_fwd(w, x, pos)
        return self._jit_head_loss(rest_dev, x, labels)

    def materialize(self) -> Dict[str, Any]:
        """Full parameter pytree as host numpy, in the engine's stacked
        layout (``consolidated_state_dict`` analogue — materializes
        everything; meant for tests/export, not the training loop)."""
        slices = [self._swap.acquire(self._wname(l), device_put=False)
                  for l in range(self.L)]
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *slices)
        out = dict(self._swap.acquire(self._wname(None), device_put=False))
        out["blocks"] = {"block": stacked}
        return out

    def ingest(self, params: Dict[str, Any]) -> None:
        """Write a full (host) parameter pytree into the NVMe store —
        layer-sliced, one group at a time (dense→NVMe checkpoint bridge;
        also how tests seed identical weights into two engines)."""
        stacked = params["blocks"]["block"]
        for l in range(self.L):
            w = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[l], stacked)
            self._swap.offload(self._wname(l), w)
            self._cache.pop(self._wname(l))
        rest = {k: jax.tree_util.tree_map(np.asarray, v)
                for k, v in params.items() if k != "blocks"}
        self._swap.offload(self._wname(None), rest)
        self._cache.pop(self._wname(None))

    # --- checkpoint -------------------------------------------------------
    def save_files(self, dst_dir: str) -> None:
        """Checkpoint by file copy — O(io-buffer) host RAM, params and
        optimizer states never gathered."""
        os.makedirs(dst_dir, exist_ok=True)
        self._swap.flush()
        if self._oswap is not self._swap:
            self._oswap.flush()
        for l in list(range(self.L)) + [None]:
            self._swap.swapper.copy_files(self._wname(l), dst_dir)
            self._oswap.swapper.copy_files(self._osname(l), dst_dir)
        with open(os.path.join(dst_dir, "param_nvme_meta.json"), "w") as f:
            json.dump({"num_layers": self.L, "count": self.count,
                       "tie_embeddings": self.cfg.tie_embeddings}, f)

    def load_files(self, src_dir: str,
                   load_optimizer_states: bool = True) -> None:
        """Adopt a checkpoint's files. With ``load_optimizer_states=False``
        only the weights are adopted — m/v keep their current (fresh-zero)
        contents and the applied-update count stays, matching the dense
        path's weights-only resume."""
        with open(os.path.join(src_dir, "param_nvme_meta.json")) as f:
            meta = json.load(f)
        if meta["num_layers"] != self.L:
            raise ValueError(
                f"param-NVMe checkpoint has {meta['num_layers']} layers, "
                f"engine has {self.L}")
        self._swap.flush()
        if self._oswap is not self._swap:
            self._oswap.flush()
        for l in list(range(self.L)) + [None]:
            like = self._abs_layer if l is not None else self._abs_rest
            self._swap.swapper.adopt_files(self._wname(l), src_dir, like)
            self._cache.pop(self._wname(l))
            if load_optimizer_states:
                z = jax.tree_util.tree_map(
                    lambda x: np.empty(tuple(x.shape), np.float32), like)
                self._oswap.swapper.adopt_files(
                    self._osname(l), src_dir, {"mu": z, "nu": z})
        if load_optimizer_states:
            self.count = int(meta["count"])

    def close(self) -> None:
        self._swap.close()
        if self._oswap is not self._swap:
            self._oswap.close()
