"""Hybrid engine — one set of weights for RLHF train + generate.

TPU-native analogue of reference ``runtime/hybrid_engine.py:32``
(``DeepSpeedHybridEngine``): the actor model trains under ZeRO and flips to
an inference path for rollout generation. The reference gathers ZeRO-3
params into injected CUDA containers and fuses LoRA (:178-282); here the
flip is free of weight copies — ``generate`` jits the decode program against
the *same* sharded param pytree the train step owns (XLA inserts the
gathers), with optional LoRA fuse/unfuse around generation and a retained
KV workspace between rollouts (the reference's ``retake_inference_cache``).
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.llama import LlamaDecoderModel, init_kv_caches
from deepspeed_tpu.ops.lora import fuse_lora, unfuse_lora
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.timer import Timer


class DeepSpeedHybridEngine(DeepSpeedEngine):
    def __init__(self, *args, model_config=None, lora_adapters=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.model_cfg = model_config or getattr(self.module, "cfg", None)
        self.lora_adapters = lora_adapters
        self._lora_fused = False
        self._decode_fn = None
        self._kv_caches = None
        self._in_eval = False
        self.generate_time = 0.0
        self.latency_timer = Timer("generate")

    # --- train/eval flips (reference :386-434) ----------------------------
    def eval(self):
        """Enter generation mode: fuse LoRA into the base weights."""
        if self.lora_adapters and not self._lora_fused:
            self.params = jax.jit(
                lambda p: fuse_lora(p, self.lora_adapters),
                donate_argnums=(0,))(self.params)
            self._lora_fused = True
        self._in_eval = True

    def train(self, mode: bool = True):
        """Return to training: unfuse LoRA so adapter grads stay separate."""
        if not mode:
            return self.eval()
        if self.lora_adapters and self._lora_fused:
            self.params = jax.jit(
                lambda p: unfuse_lora(p, self.lora_adapters),
                donate_argnums=(0,))(self.params)
            self._lora_fused = False
        self._in_eval = False

    # --- KV workspace mgmt (reference :165-177) ---------------------------
    def _ensure_decode(self, batch_size: int, max_len: int):
        assert self.model_cfg is not None, \
            "hybrid engine generate() needs model_config (LlamaConfig)"
        if self._kv_caches is not None and \
                self._kv_caches[0].shape[1] == batch_size and \
                self._kv_caches[0].shape[2] >= max_len:
            return
        decoder = LlamaDecoderModel(self.model_cfg)
        self._kv_caches = init_kv_caches(self.model_cfg, batch_size, max_len,
                                         self.compute_dtype)
        self._decode_fn = jax.jit(
            lambda p, t, c, i: decoder.apply({"params": p}, t, c, i),
            donate_argnums=(2,))

    def retake_inference_cache(self):
        pass  # workspace persists as self._kv_caches; nothing to re-allocate

    def release_inference_cache(self):
        self._kv_caches = None
        self._decode_fn = None

    def reset_inference_cache(self):
        if self._kv_caches is not None:
            self._kv_caches = jax.tree_util.tree_map(jnp.zeros_like,
                                                     self._kv_caches)

    # --- generation (reference :178-282) ----------------------------------
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 rng: Optional[jax.Array] = None,
                 eos_token_id: Optional[int] = None):
        was_training = not self._in_eval
        if was_training:
            self.eval()
        self.latency_timer.start()

        input_ids = jnp.asarray(input_ids)
        B, T = input_ids.shape
        self._ensure_decode(B, T + max_new_tokens)
        if rng is None:
            rng = jax.random.PRNGKey(self.global_steps)

        with self._ctx():
            logits, caches = self._decode_fn(
                self.params, input_ids, self._kv_caches,
                jnp.asarray(0, jnp.int32))
        next_logits = logits[:, -1, :]
        out = [input_ids]
        finished = jnp.zeros((B,), bool)
        for i in range(max_new_tokens):
            if temperature > 0.0:
                rng, key = jax.random.split(rng)
                scaled = next_logits / temperature
                if top_k > 0:
                    kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
                    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
                nxt = jax.random.categorical(key, scaled, axis=-1)
            else:
                nxt = jnp.argmax(next_logits, axis=-1)
            if eos_token_id is not None:
                nxt = jnp.where(finished, eos_token_id, nxt)
                finished = finished | (nxt == eos_token_id)
            out.append(nxt[:, None])
            if i == max_new_tokens - 1:
                break
            with self._ctx():
                logits, caches = self._decode_fn(
                    self.params, nxt[:, None], caches,
                    jnp.asarray(T + i, jnp.int32))
            next_logits = logits[:, 0, :]
        self._kv_caches = caches

        self.latency_timer.stop(synchronize=True)
        self.generate_time = self.latency_timer.elapsed(reset=True)
        if was_training:
            self.train()
        return jnp.concatenate(out, axis=1)
