"""Hybrid engine — one set of weights for RLHF train + generate.

TPU-native analogue of reference ``runtime/hybrid_engine.py:32``
(``DeepSpeedHybridEngine``): the actor model trains under ZeRO and flips to
an inference path for rollout generation. The reference gathers ZeRO-3
params into injected CUDA containers and fuses LoRA (:178-282); here the
flip is free of weight copies — ``generate`` jits the decode program against
the *same* sharded param pytree the train step owns (XLA inserts the
gathers), with optional LoRA fuse/unfuse around generation and a retained
KV workspace between rollouts (the reference's ``retake_inference_cache``).
"""

from collections import OrderedDict
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.lora import fuse_lora, unfuse_lora
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.timer import Timer


class DeepSpeedHybridEngine(DeepSpeedEngine):
    def __init__(self, *args, model_config=None, lora_adapters=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.model_cfg = model_config or getattr(self.module, "cfg", None)
        self.lora_adapters = lora_adapters
        self._lora_fused = False
        self._decode_fn = None
        self._kv_caches = None
        self._gen_cache: "OrderedDict[Any, Any]" = OrderedDict()
        self._in_eval = False
        self.generate_time = 0.0
        self.latency_timer = Timer("generate")

    # --- train/eval flips (reference :386-434) ----------------------------
    def eval(self):
        """Enter generation mode: fuse LoRA into the base weights."""
        if self.lora_adapters and not self._lora_fused:
            self.params = jax.jit(
                lambda p: fuse_lora(p, self.lora_adapters),
                donate_argnums=(0,))(self.params)
            self._lora_fused = True
        self._in_eval = True

    def train(self, mode: bool = True):
        """Return to training: unfuse LoRA so adapter grads stay separate."""
        if not mode:
            return self.eval()
        if self.lora_adapters and self._lora_fused:
            self.params = jax.jit(
                lambda p: unfuse_lora(p, self.lora_adapters),
                donate_argnums=(0,))(self.params)
            self._lora_fused = False
        self._in_eval = False

    # --- KV workspace mgmt (reference :165-177) ---------------------------
    def _ensure_decode(self, batch_size: int, max_len: int):
        from deepspeed_tpu.inference.engine import resolve_decoder

        assert self.model_cfg is not None, \
            "hybrid engine generate() needs model_config " \
            "(LlamaConfig or TransformerConfig)"
        if self._kv_caches is not None and \
                self._kv_caches[0].shape[1] == batch_size and \
                self._kv_caches[0].shape[2] >= max_len:
            return
        decoder, init_caches, transform = resolve_decoder(self.model_cfg)
        self._decoder = decoder
        self._decode_transform = transform
        # the decoder writes K/V in the MODEL config's dtype — caches must
        # match it, not the training precision (an fp32 model under the
        # default-bf16 engine config would hit a dtype mismatch in the
        # cache update)
        cache_dtype = getattr(self.model_cfg, "dtype", None) \
            or self.compute_dtype
        self._kv_caches = init_caches(self.model_cfg, batch_size, max_len,
                                      cache_dtype)
        self._gen_cache = OrderedDict()

        def step(p, t, c, i):
            if transform is not None:
                p = transform(p)
            return decoder.apply({"params": p}, t, c, i)

        self._decode_fn = jax.jit(step, donate_argnums=(2,))

    def retake_inference_cache(self):
        pass  # workspace persists as self._kv_caches; nothing to re-allocate

    def release_inference_cache(self):
        self._kv_caches = None
        self._decode_fn = None
        self._gen_cache = OrderedDict()

    def reset_inference_cache(self):
        if self._kv_caches is not None:
            self._kv_caches = jax.tree_util.tree_map(jnp.zeros_like,
                                                     self._kv_caches)

    # --- generation (reference :178-282) ----------------------------------
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 rng: Optional[jax.Array] = None,
                 eos_token_id: Optional[int] = None, *,
                 top_p: float = 1.0):
        """Rollout generation against the live (sharded, LoRA-fused) training
        params — one fused prefill+decode program and one compiled-program
        cache policy shared with the inference engine
        (inference/engine.py get_or_build_gen_fn)."""
        from deepspeed_tpu.inference.engine import (
            check_decode_length, gen_capacity, get_or_build_gen_fn,
            prompt_capacity,
        )

        was_training = not self._in_eval
        if was_training:
            self.eval()
        self.latency_timer.start()

        input_ids = jnp.asarray(input_ids, jnp.int32)
        B, T = input_ids.shape
        check_decode_length(self.model_cfg, T + max_new_tokens)
        T_cap = prompt_capacity(T, self.model_cfg)
        pad = T_cap - T
        if pad:
            input_ids = jnp.pad(input_ids, ((0, 0), (pad, 0)))
        self._ensure_decode(B, T_cap + gen_capacity(max_new_tokens))
        decoder = self._decoder
        transform = self._decode_transform
        params_fn, params_key = transform, \
            "fused" if transform is not None else None
        if self._config.hybrid_engine.int8_streaming_rollout:
            # rollouts through the int8 weight-streaming kernel: the LIVE
            # training weights are rowwise-quantized at the program top,
            # so every decode matmul reads half the HBM bytes (inference
            # quant.streaming; models/llama.quantize_fused_rowwise)
            if transform is None:
                raise NotImplementedError(
                    "hybrid_engine.int8_streaming_rollout requires the "
                    "fused Llama decode path (scan-stacked LlamaConfig)")
            from deepspeed_tpu.models.llama import quantize_fused_rowwise

            mcfg = self.model_cfg
            params_fn = lambda p: quantize_fused_rowwise(transform(p), mcfg)
            params_key = "fused-int8stream"
        gen_fn, cap = get_or_build_gen_fn(
            self._gen_cache,
            lambda p, t, c, i, s: decoder.apply({"params": p}, t, c, i, s),
            B, T_cap, max_new_tokens, params_fn=params_fn,
            params_key=params_key)
        if rng is None:
            rng = jax.random.PRNGKey(self.global_steps)
        eos = -1 if eos_token_id is None else int(eos_token_id)

        with self._ctx():
            tokens, self._kv_caches = gen_fn(
                self.params, input_ids, self._kv_caches, rng,
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(top_k, jnp.int32),
                jnp.asarray(top_p, jnp.float32),
                jnp.asarray(eos, jnp.int32),
                jnp.asarray(max_new_tokens, jnp.int32),
                jnp.asarray(pad, jnp.int32))
        tokens = tokens[:, pad: T_cap + max_new_tokens]

        self.latency_timer.stop(synchronize=True)
        self.generate_time = self.latency_timer.elapsed(reset=True)
        if was_training:
            self.train()
        return tokens
