"""Curriculum-aware data sampling.

Reference ``runtime/data_pipeline/data_sampling/data_sampler.py:36``
(``DeepSpeedDataSampler``): samples batches whose difficulty metric stays
under the curriculum's current threshold, clustering the dataset by a
difficulty metric. Indices are deterministic in (seed, epoch, step) so all
hosts draw identical batches without communication — the property the
reference gets by broadcasting from rank 0.
"""

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler,
)


class DeepSpeedDataSampler:
    def __init__(self, difficulties: Sequence[float], batch_size: int,
                 curriculum: Optional[CurriculumScheduler] = None,
                 seed: int = 0, drop_last: bool = True):
        self.difficulties = np.asarray(difficulties)
        self.batch_size = batch_size
        self.curriculum = curriculum
        self.seed = seed
        self.drop_last = drop_last
        self.global_step = 0
        # difficulty-sorted clusters (reference builds an indexed dataset per
        # metric bucket)
        self.order = np.argsort(self.difficulties, kind="stable")

    def eligible_indices(self) -> np.ndarray:
        if self.curriculum is None:
            return self.order
        threshold = self.curriculum.get_current_difficulty()
        mask = self.difficulties[self.order] <= threshold
        eligible = self.order[mask]
        if len(eligible) < self.batch_size:
            eligible = self.order[: self.batch_size]
        return eligible

    def __iter__(self) -> Iterator[List[int]]:
        while True:
            if self.curriculum is not None:
                self.curriculum.update_difficulty(self.global_step)
            eligible = self.eligible_indices()
            rng = np.random.default_rng(self.seed + self.global_step)
            idx = rng.choice(eligible, size=self.batch_size,
                             replace=len(eligible) < self.batch_size)
            self.global_step += 1
            yield idx.tolist()

    @classmethod
    def from_analysis(cls, save_path: str, metric_name: str, batch_size: int,
                      curriculum: Optional[CurriculumScheduler] = None,
                      seed: int = 0) -> "DeepSpeedDataSampler":
        """Build from a ``DataAnalyzer`` output directory: sample
        difficulties come from the metric's ``index_to_metric`` file."""
        from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
            load_analysis,
        )

        values, _, _ = load_analysis(save_path, metric_name)
        return cls(values, batch_size, curriculum=curriculum, seed=seed)

    def state_dict(self) -> Dict:
        state = {"global_step": self.global_step}
        if self.curriculum is not None:
            state["curriculum"] = self.curriculum.state_dict()
        return state

    def load_state_dict(self, sd: Dict) -> None:
        self.global_step = sd["global_step"]
        if self.curriculum is not None and "curriculum" in sd:
            self.curriculum.load_state_dict(sd["curriculum"])
