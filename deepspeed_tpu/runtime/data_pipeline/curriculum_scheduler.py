"""Curriculum learning scheduler.

Reference ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py``:
difficulty (typically sequence length) ramps from ``min_difficulty`` to
``max_difficulty`` under fixed_linear / fixed_root / fixed_discrete /
custom schedules. Pure arithmetic — ports conceptually intact; the engine
truncates each batch's sequence dim to the current difficulty (a static
slice per difficulty value; XLA compiles one program per distinct seqlen,
which the difficulty_step quantization keeps to a handful).
"""

import math
from typing import Any, Callable, Dict, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        self.state: Dict[str, Any] = {}
        assert "curriculum_type" in config and "min_difficulty" in config \
            and "max_difficulty" in config, \
            "curriculum config needs curriculum_type/min_difficulty/max_difficulty"
        self.state["min_difficulty"] = config["min_difficulty"]
        self.state["max_difficulty"] = config["max_difficulty"]
        self.state["current_difficulty"] = config["min_difficulty"]
        self.state["schedule_type"] = config["curriculum_type"]
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None

        stype = config["curriculum_type"]
        if stype in (FIXED_LINEAR, FIXED_ROOT):
            sc = config["schedule_config"]
            assert "total_curriculum_step" in sc and "difficulty_step" in sc
            self.state["schedule"] = dict(sc)
            if stype == FIXED_ROOT:
                assert "root_degree" in sc
        elif stype == FIXED_DISCRETE:
            sc = config["schedule_config"]
            assert "difficulty" in sc and "max_step" in sc
            assert len(sc["difficulty"]) == len(sc["max_step"]) + 1
            self.state["schedule"] = dict(sc)
        elif stype == CUSTOM:
            self.state["schedule"] = {}
        else:
            raise ValueError(f"Unknown curriculum schedule {stype}")

    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_get_difficulty = fn

    def _fixed_linear(self, global_steps: int) -> int:
        sc = self.state["schedule"]
        frac = min(1.0, global_steps / sc["total_curriculum_step"])
        diff = self.state["min_difficulty"] + frac * (
            self.state["max_difficulty"] - self.state["min_difficulty"])
        step = sc["difficulty_step"]
        return min(self.state["max_difficulty"],
                   int(diff / step) * step if diff >= step else step)

    def _fixed_root(self, global_steps: int) -> int:
        sc = self.state["schedule"]
        frac = min(1.0, global_steps / sc["total_curriculum_step"])
        power = 1.0 / sc["root_degree"]
        diff = self.state["min_difficulty"] + (frac ** power) * (
            self.state["max_difficulty"] - self.state["min_difficulty"])
        step = sc["difficulty_step"]
        return min(self.state["max_difficulty"],
                   int(diff / step) * step if diff >= step else step)

    def _fixed_discrete(self, global_steps: int) -> int:
        sc = self.state["schedule"]
        for diff, max_step in zip(sc["difficulty"], sc["max_step"]):
            if global_steps <= max_step:
                return diff
        return sc["difficulty"][-1]

    def update_difficulty(self, global_steps: int) -> int:
        stype = self.state["schedule_type"]
        if stype == FIXED_LINEAR:
            d = self._fixed_linear(global_steps)
        elif stype == FIXED_ROOT:
            d = self._fixed_root(global_steps)
        elif stype == FIXED_DISCRETE:
            d = self._fixed_discrete(global_steps)
        else:
            assert self.custom_get_difficulty is not None, \
                "custom curriculum requires set_custom_get_difficulty"
            d = self.custom_get_difficulty(global_steps)
        self.state["current_difficulty"] = d
        return d

    def state_dict(self) -> Dict[str, Any]:
        return dict(self.state)

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.state.update(sd)


def truncate_to_difficulty(batch: Dict[str, Any], difficulty: int,
                           seq_keys=("input_ids", "labels", "positions",
                                     "attention_mask")):
    """Apply curriculum seqlen: slice the sequence dim (reference
    engine.py:1702-1705 truncates inputs at the curriculum seqlen)."""
    out = {}
    for k, v in batch.items():
        if k in seq_keys and getattr(v, "ndim", 0) >= 2 and v.shape[-1] > difficulty:
            out[k] = v[..., :difficulty]
        else:
            out[k] = v
    return out
