from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler,
    truncate_to_difficulty,
)
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
    DataAnalyzer,
    load_analysis,
)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DeepSpeedDataSampler
from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    make_builder,
)
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
    RandomLTDScheduler,
    gather_tokens,
    sample_kept_tokens,
    scatter_tokens,
    slice_attention_mask,
)
