"""Offline data analyzer (reference
``runtime/data_pipeline/data_sampling/data_analyzer.py``).

Walks a dataset once, computes per-sample difficulty metrics with
user-supplied functions, and writes, per metric:

- ``{metric}/index_to_metric`` — metric value per sample index;
- ``{metric}/index_to_sample`` — sample indices grouped by metric value
  (one "document" per distinct value, ascending) — the structure the
  curriculum sampler reads to form difficulty clusters;
- ``{metric}/metric_values.json`` — {min, max, count}.

Sharding across workers mirrors the reference (``worker_id``/``num_workers``
split + ``merge_file_``), but runs in-process — no launched jobs.
"""

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, make_builder,
)
from deepspeed_tpu.utils.logging import logger

MetricFn = Callable[[Any, int], float]


class DataAnalyzer:
    def __init__(self, dataset: Sequence[Any], metric_names: List[str],
                 metric_functions: List[MetricFn], save_path: str,
                 worker_id: int = 0, num_workers: int = 1,
                 metric_dtype=np.float32):
        assert len(metric_names) == len(metric_functions)
        self.dataset = dataset
        self.metric_names = metric_names
        self.metric_functions = metric_functions
        self.save_path = save_path
        self.worker_id = worker_id
        self.num_workers = num_workers
        self.metric_dtype = np.dtype(metric_dtype)

    def _shard_range(self) -> range:
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return range(lo, min(lo + per, n))

    def _metric_dir(self, name: str) -> str:
        d = os.path.join(self.save_path, name)
        os.makedirs(d, exist_ok=True)
        return d

    def run_map(self) -> Dict[str, str]:
        """Compute this worker's metric shard → ``index_to_metric`` files."""
        out = {}
        shard = self._shard_range()
        values: Dict[str, List[float]] = {n: [] for n in self.metric_names}
        for i in shard:
            sample = self.dataset[i]
            for name, fn in zip(self.metric_names, self.metric_functions):
                values[name].append(float(fn(sample, i)))
        for name in self.metric_names:
            prefix = os.path.join(self._metric_dir(name),
                                  f"index_to_metric_worker{self.worker_id}")
            b = make_builder(prefix, dtype=self.metric_dtype)
            for v in values[name]:
                b.add_item(np.asarray([v]))
            b.finalize()
            out[name] = prefix
        return out

    def run_reduce(self) -> None:
        """Merge worker shards, build metric→samples clusters."""
        for name in self.metric_names:
            d = self._metric_dir(name)
            merged = os.path.join(d, "index_to_metric")
            b = make_builder(merged, dtype=self.metric_dtype)
            for w in range(self.num_workers):
                shard_prefix = os.path.join(d, f"index_to_metric_worker{w}")
                if not MMapIndexedDataset.exists(shard_prefix):
                    raise FileNotFoundError(
                        f"missing analyzer shard {shard_prefix}; run "
                        f"run_map on worker {w} first")
                b.merge_file_(shard_prefix)
            b.finalize()

            metric_ds = MMapIndexedDataset(merged)
            vals = metric_ds.as_array().astype(np.float64)
            if not len(vals):
                raise ValueError(f"data analysis '{name}': empty dataset")
            order = np.argsort(vals, kind="stable")
            sorted_vals = vals[order]
            # one document per distinct metric value, ascending — the
            # difficulty clusters the curriculum sampler consumes
            s_prefix = os.path.join(d, "index_to_sample")
            sb = make_builder(s_prefix, dtype=np.int64)
            uniq = np.unique(sorted_vals)
            bounds = np.searchsorted(sorted_vals, uniq)
            for cluster in np.split(order, bounds[1:]):
                sb.add_item(cluster)
                sb.end_document()
            sb.finalize()
            with open(os.path.join(d, "metric_values.json"), "w") as f:
                json.dump({"min": float(vals.min()), "max": float(vals.max()),
                           "count": int(len(vals)),
                           "num_distinct": int(len(uniq))}, f)
            logger.info(f"data analysis '{name}': {len(vals)} samples, "
                        f"{len(uniq)} distinct values")

    def run(self) -> None:
        """Single-process convenience: map all shards then reduce."""
        for w in range(self.num_workers):
            DataAnalyzer(self.dataset, self.metric_names,
                         self.metric_functions, self.save_path,
                         worker_id=w, num_workers=self.num_workers,
                         metric_dtype=self.metric_dtype).run_map()
        self.run_reduce()


def load_analysis(save_path: str, metric_name: str):
    """(values per sample, clusters list, summary dict) for one metric."""
    d = os.path.join(save_path, metric_name)
    metric_ds = MMapIndexedDataset(os.path.join(d, "index_to_metric"))
    sample_ds = MMapIndexedDataset(os.path.join(d, "index_to_sample"))
    values = metric_ds.as_array().astype(np.float64)
    clusters = [np.asarray(sample_ds[i]) for i in range(len(sample_ds))]
    with open(os.path.join(d, "metric_values.json")) as f:
        summary = json.load(f)
    return values, clusters, summary
