"""Memory-mapped indexed dataset (reference
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` — the
Megatron-LM binary format).

On-disk layout (binary-compatible with Megatron's ``MMapIndexedDataset`` so
existing corpora import unchanged):

- ``{path}.bin`` — the concatenated sample arrays;
- ``{path}.idx`` — header ``MMIDIDX\\x00\\x00`` magic, uint64 version=1,
  uint8 dtype code, uint64 sequence count, uint64 document count, then
  int32 sizes[count], int64 pointers[count] (byte offsets), int64
  doc_idx[doc_count].

Reads are ``np.memmap`` views — no host copy until sliced, which keeps the
input pipeline off the training hot path.
"""

import os
import struct
from typing import Iterable, List, Optional, Sequence

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
# Megatron dtype codes
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float64, 7: np.float32, 8: np.uint16}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer (reference ``MMapIndexedDatasetBuilder``)."""

    def __init__(self, out_path_prefix: str, dtype=np.int32):
        self._prefix = out_path_prefix
        self._dtype = np.dtype(dtype)
        self._bin = open(data_file_path(out_path_prefix), "wb")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, array) -> None:
        arr = np.asarray(array, dtype=self._dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, other_prefix: str) -> None:
        """Append another dataset's samples (reference multi-worker merge)."""
        other = MMapIndexedDataset(other_prefix)
        assert other.dtype == self._dtype
        offset = len(self._sizes)
        with open(data_file_path(other_prefix), "rb") as f:
            while True:
                chunk = f.read(1 << 24)
                if not chunk:
                    break
                self._bin.write(chunk)
        self._sizes.extend(int(s) for s in other.sizes)
        self._doc_idx.extend(offset + int(d) for d in other.doc_idx[1:])

    def finalize(self) -> None:
        self._bin.close()
        sizes = np.asarray(self._sizes, dtype=np.int32)
        pointers = np.zeros(len(sizes), dtype=np.int64)
        if len(sizes):
            np.cumsum(sizes[:-1] * self._dtype.itemsize, out=pointers[1:])
        doc_idx = np.asarray(self._doc_idx, dtype=np.int64)
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", _CODES[self._dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(doc_idx.tobytes(order="C"))


class MMapIndexedDataset:
    """Reader (reference ``MMapIndexedDataset``): ``ds[i]`` → np array."""

    def __init__(self, path_prefix: str):
        self._prefix = path_prefix
        with open(index_file_path(path_prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{index_file_path(path_prefix)}: bad magic "
                                 f"{magic!r} (not an MMapIndexedDataset)")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != 1:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(_DTYPES[code])
            (count,) = struct.unpack("<Q", f.read(8))
            (doc_count,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_mm = np.memmap(index_file_path(path_prefix), mode="r",
                           dtype=np.uint8)
        self.sizes = idx_mm[offset:offset + 4 * count].view(np.int32)
        p0 = offset + 4 * count
        self.pointers = idx_mm[p0:p0 + 8 * count].view(np.int64)
        d0 = p0 + 8 * count
        self.doc_idx = idx_mm[d0:d0 + 8 * doc_count].view(np.int64)
        # np.memmap rejects empty files; a finalized-but-empty dataset (e.g.
        # an analyzer worker whose shard was empty) reads as zero samples
        if os.path.getsize(data_file_path(path_prefix)) == 0:
            self._data = np.zeros((0,), dtype=np.uint8)
        else:
            self._data = np.memmap(data_file_path(path_prefix), mode="r",
                                   dtype=np.uint8)

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        ptr = int(self.pointers[i])
        size = int(self.sizes[i])
        return self._data[ptr:ptr + size * self.dtype.itemsize].view(self.dtype)

    def get(self, i: int, offset: int = 0, length: Optional[int] = None):
        """Sub-range of sample i without materializing the rest."""
        full = self[i]
        end = len(full) if length is None else offset + length
        return full[offset:end]

    def as_array(self) -> np.ndarray:
        """The whole dataset as one flat array (vectorized read) — only
        meaningful when every sample has the same element count, e.g. the
        analyzer's one-scalar-per-sample metric files."""
        return np.asarray(self._data.view(self.dtype) if len(self._data)
                          else np.zeros((0,), self.dtype))

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return (os.path.exists(data_file_path(path_prefix))
                and os.path.exists(index_file_path(path_prefix)))


def make_builder(out_prefix: str, dtype=np.int32) -> MMapIndexedDatasetBuilder:
    return MMapIndexedDatasetBuilder(out_prefix, dtype=dtype)
