"""Random layerwise token dropping (random-LTD).

TPU-native analogue of reference ``runtime/data_pipeline/data_routing/``
(``RandomLTDScheduler`` scheduler.py:38) + the CUDA kernels
``csrc/random_ltd/{token_sort.cu,gather_scatter.cu}``: middle layers run on
a random subset of tokens; kept-token count ramps up over training. The
gather/scatter kernels become ``jnp.take_along_axis`` /
``.at[].set`` (XLA lowers these to efficient dynamic-slice/DUS on TPU);
random sampling uses a sorted random permutation so kept tokens stay in
causal order (the reference's token_sort kernel).
"""

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def sample_kept_tokens(rng: jax.Array, seq_len: int, keep: int,
                       batch_size: int) -> jnp.ndarray:
    """[B, keep] sorted indices of kept tokens (causal order preserved)."""
    def one(key):
        perm = jax.random.permutation(key, seq_len)[:keep]
        return jnp.sort(perm)

    keys = jax.random.split(rng, batch_size)
    return jax.vmap(one)(keys)


def gather_tokens(x: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """[B, S, D], [B, K] -> [B, K, D] (csrc gather_scatter.cu:gather)."""
    return jnp.take_along_axis(x, indices[..., None], axis=1)


def scatter_tokens(full: jnp.ndarray, dropped: jnp.ndarray,
                   indices: jnp.ndarray) -> jnp.ndarray:
    """Write [B, K, D] back into [B, S, D] at indices (scatter kernel)."""
    B = full.shape[0]
    b_idx = jnp.arange(B)[:, None]
    return full.at[b_idx, indices].set(dropped)


def slice_attention_mask(mask: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """[1/B, 1, S, S] additive mask → sliced [B, 1, K, K]
    (csrc slice_attn_masks.cu)."""
    B = indices.shape[0]
    mask = jnp.broadcast_to(mask, (B,) + mask.shape[1:])
    rows = jnp.take_along_axis(mask, indices[:, None, :, None], axis=2)
    return jnp.take_along_axis(rows, indices[:, None, None, :], axis=3)


class RandomLTDScheduler:
    """Kept-token schedule (reference scheduler.py:38): linear ramp from
    ``random_ltd_schedule.min_value`` tokens to the full sequence."""

    def __init__(self, config: Dict[str, Any]):
        ltd = config.get("random_ltd", config)
        self.enabled = ltd.get("enabled", False)
        self.total_layers = ltd.get("total_layer_num", 0)
        self.ltd_layers = ltd.get("random_ltd_layer_num", 0)
        self.layer_ids = ltd.get("random_ltd_layer_id", [])
        sched = ltd.get("random_ltd_schedule", {})
        self.min_value = sched.get("min_value", 128)
        self.max_value = sched.get("max_value", 512)
        sconf = sched.get("schedule_config", {})
        self.total_steps = sconf.get("total_curriculum_step", 1000)
        self.difficulty_step = sconf.get("difficulty_step", 8)
        self.current_seq = self.min_value
        self.global_steps = 0

    def get_current_seq(self) -> int:
        return self.current_seq

    def update_seq(self, global_steps: int) -> int:
        frac = min(1.0, global_steps / max(self.total_steps, 1))
        v = self.min_value + frac * (self.max_value - self.min_value)
        v = int(v / self.difficulty_step) * self.difficulty_step
        self.current_seq = max(self.min_value, min(self.max_value, v))
        self.global_steps = global_steps
        return self.current_seq

    def state_dict(self):
        return {"current_seq": self.current_seq,
                "global_steps": self.global_steps}

    def load_state_dict(self, sd):
        self.current_seq = sd["current_seq"]
        self.global_steps = sd["global_steps"]
