"""MoQ — Mixture-of-Quantization training quantizer
(reference ``runtime/quantize.py:14``).

MoQ reduces weight precision during training on a period schedule, with an
optional eigenvalue signal: when provided, a layer's quantization period
stretches by its Hessian eigenvalue relative to the max (sensitive layers —
large curvature — keep precision longer). Quantization itself reuses the
compression fake-quant kernels (symmetric/asymmetric, per-group).
"""

from types import SimpleNamespace
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


class Quantizer:
    """reference ``Quantizer`` (runtime/quantize.py:14). Knobs mirror the
    ``quantize_training`` config section: q_start_bits/q_target_bits,
    q_period (steps between bit reductions), q_rounding, q_type,
    q_groups, use_quantizer_kernel (accepted; XLA path always)."""

    def __init__(self, q_start_bits: int = 16, q_target_bits: int = 8,
                 q_period: int = 100, q_rounding: str = "nearest",
                 q_type: str = "symmetric", q_groups: int = 1,
                 q_verbose: bool = False, use_quantizer_kernel: bool = False,
                 layer_name: str = "layer_"):
        self.q_start_bits = q_start_bits
        self.q_target_bits = q_target_bits
        self.q_period = q_period
        self.q_rounding = q_rounding
        self.q_type = q_type
        self.q_groups = q_groups
        self.q_verbose = q_verbose
        self.layer_name = layer_name
        self.qsteps = 0
        # per-layer current bits, lazily sized on first quantize()
        self.bits: Dict[str, int] = {}
        self.periods: Dict[str, int] = {}
        self._jit_cache: Dict[Any, Any] = {}

    def _layer_of(self, path: str) -> Optional[str]:
        for part in path.split("/"):
            if part.startswith(self.layer_name):
                return part
        return None

    def update_eigenvalues(self, eigenvalues: List[float],
                           layer_names: List[str]) -> None:
        """Stretch each layer's period by its relative eigenvalue
        (reference: period[i] *= eigenvalue[i]/max)."""
        if not eigenvalues:
            return
        mx = max(eigenvalues)
        for name, ev in zip(layer_names, eigenvalues):
            self.periods[name] = max(
                self.q_period, int(round(self.q_period * (1 + ev / mx))))

    def _bits_for(self, layer: Optional[str]) -> int:
        key = layer or "__global__"
        if key not in self.bits:
            self.bits[key] = self.q_start_bits
        period = self.periods.get(key, self.q_period)
        reductions = self.qsteps // period
        bits = max(self.q_target_bits, self.q_start_bits - reductions)
        if bits != self.bits[key] and self.q_verbose:
            logger.info(f"MoQ: {key} precision → {bits} bits "
                        f"(step {self.qsteps})")
        self.bits[key] = bits
        return bits

    def _apply_fn(self, bits_sig):
        """One jitted whole-tree quantize program per distinct per-layer
        bit layout (bit widths are compile-time constants; the step index
        stays traced so stochastic rounding doesn't recompile)."""
        if bits_sig in self._jit_cache:
            return self._jit_cache[bits_sig]

        from deepspeed_tpu.compression.compress import _fake_quant

        mapping = dict(bits_sig)
        shared = SimpleNamespace(quantize_groups=self.q_groups,
                                 rounding=self.q_rounding,
                                 quantization_type=self.q_type)

        def apply(params, step):
            def visit(path, leaf):
                p = "/".join(str(getattr(k, "key", k)) for k in path)
                bits = mapping.get(p)
                if bits is None:
                    return leaf
                q = _fake_quant(leaf.astype(jnp.float32), float(bits),
                                shared, step)
                return q.astype(leaf.dtype)

            return jax.tree_util.tree_map_with_path(visit, params)

        fn = jax.jit(apply)
        self._jit_cache[bits_sig] = fn
        return fn

    def quantize(self, params: Any, overflow: bool = False) -> Any:
        """Fake-quantize 2D+ kernels at each layer's current bit-width
        (straight-through; the engine calls this at GAS boundaries —
        reference engine.py:1984). Skipped on fp16 overflow steps."""
        if overflow:
            return params
        self.qsteps += 1

        sig = []
        for path, leaf in jax.tree_util.tree_leaves_with_path(params):
            p = "/".join(str(getattr(k, "key", k)) for k in path)
            if not hasattr(leaf, "ndim") or leaf.ndim < 2 or "kernel" not in p:
                continue
            bits = self._bits_for(self._layer_of(p))
            if bits < 16:
                sig.append((p, bits))
        if not sig:
            return params
        fn = self._apply_fn(tuple(sig))
        return fn(params, jnp.asarray(self.qsteps, jnp.int32))
