"""Static and dynamic loss scaling (reference ``runtime/fp16/loss_scaler.py``
:66/:90/:203). Pure-functional: scaler state is a small pytree carried through
the jitted train step; overflow is detected from non-finite grads and the
step is skipped inside jit with ``jnp.where`` (no host round-trip).

bf16 training doesn't need this — it is wired only when fp16.enabled.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class LossScalerState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar
    overflow_streak: jnp.ndarray  # consecutive good steps since last overflow
    hysteresis: jnp.ndarray     # remaining tolerated overflows before cut


def make_static_scaler_state(scale: float) -> LossScalerState:
    return LossScalerState(
        scale=jnp.asarray(scale, jnp.float32),
        overflow_streak=jnp.zeros((), jnp.int32),
        hysteresis=jnp.asarray(2, jnp.int32),
    )


def make_dynamic_scaler_state(initial_scale_power: int = 16,
                              hysteresis: int = 2) -> LossScalerState:
    return LossScalerState(
        scale=jnp.asarray(2.0 ** initial_scale_power, jnp.float32),
        overflow_streak=jnp.zeros((), jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32),
    )


def grads_finite(grads: Any) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(grads)
    finite = jnp.asarray(True)
    for g in leaves:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
    return finite


def update_scaler(state: LossScalerState, finite: jnp.ndarray,
                  dynamic: bool, scale_window: int = 1000,
                  scale_factor: float = 2.0, min_scale: float = 1.0,
                  hysteresis: int = 2) -> LossScalerState:
    """reference DynamicLossScaler.update_scale (:139)."""
    if not dynamic:
        return state
    hyst = jnp.where(finite, state.hysteresis, state.hysteresis - 1)
    cut = jnp.logical_and(~finite, hyst <= 0)
    new_scale = jnp.where(
        cut, jnp.maximum(state.scale / scale_factor, min_scale), state.scale)
    streak = jnp.where(finite, state.overflow_streak + 1, 0)
    grow = jnp.logical_and(finite, streak >= scale_window)
    new_scale = jnp.where(grow, new_scale * scale_factor, new_scale)
    streak = jnp.where(grow, 0, streak)
    hyst = jnp.where(cut | grow, jnp.asarray(hysteresis, jnp.int32), hyst)
    return LossScalerState(scale=new_scale, overflow_streak=streak, hysteresis=hyst)
