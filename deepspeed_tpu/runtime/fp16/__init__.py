from deepspeed_tpu.runtime.fp16.loss_scaler import (  # noqa: F401
    LossScalerState,
    grads_finite,
    make_dynamic_scaler_state,
    make_static_scaler_state,
    update_scaler,
)
