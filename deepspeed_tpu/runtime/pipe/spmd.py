"""SPMD pipeline parallelism over the ``pipe`` mesh axis.

TPU-native replacement for the reference's process-based pipeline engine
(``deepspeed/runtime/pipe/engine.py:42`` with p2p send/recv + the
instruction interpreter ``_exec_schedule`` :1293). On TPU all stages run the
same program (SPMD): each stage holds a shard of the layer stack, and
activations move between stages with a single ``lax.ppermute`` per step —
the ICI-native analogue of the reference's meta+tensor p2p handshake
(pipe/engine.py:795-913).

The loop below *is* the GPipe schedule: over ``M + P - 1`` ticks, stage 0
feeds a new microbatch each tick while downstream stages process what the
ring delivered; differentiating through the loop (lax.scan of ppermute +
block application) yields the backward pipeline automatically, so no
separate backward instruction stream is needed. 1F1B's memory advantage is
recovered with per-block rematerialization instead of schedule reordering.

Used inside ``shard_map`` with the layer-stacked parameters sharded over the
pipe axis (leading layer dim), e.g. the scan-over-layers LLaMA params.
"""

import functools
from typing import Any, Callable

import jax
from deepspeed_tpu.utils.jax_compat import varying_cast, axis_size
import jax.numpy as jnp
from jax import lax


def spmd_pipeline(block_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                  local_params: Any,
                  microbatches: jnp.ndarray,
                  *,
                  axis_name: str = "pipe",
                  num_stages: int = None) -> jnp.ndarray:
    """Run ``microbatches`` through a P-stage pipeline. Call inside shard_map.

    Args:
      block_fn: applies this stage's local layer stack: (local_params, x) -> y.
      local_params: this stage's parameter shard (leading dim = layers/stage).
      microbatches: [M, ...] microbatch activations entering stage 0.
      axis_name: mesh axis carrying the stages.
      num_stages: defaults to the axis size.

    Returns [M, ...] outputs as produced by the last stage (valid on every
    stage — they are rotated back around the ring so the result is replicated
    over the pipe axis).
    """
    P = num_stages or axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + P - 1

    # mark the carries as device-varying over the pipe axis (their values
    # differ per stage once the ring starts turning)
    def _varying(x):
        return varying_cast(x, (axis_name,))

    state = _varying(jnp.zeros_like(microbatches[0]))
    outputs = _varying(jnp.zeros_like(microbatches))

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clamped); others take the ring value
        inp = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, inp, state)
        y = block_fn(local_params, x)
        # the last stage emits microbatch t-(P-1)
        out_idx = jnp.clip(t - (P - 1), 0, M - 1)
        emit = jnp.logical_and(stage == P - 1, t >= P - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, axis=0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(emit, y, cur), out_idx, axis=0)
        # rotate: stage i -> stage i+1 (last stage's y wraps to 0, ignored)
        state = lax.ppermute(y, axis_name,
                             [(i, (i + 1) % P) for i in range(P)])
        return (state, outputs), None

    (state, outputs), _ = lax.scan(tick, (state, outputs), jnp.arange(T))
    # replicate results over the pipe axis so loss math is stage-agnostic
    outputs = lax.psum(jnp.where(stage == P - 1, outputs, jnp.zeros_like(outputs)),
                       axis_name)
    return outputs


def pipeline_partition(num_items: int, num_parts: int, part: int):
    """Balanced contiguous partition bounds (reference
    ``deepspeed/runtime/utils.py:603`` partition_balanced for uniform case)."""
    base = num_items // num_parts
    extra = num_items % num_parts
    start = part * base + min(part, extra)
    size = base + (1 if part < extra else 0)
    return start, start + size
