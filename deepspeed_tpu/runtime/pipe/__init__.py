from deepspeed_tpu.runtime.pipe.schedule import (
    InferenceSchedule, PipeInstruction, PipeSchedule, TrainSchedule,
)
from deepspeed_tpu.runtime.pipe.spmd import pipeline_partition, spmd_pipeline
