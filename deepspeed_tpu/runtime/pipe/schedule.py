"""Pipeline schedules as instruction streams.

Keeps the reference's genuinely good design (``runtime/pipe/schedule.py``:
``PipeSchedule`` yielding ``PipeInstruction`` lists; ``TrainSchedule`` :189
1F1B, ``InferenceSchedule`` :135) as a first-class, testable artifact. On
TPU the SPMD executor (pipe/spmd.py) realizes the same dataflow implicitly,
but the schedules remain the source of truth for step-count/bubble math,
the wall-clock model used by the autotuner, and for a future
instruction-interpreting executor over ``ppermute``.
"""

from typing import Iterator, List


class PipeInstruction:
    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class ForwardPass(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class BackwardPass(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class SendActivation(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class RecvActivation(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class SendGrad(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class RecvGrad(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class PipeSchedule:
    """Base schedule: yields lists of instructions per step."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def wall_clock_ticks(self) -> int:
        """Global ticks to drain the schedule (each tick ≈ one stage
        compute unit)."""
        raise NotImplementedError

    def bubble_fraction(self) -> float:
        """Idle fraction per stage: 1 - useful_ticks / wall_clock_ticks().
        For fill-drain/1F1B this is (P-1)/(M+P-1) — the model the
        autotuner uses to order num_micro candidates, and the reason
        micro-batch count M should exceed the stage count P."""
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference schedule.py:135)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            valid = 0 <= micro_batch_id < self.micro_batches
            if valid:
                buf = self._buffer_idx(micro_batch_id)
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=buf))
                else:
                    cmds.append(RecvActivation(buffer_id=buf))
                cmds.append(ForwardPass(buffer_id=buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=buf))
            yield cmds

    def wall_clock_ticks(self) -> int:
        return self.micro_batches + self.stages - 1

    def bubble_fraction(self) -> float:
        return (self.stages - 1) / self.wall_clock_ticks()

    def num_pipe_buffers(self) -> int:
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B (reference schedule.py:189): warmup forwards, steady-state
    alternating 1 forward / 1 backward, cooldown backwards, then reduce+step.
    """

    def steps(self):
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            cmds: List[PipeInstruction] = []
            valid = 0 <= micro_batch_id < self.micro_batches
            if valid:
                buf = self._buffer_idx(micro_batch_id)
                if is_forward:
                    if self.is_first_stage:
                        cmds.append(LoadMicroBatch(buffer_id=buf))
                    else:
                        cmds.append(RecvActivation(buffer_id=buf))
                    cmds.append(ForwardPass(buffer_id=buf))
                    if not self.is_last_stage:
                        cmds.append(SendActivation(buffer_id=buf))
                else:
                    if not self.is_last_stage:
                        cmds.append(RecvGrad(buffer_id=buf))
                    cmds.append(BackwardPass(buffer_id=buf))
                    if not self.is_first_stage:
                        cmds.append(SendGrad(buffer_id=buf))
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds

    def _step_to_micro_batch(self, step_id: int):
        """Even steps forward, odd steps backward, offset by stage position
        so forward of stage s for microbatch m lands at step 2m + s and the
        matching backward at 2(m + stages - 1) - s + 1."""
        if _is_even(step_id) == _is_even(self.stage_id):
            micro_batch_id = (step_id - self.stage_id) // 2
            return micro_batch_id, True
        micro_batch_id = (step_id - 2 * (self.stages - 1) + self.stage_id - 1) // 2
        return micro_batch_id, False

    def wall_clock_ticks(self) -> int:
        return 2 * (self.micro_batches + self.stages - 1)

    def bubble_fraction(self) -> float:
        # each stage does 2M useful ticks of the total
        return 1.0 - 2 * self.micro_batches / self.wall_clock_ticks()

    def num_pipe_buffers(self) -> int:
        """In-flight activations at this stage (1F1B memory bound)."""
        return max(2, min(self.micro_batches, self.stages - self.stage_id))


def _is_even(x: int) -> bool:
    return x % 2 == 0
