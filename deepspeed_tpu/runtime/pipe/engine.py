"""PipelineEngine — pipeline-parallel training.

TPU-native analogue of reference ``runtime/pipe/engine.py:42``
(``PipelineEngine``) + ``pipe/module.py`` (``PipelineModule``): instead of a
subclassed engine interpreting instruction streams over p2p sockets, the
pipeline is a *loss function*: inside one ``shard_map`` over the
``(pipe, data)`` mesh axes, the scan-stacked transformer blocks (leading
layer dim sharded over ``pipe``) run through the collective-permute pipeline
(pipe/spmd.py), embedding/head/loss compute replicated per stage, and
``jax.grad`` differentiates straight through — the backward 1F1B emerges
from the transpose of the forward schedule. The engine machinery (ZeRO-1
optimizer sharding, grad accumulation, fp16, checkpointing) is inherited
unchanged from DeepSpeedEngine.

Layer placement: the scan-stacked params' leading dim is the LayerSpec list;
sharding it over ``pipe`` IS ``PipelineModule.partition_layers`` with
uniform balancing (parts from runtime/utils.partition_uniform).
"""

from typing import Any, Dict, Optional

import jax
from deepspeed_tpu.utils.jax_compat import shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from deepspeed_tpu.models.llama import LlamaConfig
from deepspeed_tpu.models.transformer import make_causal_mask
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.spmd import spmd_pipeline
from deepspeed_tpu.utils.logging import log_dist, logger


def _pipe_block_specs(mesh) -> Dict[str, Any]:
    """in_specs for the pipeline loss shard_map: blocks sharded over pipe,
    everything else replicated across pipe and data."""
    return {
        "blocks": PartitionSpec("pipe"),
        "other": PartitionSpec(),
    }


def make_pipeline_lm_loss(cfg: LlamaConfig, mesh, num_micro: Optional[int] = None):
    """Causal-LM loss with the block stack pipelined over the pipe axis.

    Expects LlamaModel(scan_layers=True) parameters: ``blocks/block/...``
    leaves with leading dim num_layers (sharded over 'pipe' by the
    PipelineEngine's sharding rules).
    """
    from deepspeed_tpu.models.llama import LlamaBlock, LlamaModel

    P_pipe = mesh.shape["pipe"]
    P_data = mesh.shape["data"]
    M = num_micro or max(P_pipe, 1)
    block = LlamaBlock(cfg)

    def loss_fn(params, batch, rngs=None):
        blocks = params["blocks"]["block"]
        rest = {k: v for k, v in params.items() if k != "blocks"}

        def inner(blocks_local, rest_rep, input_ids, labels):
            B_loc, S = input_ids.shape
            embed_tab = rest_rep["embed_tokens"]["embedding"]
            x = embed_tab[input_ids].astype(cfg.dtype)
            mask = make_causal_mask(S)

            assert B_loc % M == 0, (
                f"local batch {B_loc} must divide into {M} pipeline microbatches")
            micro = x.reshape(M, B_loc // M, S, x.shape[-1])
            # positions are the same arange for every full-sequence microbatch;
            # [1, S] broadcasts over the microbatch dim inside rotary
            upos = jnp.arange(S, dtype=jnp.int32)[None, :]

            def stage_fn(local_blocks, xm):
                # apply this stage's layer shard sequentially
                def layer(x, layer_params):
                    y = block.apply({"params": layer_params}, x, mask, upos)
                    return y, None

                y, _ = lax.scan(layer, xm, local_blocks)
                return y

            y = spmd_pipeline(stage_fn, blocks_local, micro, axis_name="pipe")
            y = y.reshape(B_loc, S, -1)

            # final norm + head (replicated per stage)
            scale = rest_rep["final_norm"]["scale"]
            y32 = y.astype(jnp.float32)
            var = jnp.mean(jnp.square(y32), axis=-1, keepdims=True)
            y = (y32 * lax.rsqrt(var + cfg.rms_norm_eps) * scale).astype(cfg.dtype)
            if cfg.tie_embeddings:
                logits = (y.astype(jnp.float32) @ embed_tab.T.astype(jnp.float32))
            else:
                logits = y @ rest_rep["lm_head"]["kernel"].astype(cfg.dtype)
            logits = logits.astype(jnp.float32)

            valid = labels != -100
            safe = jnp.where(valid, labels, 0)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
            loss_sum = jnp.sum(jnp.where(valid, -ll, 0.0))
            count = jnp.sum(valid)
            # average over the full global batch (sum over data shards)
            loss_sum = lax.psum(loss_sum, "data")
            count = lax.psum(count, "data")
            return loss_sum / jnp.maximum(count, 1)

        return shard_map(
            inner, mesh=mesh,
            in_specs=(PartitionSpec("pipe"), PartitionSpec(),
                      PartitionSpec("data"), PartitionSpec("data")),
            out_specs=PartitionSpec(),
        )(blocks, rest, batch["input_ids"], batch["labels"])

    return loss_fn


def pipeline_sharding_rules(tp: bool = False):
    """Extra rules: stacked block params shard their layer dim over pipe.
    With ``tp``, the non-layer dims additionally ride the tensor axis
    (matching interpreter.tp_block_specs) so block weights are STORED at
    1/(pipe*tp) per device — the Megatron PP x TP composition
    (reference pipe/topology.py:244)."""
    from deepspeed_tpu.parallel.partition import DEFAULT_TP_RULES, TENSOR_AXIS

    if tp:
        block_rules = [
            (r"blocks/block/.*(q_proj|k_proj|v_proj|gate_proj|up_proj)"
             r".*kernel", ("pipe", None, TENSOR_AXIS)),
            (r"blocks/block/.*(o_proj|down_proj).*kernel",
             ("pipe", TENSOR_AXIS, None)),
            (r"blocks/block/.*", ("pipe", None, None)),
            (r"blocks/block/.*scale", ("pipe", None)),
        ]
    else:
        block_rules = [(r"blocks/block/.*", ("pipe", None, None)),
                       (r"blocks/block/.*scale", ("pipe", None))]
    return [*block_rules, *DEFAULT_TP_RULES]


class PipelineEngine(DeepSpeedEngine):
    """Engine whose loss pipelines the model over the pipe axis. Use via
    ``deepspeed_tpu.initialize(..., model_config=cfg)`` with a mesh whose
    pipe axis > 1 (the analogue of passing a PipelineModule)."""

    def __init__(self, model=None, model_config: Optional[LlamaConfig] = None,
                 num_micro: Optional[int] = None, **kwargs):
        cfg = model_config or getattr(model, "cfg", None)
        assert cfg is not None, "PipelineEngine needs the model config"
        assert cfg.scan_layers, "PipelineEngine requires scan_layers=True " \
            "(stacked blocks are the LayerSpec list)"
        mesh = kwargs.get("mesh")
        assert mesh is not None, "PipelineEngine needs an explicit mesh"
        assert cfg.num_layers % mesh.shape["pipe"] == 0, (
            f"{cfg.num_layers} layers must divide pipe={mesh.shape['pipe']}")
        ds_cfg = kwargs.get("config")
        pipe_cfg = getattr(ds_cfg, "pipeline", None)
        schedule = getattr(pipe_cfg, "schedule", "auto")
        if num_micro is None:
            num_micro = getattr(pipe_cfg, "num_micro", None)
        tp = mesh.shape.get("tensor", 1)
        sp = mesh.shape.get("sequence", 1)
        n_kv = cfg.num_kv_heads or cfg.num_heads
        # the TP interpreter shards heads: indivisible MQA/GQA configs and
        # non-XLA attention impls keep the GSPMD-gpipe path (which handles
        # both), instead of crashing mid-trace
        tp_interpretable = (tp == 1 or (
            cfg.num_heads % tp == 0 and n_kv % tp == 0
            and cfg.attention_impl in ("auto", "xla")))
        if schedule == "auto":
            # 1F1B keeps tensor sharding inside the pipe loop (the
            # interpreter's TP block fn, interpreter.make_tp_block_fn);
            # sequence parallelism, indivisible MQA/GQA head counts, and
            # non-XLA attention impls keep the SPMD-gpipe path (GSPMD
            # threads those shardings/kernels; the interpreter's explicit
            # specs don't)
            schedule = "gpipe" if (sp > 1 or not tp_interpretable) \
                else "1f1b"
            if schedule == "gpipe" and (sp > 1 or tp > 1):
                log_dist("pipeline.schedule=auto → gpipe: "
                         + (f"mesh has sequence={sp}" if sp > 1 else
                            f"tensor={tp} with heads {cfg.num_heads}/"
                            f"kv {n_kv} or attention_impl="
                            f"{cfg.attention_impl!r} outside the TP "
                            f"interpreter's scope"), ranks=[0])
        elif schedule == "1f1b" and sp > 1:
            raise ValueError(
                "pipeline.schedule=1f1b does not compose with "
                f"sequence={sp}: the interpreter does not thread "
                "sequence-parallel attention — use schedule=gpipe (or "
                "'auto')")
        elif schedule == "1f1b" and not tp_interpretable:
            raise ValueError(
                f"pipeline.schedule=1f1b with tensor={tp}: the TP "
                f"interpreter shards attention heads ({cfg.num_heads} "
                f"heads / {n_kv} kv heads must both divide tensor) and "
                f"supports attention_impl auto/xla only (got "
                f"{cfg.attention_impl!r}) — use schedule=gpipe (or "
                f"'auto')")
        if schedule == "1f1b":
            # instruction-executing 1F1B (pipe/interpreter.py — reference
            # _exec_schedule, pipe/engine.py:1293)
            from deepspeed_tpu.runtime.pipe.interpreter import (
                make_1f1b_lm_loss,
            )

            loss_fn = make_1f1b_lm_loss(cfg, mesh, num_micro)
        elif schedule == "gpipe":
            # SPMD fill-drain with remat standing in for 1F1B memory
            loss_fn = make_pipeline_lm_loss(cfg, mesh, num_micro)
        else:
            raise ValueError(
                f"pipeline.schedule={schedule!r}: supported schedules are "
                f"'1f1b' (instruction interpreter) and 'gpipe' (SPMD "
                f"fill-drain); 'interleaved' is not implemented")
        if kwargs.get("sharding_rules") is None:
            kwargs["sharding_rules"] = pipeline_sharding_rules(
                tp=schedule == "1f1b" and tp > 1)
        super().__init__(model=model, loss_fn=loss_fn, **kwargs)
        self.num_stages = mesh.shape["pipe"]
        self.pipe_schedule = schedule
        self.num_micro = num_micro or self.num_stages
        # surface the bubble (reference never reports it; with M=P it is
        # ~50% — raising pipeline.num_micro shrinks it as (P-1)/(M+P-1))
        from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule

        self.bubble_fraction = TrainSchedule(
            self.num_micro, self.num_stages, 0).bubble_fraction()
        # dsttrain schedule observability (docs/OBSERVABILITY.md): the
        # static bubble next to the measured schedule-efficiency gauge
        # _after_step maintains, plus microbatch lanes in the step trace
        # (reconstructed from tick_plan — 1F1B only; the gpipe fill-drain
        # executes a different tick mapping, so no lanes are faked there)
        self._pipe_bubble = self.bubble_fraction
        self.metrics.set_gauge("train.pipeline.bubble_fraction",
                               self.bubble_fraction)
        self.metrics.set_gauge("train.pipeline.num_micro", self.num_micro)
        self.metrics.set_gauge("train.pipeline.stages", self.num_stages)
        if schedule == "1f1b":
            self._pipe_lane_info = (self.num_micro, self.num_stages)
        log_dist(f"PipelineEngine: {self.num_stages} stages x "
                 f"{cfg.num_layers // self.num_stages} layers "
                 f"({schedule}, {self.num_micro} microbatches, "
                 f"bubble {self.bubble_fraction:.0%})", ranks=[0])
