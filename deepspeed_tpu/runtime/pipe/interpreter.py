"""1F1B schedule EXECUTION over the pipe mesh axis.

TPU-native analogue of the reference's instruction interpreter
(``deepspeed/runtime/pipe/engine.py:1293`` ``_exec_schedule`` running
``TrainSchedule`` — schedule.py:189): the same warmup/steady/cooldown 1F1B
timing, executed for real rather than approximated by GPipe+remat.

SPMD mechanics (all stages run ONE program inside ``shard_map``):

- Each global tick, a stage either runs a ForwardPass or a BackwardPass —
  ``lax.cond`` on the (device-varying) stage index; the tick→(microbatch,
  direction) mapping is the **same arithmetic as TrainSchedule**
  (``_step_to_micro_batch``), unit-tested equal to its instruction stream.
- SendActivation/RecvActivation and SendGrad/RecvGrad become two
  unconditional ``lax.ppermute`` rings per tick (fwd ring s→s+1, grad ring
  s→s-1); invalid slots carry zeros. A value sent at the end of tick t is
  consumed at tick t+1 — exactly the reference's p2p handshake timing.
- BackwardPass recomputes the stage forward from the SAVED stage input
  (activation-checkpoint style, one residual per in-flight microbatch —
  the 1F1B memory bound: ``min(M, P)`` buffers instead of GPipe's M) and
  applies ``jax.vjp`` with the received output-gradient as cotangent. The
  last stage seeds the chain from the loss; the first stage backprops into
  the embedding.
- Parameter gradients accumulate across BackwardPasses (ReduceGrads =
  the closing psums), and the whole (loss, grads) computation is wrapped in
  ``jax.custom_vjp`` so the engine's ``jax.value_and_grad`` consumes it
  unchanged (the loss cotangent — e.g. the fp16 loss scale — multiplies
  the saved gradients).

Model-agnostic: the executor takes (embed_fn, block_fn, head_loss_fn), so
any scan-stacked flax block pipelines — the LayerSpec-generality the
SPMD-GPipe path lacked (VERDICT r1 #5).
"""

from typing import Any, Callable, Optional

import jax
from deepspeed_tpu.utils.jax_compat import (
    LEGACY_SHARD_MAP_KW, axis_size, shard_map, varying_cast, vma_of,
)
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec

TICK_FWD, TICK_BWD, TICK_IDLE = 1, 0, -1


def tick_plan(t: int, stage: int, num_micro: int, num_stages: int):
    """(micro_batch, direction) executed by ``stage`` at global tick ``t``.

    THE schedule arithmetic (TrainSchedule._step_to_micro_batch, reference
    schedule.py:189) — shared between this executor and the test that
    cross-checks it against the instruction stream. Works on python ints
    and traced arrays alike.
    """
    fwd = (t % 2) == (stage % 2)
    mb_f = (t - stage) // 2
    mb_b = (t - 2 * (num_stages - 1) + stage - 1) // 2
    if isinstance(t, (int, np.integer)):
        if fwd and 0 <= mb_f < num_micro:
            return mb_f, TICK_FWD
        if (not fwd) and 0 <= mb_b < num_micro:
            return mb_b, TICK_BWD
        return -1, TICK_IDLE
    do_f = jnp.logical_and(fwd, jnp.logical_and(mb_f >= 0, mb_f < num_micro))
    do_b = jnp.logical_and(~fwd, jnp.logical_and(mb_b >= 0, mb_b < num_micro))
    return (mb_f, mb_b), (do_f, do_b)


def schedule_bubble_fraction(num_micro: int, num_stages: int) -> float:
    """Closed-form 1F1B bubble fraction derived by COUNTING
    :func:`tick_plan` idle ticks — the cross-check the dsttrain gauge
    ``train.pipeline.bubble_fraction`` is pinned against
    (tests/unit/test_dsttrain.py): every stage does 2M useful ticks of
    the 2(M+P-1) total, so the idle fraction is (P-1)/(M+P-1), exactly
    ``TrainSchedule.bubble_fraction()``."""
    T = 2 * (num_micro + num_stages - 1)
    if T <= 0 or num_stages <= 0:
        return 0.0
    idle = sum(
        1 for s in range(num_stages) for t in range(T)
        if tick_plan(t, s, num_micro, num_stages)[1] == TICK_IDLE)
    return idle / (T * num_stages)


def exec_1f1b(embed_fn: Callable, block_fn: Callable, head_loss_fn: Callable,
              blocks_local: Any, rest: Any,
              input_ids: jnp.ndarray, labels: jnp.ndarray,
              num_micro: int, *, axis_name: str = "pipe",
              data_axis: Optional[str] = "data", dtype=jnp.float32,
              blocks_extra_axes=None):
    """Run the 1F1B schedule; call inside shard_map over (pipe[, data]).

    embed_fn(rest, ids[mb, S]) -> activations [mb, S, D]
    block_fn(blocks_local, x) -> y          (this stage's layer shard)
    head_loss_fn(rest, y, labels) -> (loss_sum, token_count)

    Returns (mean_loss [replicated], blocks_grads, rest_grads) — gradients
    of the GLOBAL mean loss.
    """
    P = axis_size(axis_name)
    s = lax.axis_index(axis_name)
    M = num_micro
    is_first = s == 0
    is_last = s == P - 1
    B_loc, S = input_ids.shape
    assert B_loc % M == 0, (
        f"local batch {B_loc} must divide into {M} microbatches")
    ids_mb = input_ids.reshape(M, B_loc // M, S)
    labels_mb = labels.reshape(M, B_loc // M, S)

    # activation shape probe (static): one embed under eval_shape
    act_shape = jax.eval_shape(lambda r, i: embed_fn(r, i),
                               rest, ids_mb[0]).shape
    n_buf = max(2, min(M, P))

    all_axes = (axis_name,) + ((data_axis,) if data_axis else ())
    # Individual block leaves may additionally vary over TP-style axes
    # (``blocks_extra_axes``: per-leaf tuples, e.g. ("tensor",) for the
    # sharded kernels, () for tensor-replicated norm scales): the weight
    # shards genuinely differ per rank there. Activations stay INVARIANT
    # over those axes — a TP block_fn psums its partial outputs, and AD's
    # pvary/psum transposition then inserts the Megatron-style backward
    # input-grad reductions automatically (legal inside the cond branches:
    # the tick predicate varies over pipe only, never over tensor).
    if blocks_extra_axes is None:
        blocks_extra_axes = jax.tree_util.tree_map(lambda _: (),
                                                   blocks_local)

    def _varying(x, axes=all_axes):
        """Mark ``x`` device-varying over every mapped axis it isn't yet.

        Critical for the cond branches below: if params stayed replicated
        over pipe/data, AD's vma promotion would transpose to psums INSIDE
        the branches over THOSE axes — collectives under a device-varying
        predicate deadlock. Pre-varying keeps the branches free of
        pipe/data collectives; the explicit psums after the scan do those
        reductions once, uniformly.

        Spelled through utils.jax_compat (``varying_cast``/``vma_of``) —
        the ``lax.pvary`` spelling deprecation-warns on current JAX and
        pre-vma JAX has no cast at all; the compat seam keeps this hot
        path warning-clean across the support window (pytest.ini turns
        DeprecationWarning into an error for this module).
        """
        have = vma_of(x)
        missing = tuple(a for a in axes if a not in have)
        return varying_cast(x, missing) if missing else x

    blocks_v = jax.tree_util.tree_map(
        lambda x, ax: _varying(x, all_axes + tuple(ax)),
        blocks_local, blocks_extra_axes)
    rest_v = jax.tree_util.tree_map(_varying, rest)
    zero_act = _varying(jnp.zeros(act_shape, dtype))
    acts0 = _varying(jnp.zeros((n_buf,) + act_shape, dtype))
    gb0 = jax.tree_util.tree_map(
        lambda p, ax: _varying(jnp.zeros(p.shape, jnp.float32),
                               all_axes + tuple(ax)),
        blocks_local, blocks_extra_axes)
    gr0 = jax.tree_util.tree_map(
        lambda p: _varying(jnp.zeros(p.shape, jnp.float32)), rest)

    fwd_perm = [(i, (i + 1) % P) for i in range(P)]
    bwd_perm = [(i, (i - 1) % P) for i in range(P)]

    def stage_obj(blocks_p, rest_p, x_saved, ids_b, labels_b, dy):
        """Scalar objective whose gradient is this stage's BackwardPass:
        last stage → the real loss; others → <y, received dy>. lax.cond on
        is_last keeps the vocab-projection head (often the dominant
        per-tick FLOP) off the P-1 non-last stages; both branches are
        collective-free, so the device-varying predicate is safe.
        aux = token count for the global loss mean."""
        # embed only on the first stage (same cond discipline as the head:
        # collective-free branches under a device-varying predicate) — the
        # P-1 other stages previously computed-and-discarded it every
        # backward tick (VERDICT r2 weak #6)
        x = lax.cond(
            is_first,
            lambda op: embed_fn(op[0], op[1]).astype(dtype),
            lambda op: op[2],
            (rest_p, ids_b, x_saved))
        y = block_fn(blocks_p, x)

        def head_branch(y):
            loss_sum, cnt = head_loss_fn(rest_p, y, labels_b)
            return loss_sum, _varying(jnp.asarray(cnt, jnp.int32))

        def flat_branch(y):
            flat = jnp.vdot(y.astype(jnp.float32), dy.astype(jnp.float32))
            return flat, _varying(jnp.zeros((), jnp.int32))

        return lax.cond(is_last, head_branch, flat_branch, y)

    def tick(carry, t):
        acts, recv_act, recv_grad, gb, gr, loss_sum, count = carry
        (mb_f, mb_b), (do_fwd, do_bwd) = tick_plan(t, s, M, P)
        mb_f_c = jnp.clip(mb_f, 0, M - 1)
        mb_b_c = jnp.clip(mb_b, 0, M - 1)
        buf_f = jnp.remainder(mb_f_c, n_buf)
        buf_b = jnp.remainder(mb_b_c, n_buf)

        # --- ForwardPass (LoadMicroBatch/RecvActivation folded in) -------
        def fwd_branch(args):
            acts, recv_act = args
            ids_f = lax.dynamic_index_in_dim(ids_mb, mb_f_c, 0,
                                             keepdims=False)
            x = lax.cond(
                is_first,
                lambda op: embed_fn(rest_v, op[0]).astype(dtype),
                lambda op: op[1],
                (ids_f, recv_act))
            y = block_fn(blocks_v, x)
            acts = lax.dynamic_update_index_in_dim(acts, x, buf_f, 0)
            return acts, y

        def fwd_skip(args):
            acts, _ = args
            return acts, zero_act

        acts, y_f = lax.cond(do_fwd, fwd_branch, fwd_skip, (acts, recv_act))

        # --- BackwardPass (recompute + vjp; RecvGrad folded in) ----------
        def bwd_branch(args):
            acts, recv_grad = args
            x_saved = lax.dynamic_index_in_dim(acts, buf_b, 0,
                                               keepdims=False)
            ids_b = lax.dynamic_index_in_dim(ids_mb, mb_b_c, 0,
                                             keepdims=False)
            lab_b = lax.dynamic_index_in_dim(labels_mb, mb_b_c, 0,
                                             keepdims=False)
            val, vjp, cnt = jax.vjp(
                lambda bp, rp, xs: stage_obj(bp, rp, xs, ids_b, lab_b,
                                             recv_grad),
                blocks_v, rest_v, x_saved, has_aux=True)
            # seed derived from val so it carries the same varying-axes
            # type (shard_map vma) as the differentiated output
            db, dr, dx = vjp(val * 0.0 + 1.0)
            # loss/count only meaningful at the last stage (cnt is already
            # zero elsewhere via stage_obj's cond)
            lsum = _varying(jnp.where(is_last, val, 0.0))
            return db, dr, dx.astype(dtype), lsum, cnt

        def bwd_skip(args):
            return (gb0, gr0, zero_act,
                    _varying(jnp.zeros((), jnp.float32)),
                    _varying(jnp.zeros((), jnp.int32)))

        db, dr, dx, lsum, cnt = lax.cond(do_bwd, bwd_branch, bwd_skip,
                                         (acts, recv_grad))
        gb = jax.tree_util.tree_map(jnp.add, gb, db)
        gr = jax.tree_util.tree_map(jnp.add, gr, dr)
        loss_sum = loss_sum + lsum
        count = count + cnt

        # --- SendActivation / SendGrad (unconditional rings) -------------
        send_act = jnp.where(jnp.logical_and(do_fwd, ~is_last), y_f,
                             zero_act)
        send_grad = jnp.where(jnp.logical_and(do_bwd, ~is_first), dx,
                              zero_act)
        recv_act = lax.ppermute(send_act, axis_name, fwd_perm)
        recv_grad = lax.ppermute(send_grad, axis_name, bwd_perm)
        return (acts, recv_act, recv_grad, gb, gr, loss_sum, count), None

    T = 2 * (M + P - 1)
    carry0 = (acts0, zero_act, zero_act, gb0, gr0,
              _varying(jnp.zeros((), jnp.float32)),
              _varying(jnp.zeros((), jnp.int32)))
    (acts, _, _, gb, gr, loss_sum, count), _ = lax.scan(
        tick, carry0, jnp.arange(T))

    # ReduceGrads/ReduceTiedGrads + loss aggregation: pipe-replicated parts
    # (embedding/head) sum over stages; everything averages over data
    axes = (axis_name,) + ((data_axis,) if data_axis else ())
    loss_sum = lax.psum(loss_sum, axes)
    count = lax.psum(count, axes)
    denom = jnp.maximum(count, 1).astype(jnp.float32)
    gr = jax.tree_util.tree_map(
        lambda g: lax.psum(g, axes) / denom, gr)
    gb = jax.tree_util.tree_map(
        lambda g: (lax.psum(g, data_axis) if data_axis else g) / denom, gb)
    return loss_sum / denom, gb, gr


def make_1f1b_loss(embed_fn, block_fn, head_loss_fn, mesh,
                   num_micro: int, dtype=jnp.float32,
                   block_key: str = "blocks", blocks_spec=None,
                   extra_axes=()):
    """Build an engine-compatible loss whose VJP runs :func:`exec_1f1b`.

    ``params[block_key]`` holds the layer-stacked block params (leading dim
    sharded over ``pipe``); everything else is pipe-replicated. The returned
    function is a ``jax.custom_vjp``: the forward computes loss AND
    gradients in one 1F1B execution, the backward hands the (cotangent-
    scaled) gradients to ``jax.value_and_grad`` — so DeepSpeedEngine's step
    machinery (fp16 scaling included) consumes it unchanged.

    ``blocks_spec``: optional pytree of PartitionSpecs for the block params
    (a TP-aware ``block_fn`` keeps its weight shards — dims beyond 'pipe'
    ride e.g. the 'tensor' axis); default replicates all non-layer dims.
    ``extra_axes``: the TP-style axes (e.g. ("tensor",)) appearing in
    blocks_spec — per-leaf vma typing is derived from the specs.
    """
    data_axis = "data" if "data" in mesh.axis_names else None
    blocks_axes = None
    if blocks_spec is not None:
        extra = set(extra_axes)
        blocks_axes = jax.tree_util.tree_map(
            lambda spec: tuple(a for a in spec if a in extra),
            blocks_spec, is_leaf=lambda x: isinstance(x, PartitionSpec))

    def _run(params, batch):
        blocks = params[block_key]
        rest = {k: v for k, v in params.items() if k != block_key}

        def inner(blocks_l, rest_r, ids, labels):
            loss, gb, gr = exec_1f1b(
                embed_fn, block_fn, head_loss_fn, blocks_l, rest_r, ids,
                labels, num_micro, axis_name="pipe", data_axis=data_axis,
                dtype=dtype, blocks_extra_axes=blocks_axes)
            return loss, gb, gr

        # batch shards over data only when the mesh has that axis (the
        # executor's data_axis=None handling must be reachable)
        batch_pspec = PartitionSpec(data_axis)
        b_spec = (PartitionSpec("pipe") if blocks_spec is None
                  else blocks_spec)
        loss, gb, gr = shard_map(
            inner, mesh=mesh, **LEGACY_SHARD_MAP_KW,
            in_specs=(b_spec, PartitionSpec(),
                      batch_pspec, batch_pspec),
            out_specs=(PartitionSpec(), b_spec,
                       PartitionSpec()),
        )(blocks, rest, batch["input_ids"], batch["labels"])
        grads = dict(gr)
        grads[block_key] = gb
        # cast grads to param dtypes (stage vjp accumulates in fp32)
        grads = jax.tree_util.tree_map(
            lambda g, p: g.astype(p.dtype), grads, params)
        return loss, grads

    @jax.custom_vjp
    def loss_fn(params, batch):
        loss, _ = _run(params, batch)
        return loss

    def fwd(params, batch):
        loss, grads = _run(params, batch)
        return loss, (grads, batch)

    def bwd(res, g):
        grads, batch = res
        scaled = jax.tree_util.tree_map(lambda x: x * g, grads)
        # integer batch arrays take float0 cotangents
        dbatch = jax.tree_util.tree_map(
            lambda x: np.zeros(x.shape, jax.dtypes.float0), batch)
        return scaled, dbatch

    loss_fn.defvjp(fwd, bwd)
    return loss_fn


def tp_block_specs(tp_axis: str = "tensor"):
    """PartitionSpecs for the stacked LlamaBlock tree under 1F1B x TP:
    layer dim over pipe, column-parallel kernels' output dim and
    row-parallel kernels' input dim over the tensor axis (the Megatron
    partitioning the reference composes with PP,
    runtime/pipe/topology.py:244)."""
    col = PartitionSpec("pipe", None, tp_axis)      # q/k/v, gate/up
    row = PartitionSpec("pipe", tp_axis, None)      # o, down
    vec = PartitionSpec("pipe", None)               # norm scales
    return {"block": {
        "attn": {"q_proj": {"kernel": col}, "k_proj": {"kernel": col},
                 "v_proj": {"kernel": col}, "o_proj": {"kernel": row}},
        "mlp": {"gate_proj": {"kernel": col}, "up_proj": {"kernel": col},
                "down_proj": {"kernel": row}},
        "input_norm": {"scale": vec},
        "post_attn_norm": {"scale": vec},
    }}


def make_tp_block_fn(cfg, tp_axis: str = "tensor"):
    """TP-sharded LlamaBlock chain for the 1F1B interpreter: each tensor
    rank computes its head/ffn shard and the partial row-parallel outputs
    are psum'd over ``tp_axis`` — weights stay at 1/tp per device inside
    the pipe loop (VERDICT r3 #5; the gpipe fallback is retired).

    Same math as LlamaBlock.apply (RMSNorm fp32, rotary, fp32-softmax
    attention, SwiGLU), restructured Megatron-style.
    """
    from deepspeed_tpu.models.transformer import (
        dot_product_attention, make_causal_mask, rotary_embedding,
    )

    hd = cfg.hidden_size // cfg.num_heads
    n_kv = cfg.num_kv_heads or cfg.num_heads

    def rms(x, scale):
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        return (x32 * lax.rsqrt(var + cfg.rms_norm_eps)
                * scale).astype(cfg.dtype)

    def block_fn(blocks_local, x):
        tp = axis_size(tp_axis)
        assert cfg.num_heads % tp == 0 and n_kv % tp == 0, (
            f"heads {cfg.num_heads}/kv {n_kv} must divide tensor={tp}")
        nh_loc, nkv_loc = cfg.num_heads // tp, n_kv // tp
        B, S = x.shape[0], x.shape[1]
        mask = make_causal_mask(S)
        pos = jnp.arange(S, dtype=jnp.int32)[None, :]

        def layer(h0, w):
            a, m = w["attn"], w["mlp"]
            hn = rms(h0, w["input_norm"]["scale"])
            mm = lambda t, k: t @ k.astype(cfg.dtype)
            q = mm(hn, a["q_proj"]["kernel"]).reshape(B, S, nh_loc, hd)
            k = mm(hn, a["k_proj"]["kernel"]).reshape(B, S, nkv_loc, hd)
            v = mm(hn, a["v_proj"]["kernel"]).reshape(B, S, nkv_loc, hd)
            q = rotary_embedding(q, pos, cfg.rope_base)
            k = rotary_embedding(k, pos, cfg.rope_base)
            if nkv_loc != nh_loc:
                k = jnp.repeat(k, nh_loc // nkv_loc, axis=2)
                v = jnp.repeat(v, nh_loc // nkv_loc, axis=2)
            att = dot_product_attention(q, k, v, mask=mask)
            att = att.astype(cfg.dtype).reshape(B, S, nh_loc * hd)
            h1 = h0 + lax.psum(mm(att, a["o_proj"]["kernel"]), tp_axis)
            hn = rms(h1, w["post_attn_norm"]["scale"])
            g = mm(hn, m["gate_proj"]["kernel"])
            u = mm(hn, m["up_proj"]["kernel"])
            d = mm(jax.nn.silu(g) * u, m["down_proj"]["kernel"])
            return h1 + lax.psum(d, tp_axis), None

        if cfg.remat:
            # honor the activation-checkpointing config (all scopes treated
            # as block-scope here: the interpreter's per-tick VJP recomputes
            # the stage anyway, so per-layer checkpointing bounds its
            # internal residuals)
            from deepspeed_tpu.models.llama import _remat_policy

            layer = jax.checkpoint(layer,
                                   policy=_remat_policy(cfg.remat_policy))
        y, _ = lax.scan(layer, x, blocks_local["block"])
        return y

    return block_fn


def make_1f1b_lm_loss(cfg, mesh, num_micro: Optional[int] = None):
    """LLaMA-family 1F1B loss (the interpreter-backed counterpart of
    pipe/engine.make_pipeline_lm_loss — same parameter tree). On meshes
    with tensor>1 the block weights stay tensor-sharded inside the pipe
    loop (make_tp_block_fn)."""
    from deepspeed_tpu.models.llama import LlamaBlock
    from deepspeed_tpu.models.transformer import make_causal_mask

    M = num_micro or max(mesh.shape["pipe"], 1)
    block = LlamaBlock(cfg)
    tp = mesh.shape.get("tensor", 1)

    def embed_fn(rest, ids):
        return rest["embed_tokens"]["embedding"][ids].astype(cfg.dtype)

    if tp > 1:
        block_fn = make_tp_block_fn(cfg)
    else:
        def block_fn(blocks_local, x):
            S = x.shape[-2]
            mask = make_causal_mask(S)
            upos = jnp.arange(S, dtype=jnp.int32)[None, :]

            def layer(h, layer_params):
                return block.apply({"params": layer_params}, h, mask,
                                   upos), None

            y, _ = lax.scan(layer, x, blocks_local["block"])
            return y

    def head_loss_fn(rest, y, labels):
        scale = rest["final_norm"]["scale"]
        y32 = y.astype(jnp.float32)
        var = jnp.mean(jnp.square(y32), axis=-1, keepdims=True)
        h = y32 * lax.rsqrt(var + cfg.rms_norm_eps) * scale
        if cfg.tie_embeddings:
            logits = h @ rest["embed_tokens"]["embedding"].T.astype(
                jnp.float32)
        else:
            logits = (h.astype(cfg.dtype)
                      @ rest["lm_head"]["kernel"].astype(cfg.dtype))
        logits = logits.astype(jnp.float32)
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return jnp.sum(jnp.where(valid, -ll, 0.0)), jnp.sum(valid)

    return make_1f1b_loss(
        embed_fn, block_fn, head_loss_fn, mesh, M, dtype=cfg.dtype,
        blocks_spec=tp_block_specs() if tp > 1 else None,
        extra_axes=("tensor",) if tp > 1 else ())
