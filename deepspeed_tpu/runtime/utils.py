"""Runtime helpers.

TPU-native analogue of reference ``deepspeed/runtime/utils.py``: memory
reporting (``see_memory_usage`` :775), gradient-norm helpers with
parallel-axis awareness (:300-520), balanced partitioning
(``partition_balanced`` :603), overflow checking (``CheckOverflow`` :176),
and flatten/unflatten (``csrc/utils/flatten_unflatten.cpp`` → raveled
pytrees, literally one call here).
"""

import gc
import math
from bisect import bisect_left
from typing import Any, List, Optional, Tuple

import jax
import jax.flatten_util
import jax.numpy as jnp
import psutil

from deepspeed_tpu.utils.logging import logger


# --- flatten/unflatten (the reference's C++ binding is one jax call) --------

def flatten_dense_tensors(tree: Any) -> Tuple[jnp.ndarray, Any]:
    """Pytree → one flat f32-preserving vector + unflattener."""
    flat, unravel = jax.flatten_util.ravel_pytree(tree)
    return flat, unravel


def unflatten_dense_tensors(flat: jnp.ndarray, unravel) -> Any:
    return unravel(flat)


# --- norms / clipping -------------------------------------------------------

def global_norm(tree: Any) -> jnp.ndarray:
    """L2 norm over a pytree. Under jit with sharded leaves XLA computes
    partial norms + cross-device reduction automatically (the analogue of
    the reference's TP/MoE-aware get_global_norm)."""
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32)))
              for x in jax.tree_util.tree_leaves(tree)]
    return jnp.sqrt(sum(leaves)) if leaves else jnp.asarray(0.0)


def clip_grad_norm_(tree: Any, max_norm: float, eps: float = 1e-6) -> Tuple[Any, jnp.ndarray]:
    """Scale grads so global norm <= max_norm; returns (clipped, norm)."""
    norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + eps))
    return jax.tree_util.tree_map(lambda g: g * scale, tree), norm


class CheckOverflow:
    """Non-finite gradient detection (reference :176). Functional: call
    inside jit; the cross-rank OR is free because grads are already global
    values under SPMD."""

    @staticmethod
    def check(grads: Any) -> jnp.ndarray:
        from deepspeed_tpu.runtime.fp16.loss_scaler import grads_finite

        return ~grads_finite(grads)

    @staticmethod
    def has_overflow(grads: Any) -> bool:
        return bool(CheckOverflow.check(grads))


# --- balanced partitioning (reference partition_balanced :603) --------------

def prefix_sum_inc(weights: List[float]) -> List[float]:
    out = []
    total = 0.0
    for w in weights:
        total += w
        out.append(total)
    return out


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundary list of length num_parts+1, near-equal item counts."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    extra = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < extra else 0)
    return parts


def partition_balanced(weights: List[float], num_parts: int) -> List[int]:
    """Weighted balanced contiguous partition via binary search over the
    bottleneck (reference uses the same idea with a prefix-sum + probe)."""
    n = len(weights)
    if num_parts >= n:
        return list(range(n)) + [n] * (num_parts - n + 1)
    prefix = [0.0] + prefix_sum_inc(weights)

    def parts_needed(limit: float) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_parts):
            # furthest end with sum(start,end) <= limit
            target = prefix[start] + limit
            end = bisect_left(prefix, target, lo=start + 1)
            if end <= n and prefix[end] == target:
                pass  # exact fit
            else:
                end -= 1
            if end <= start:
                return None  # one item exceeds limit
            bounds.append(end)
            start = end
            if end == n:
                break
        if bounds[-1] != n:
            if len(bounds) == num_parts + 1:
                return None
            bounds += [n] * (num_parts + 1 - len(bounds))
        while len(bounds) < num_parts + 1:
            bounds.append(n)
        return bounds if bounds[-1] == n else None

    lo = max(weights)
    hi = sum(weights)
    for _ in range(50):
        mid = (lo + hi) / 2
        if parts_needed(mid) is not None:
            hi = mid
        else:
            lo = mid
    result = parts_needed(hi)
    assert result is not None
    return result


# --- memory reporting -------------------------------------------------------

def see_memory_usage(message: str, force: bool = False) -> None:
    """reference :775: device + host memory snapshot, rank-0 logged."""
    if not force:
        return
    from deepspeed_tpu.accelerator import get_accelerator

    acc = get_accelerator()
    dev_alloc = acc.memory_allocated()
    dev_peak = acc.max_memory_allocated()
    vm = psutil.virtual_memory()
    logger.info(
        f"{message} | device allocated: {dev_alloc / 2**30:.2f} GB | "
        f"device peak: {dev_peak / 2**30:.2f} GB | "
        f"host used: {(vm.total - vm.available) / 2**30:.2f} GB "
        f"({vm.percent}%)")


def memory_status(msg: str = "") -> dict:
    from deepspeed_tpu.accelerator import get_accelerator

    acc = get_accelerator()
    return {
        "allocated": acc.memory_allocated(),
        "peak": acc.max_memory_allocated(),
        "total": acc.total_memory(),
    }


# --- PartitionedTensor (reference :621) ------------------------------------

class PartitionedTensor:
    """A logically-full tensor stored as the local shard of a mesh axis.

    Under SPMD this is a jax.Array with a NamedSharding; this class only
    keeps the reference's API (full()/to_meta()/data) for code ported from
    the reference's pipeline engine.
    """

    def __init__(self, tensor: jnp.ndarray, sharding=None):
        self._array = tensor if sharding is None else jax.device_put(tensor, sharding)

    @property
    def data(self):
        return self._array

    def full(self) -> jnp.ndarray:
        # resharding to replicated materializes the gathered value
        from jax.sharding import NamedSharding, PartitionSpec

        sh = self._array.sharding
        if hasattr(sh, "mesh"):
            return jax.device_put(self._array,
                                  NamedSharding(sh.mesh, PartitionSpec()))
        return self._array

    def size(self):
        return self._array.size
