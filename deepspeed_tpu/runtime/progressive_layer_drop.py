"""Progressive layer drop (reference ``runtime/progressive_layer_drop.py:10``).

PLD anneals a keep-probability theta(t) = (1-theta)·exp(-gamma·t) + theta
toward ``theta`` as training progresses; layer l of L is then dropped with
probability (l/L)·(1-theta(t)) (the PLD paper's depth-weighted schedule).
The engine tracks theta and exposes ``get_state()``; models consume it via
``layer_keep_probs`` + a ``pld`` rng (stochastic-depth residual gating —
under XLA the skipped block's FLOPs are still scheduled, so PLD here is an
accuracy/regularization feature, not a wall-clock one; a ``lax.cond``
variant is the wall-clock optimization.)
"""

import math
from typing import Dict, List

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = ((1.0 - self.theta)
                              * math.exp(-self.gamma * global_step)
                              + self.theta)
        return self.current_theta

    def get_state(self) -> Dict[str, float]:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def layer_keep_probs(self, num_layers: int) -> List[float]:
        """Keep prob per layer: deeper layers drop more (PLD paper eq. 6)."""
        th = self.get_theta()
        return [1.0 - (l / num_layers) * (1.0 - th)
                for l in range(1, num_layers + 1)]


def stochastic_depth_residual(x, sublayer_out, keep_prob: float, rng):
    """Residual gated by a Bernoulli keep draw: x + keep·f(x).

    Training-time stochastic depth (no 1/keep_prob rescale — PLD keeps the
    identity path unscaled like the reference implementation)."""
    keep = jax.random.bernoulli(rng, keep_prob).astype(sublayer_out.dtype)
    return x + keep * sublayer_out


def apply_layer_drop(block_fn, x, keep_prob, rng):
    """Whole-block PLD gate: with prob (1-keep_prob) the block is skipped
    entirely (identity). ``jnp.where`` keeps both sides traced."""
    keep = jax.random.bernoulli(rng, keep_prob)
    out = block_fn(x)
    return jnp.where(keep, out, x)
