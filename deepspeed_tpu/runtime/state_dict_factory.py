"""SDLoaderFactory — TP-aware sharded checkpoint loading for inference.

Analogue of ``deepspeed/runtime/state_dict_factory.py:21`` (SDLoaderFactory /
MegatronSDLoader): given a list of checkpoint files written at some TP degree
and a target ``mp_world_size``, each target rank loads either

- its matching file (degrees equal),
- a **merge** of ``ckpt_tp/mp_world_size`` files (target is smaller), or
- a **split slice** of one file (target is larger),

with fused-QKV rows regrouped per checkpoint version. The merge/split math
lives in ``deepspeed_tpu.checkpoint.megatron``; this wrapper keeps the
reference's factory/loader API shape so inference checkpoint configs
(``{"type": "Megatron", "checkpoints": [...], "version": ...}``,
state_dict_factory.py:24-46) port unchanged.
"""

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.checkpoint.megatron import (
    _load_pt, _to_numpy, merge_tp, split_tp,
)
from deepspeed_tpu.utils.logging import logger


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file_or_dict, checkpoint_engine=None):
        """Accept the reference's checkpoint-description JSON
        (state_dict_factory.py:24): {"type", "checkpoints", "version"}."""
        if isinstance(json_file_or_dict, str):
            with open(json_file_or_dict) as f:
                data = json.load(f)
        else:
            data = dict(json_file_or_dict)
        sd_type = data.get("type", "Megatron")
        ckpt_list = data.get("checkpoints", [])
        version = data.get("version", 2.0)
        base_dir = data.get("base_dir", "")
        if base_dir:
            ckpt_list = [os.path.join(base_dir, c) for c in ckpt_list]
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type=sd_type,
                                             version=version)

    @staticmethod
    def get_sd_loader(ckpt_list: List[str], sd_type: str = "Megatron",
                      version: float = 2.0, checkpoint_engine=None):
        if sd_type.lower() != "megatron":
            raise ValueError(f"unsupported sd_type {sd_type!r}; "
                             "only 'Megatron' sharded checkpoints")
        return MegatronSDLoader(ckpt_list, version)


class MegatronSDLoader:
    """Loads one target-TP-rank's weights from a differently-TP-sharded
    checkpoint list (reference MegatronSDLoader, state_dict_factory.py:190)."""

    def __init__(self, ckpt_list: List[str], version: float = 2.0):
        if not ckpt_list:
            raise ValueError("empty checkpoint list")
        self.ckpt_list = list(ckpt_list)
        self.version = version

    def _load(self, path: str) -> Dict[str, Any]:
        if path.endswith(".npz"):
            return dict(np.load(path))
        sd = _load_pt(path)
        return sd.get("module", sd)

    def load(self, mp_world_size: int, mp_rank: int
             ) -> Tuple[str, Dict[str, np.ndarray]]:
        """→ (provenance string, numpy state dict for this rank).

        Mirrors SDLoaderBase.load's three cases (state_dict_factory.py:57):
        direct, merge (ckpt_tp > target_tp), split (ckpt_tp < target_tp).
        """
        n = len(self.ckpt_list)
        if mp_world_size == n:
            path = self.ckpt_list[mp_rank]
            sd = {k: _to_numpy(v) for k, v in self._load(path).items()}
            return path, sd
        if mp_world_size < n:
            if n % mp_world_size:
                raise ValueError(f"ckpt tp {n} not divisible by target "
                                 f"tp {mp_world_size}")
            per = n // mp_world_size
            files = self.ckpt_list[mp_rank * per:(mp_rank + 1) * per]
            sds = [self._load(f) for f in files]
            logger.info(f"merging {len(files)} ckpt shards for rank {mp_rank}")
            return ",".join(files), merge_tp(sds, self.version)
        # split path
        if mp_world_size % n:
            raise ValueError(f"target tp {mp_world_size} not divisible by "
                             f"ckpt tp {n}")
        per = mp_world_size // n
        file_idx, offset = divmod(mp_rank, per)
        path = self.ckpt_list[file_idx]
        logical = self._load(path)
        shard = split_tp({k: _to_numpy(v) for k, v in logical.items()},
                         per, self.version)[offset]
        return path, shard
