"""The single JSON config that drives the whole framework.

TPU-native analogue of reference ``deepspeed/runtime/config.py:674``
(``DeepSpeedConfig``): one dict/file parsed into typed sub-configs with the
batch-size triangle ``train_batch_size = micro_batch * gradient_accumulation
* data_parallel_size`` auto-completed and validated.

Differences from the reference, by design:
- a ``mesh`` section declares the device mesh axes (data/fsdp/tensor/pipe/
  expert/sequence); the reference's implicit process groups become mesh axes.
- bf16 is the default precision (fp16+loss-scaling kept for parity).
"""

import json
from typing import Any, Dict, List, Optional, Union

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import (
    DeepSpeedConfigModel,
    dict_raise_error_on_duplicate_keys,
)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
LION_OPTIMIZER = "lion"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER,
    ADAGRAD_OPTIMIZER, LION_OPTIMIZER,
]


class FP16Config(DeepSpeedConfigModel):
    """`"fp16": {...}` — kept for parity; bf16 needs no loss scaling."""

    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = Field(0.0, ge=0.0)  # 0 => dynamic
    initial_scale_power: int = Field(16, ge=0)
    loss_scale_window: int = Field(1000, gt=0)
    hysteresis: int = Field(2, ge=0)
    min_loss_scale: float = Field(1.0, ge=0.0)
    fp16_master_weights_and_grads: bool = False


class BF16Config(DeepSpeedConfigModel):
    """`"bf16": {...}` — native TPU precision."""

    enabled: bool = True
    # accumulate gradients across micro-batches in fp32 (reference
    # bf16_optimizer grad accumulation dtype)
    immediate_grad_update: bool = False


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = ADAMW_OPTIMIZER
    params: Dict[str, Any] = {}
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = {}


class MeshConfig(DeepSpeedConfigModel):
    """TPU-specific: the device-mesh shape.

    Axes (any may be 1 / omitted): ``pipe`` (pipeline stages), ``data``
    (pure data parallel), ``fsdp`` (ZeRO sharding axis; merged with ``data``
    when unset), ``expert`` (MoE expert parallel), ``sequence`` (Ulysses/ring
    context parallel), ``tensor`` (megatron-style tensor parallel).

    -1 for one axis means "all remaining devices".
    """

    pipe: int = 1
    data: int = Field(-1)
    expert: int = 1
    sequence: int = 1
    tensor: int = 1
    # device assignment order, outermost first; DCN-crossing axes should be
    # outermost so TP/SP collectives ride ICI.
    axis_order: List[str] = ["pipe", "data", "expert", "sequence", "tensor"]


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """`"activation_checkpointing"` (reference activation_checkpointing/config).

    On TPU this maps to jax.checkpoint (remat) policies.
    """

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-specific: named remat policy ("nothing_saveable", "dots_saveable",
    # "dots_with_no_batch_dims_saveable", "everything_saveable")
    policy: str = "nothing_saveable"


class TensorboardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class JSONLConfig(DeepSpeedConfigModel):
    """Dependency-free JSONL event sink (monitor/monitor.py) — the
    DEFAULT monitoring backend. ``enabled: None`` (the default) means
    AUTO: the sink activates whenever monitoring is on at all, so a
    torch-free install that asked for TensorBoard still gets its events
    on disk instead of silently losing all monitoring; ``true`` turns
    monitoring on by itself, ``false`` opts out of the fallback."""

    enabled: Optional[bool] = None
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class PrometheusConfig(DeepSpeedConfigModel):
    """Prometheus textfile sink (monitor/monitor.py, dstprof): at every
    registry drain (``steps_per_print`` boundaries) the engine's full
    metrics registry is rendered as exposition text into
    ``output_path/job_name/metrics.prom`` — the node-exporter
    textfile-collector handoff (no listener, no new dependency). For a
    live scrape endpoint use the serving engine's
    ``serve.metrics_port`` instead."""

    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = []


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class PipelineConfig(DeepSpeedConfigModel):
    stages: Union[str, int] = "auto"
    partition: str = "best"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    # TPU-specific: microbatch schedule; "auto" | "1f1b" | "gpipe".
    # auto → 1f1b, except meshes with tensor/sequence parallelism where the
    # SPMD-gpipe path preserves intra-stage TP sharding (the 1F1B
    # interpreter's shard_map replicates stage weights over tensor ranks)
    schedule: str = "auto"
    # pipeline microbatches per step; None → one per stage (bubble ~50% —
    # raise it to shrink the bubble, (P-1)/(M+P-1))
    num_micro: Optional[int] = None


class MoEConfig(DeepSpeedConfigModel):
    enabled: bool = False
    num_experts: int = 1
    top_k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    moe_param_group: bool = False


class HybridEngineConfig(DeepSpeedConfigModel):
    """`"hybrid_engine"` (reference deepspeed/runtime/config.py hybrid engine
    section): RLHF actor train<->generate flip."""

    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8
    # TPU extension: rollout generation through the int8 weight-streaming
    # decode kernel (inference quant.streaming) — the live training weights
    # are rowwise-quantized INSIDE each compiled generate program, so the
    # rollout policy is the int8-rounded actor (decode reads half the HBM
    # bytes; the train path is untouched). Opt-in: rollouts then sample
    # from a slightly perturbed policy — PPO's ratio clipping absorbs it,
    # but measure before enabling for small models.
    int8_streaming_rollout: bool = False


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = {}
    # TPU-specific: async orbax-style checkpointing. Opt-in (the reference's
    # default engine is synchronous; Nebula async is opt-in the same way) —
    # an async save is only durable after checkpoint_engine.wait() or the
    # next save/load on the SAME engine.
    async_save: bool = False


class DataTypeConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class AIOConfig(DeepSpeedConfigModel):
    """Host async-IO knobs (reference aio_config.py); consumed by the C++
    io thread-pool in deepspeed_tpu/ops/aio."""

    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = [2, 4, 6]
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedConfig:
    """Parse + validate the config dict (reference runtime/config.py:674)."""

    def __init__(self, config: Union[str, Dict], mesh_shape: Optional[Dict[str, int]] = None,
                 world_size: Optional[int] = None):
        if isinstance(config, str):
            with open(config, "r") as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys
                )
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise DeepSpeedConfigError(
                f"Expected a config dict or path to a json file, got {type(config)}"
            )

        if world_size is None:
            import jax

            world_size = jax.device_count()
        self.world_size = world_size

        p = self._param_dict
        self.train_batch_size: Optional[int] = p.get("train_batch_size")
        self.train_micro_batch_size_per_gpu: Optional[int] = p.get(
            "train_micro_batch_size_per_gpu"
        )
        self.gradient_accumulation_steps: Optional[int] = p.get(
            "gradient_accumulation_steps"
        )
        self.steps_per_print: int = p.get("steps_per_print", 10)
        self.dump_state: bool = p.get("dump_state", False)
        self.gradient_clipping: float = p.get("gradient_clipping", 0.0)
        self.prescale_gradients: bool = p.get("prescale_gradients", False)
        self.gradient_predivide_factor: float = p.get("gradient_predivide_factor", 1.0)
        self.sparse_gradients_enabled: bool = p.get("sparse_gradients", False)
        self.communication_data_type: Optional[str] = p.get("communication_data_type")
        self.disable_allgather: bool = p.get("disable_allgather", False)
        self.wall_clock_breakdown: bool = p.get("wall_clock_breakdown", False)
        self.memory_breakdown: bool = p.get("memory_breakdown", False)
        self.seed: int = p.get("seed", 42)
        # TPU-specific: stream the LM-head matmul + softmax over sequence
        # chunks (ops/fused_losses.chunked_lm_xent) instead of materializing
        # [B, S, V] fp32 logits. Costs a few % step time at small scale;
        # enables configs whose logits would not otherwise fit HBM.
        fused = p.get("fused_lm_loss", {})
        if isinstance(fused, bool):
            fused = {"enabled": fused}
        self.fused_lm_loss_enabled: bool = fused.get("enabled", False)
        self.fused_lm_loss_chunk: int = fused.get("chunk_size", 256)
        # reference data_types.grad_accum_dtype (runtime/config.py
        # get_data_types): the dtype gradients are STORED in between
        # backward and the optimizer step. Default (None) keeps the param
        # dtype (fp32 master). "bf16" halves the materialized grad tree —
        # at gas=1 this loses nothing (the backward computes in the bf16
        # compute dtype anyway; fp32 storage only re-encodes bf16 values),
        # and the optimizer chain upcasts to fp32 before clipping/Adam
        # math. At gas>1 the micro-batch accumulator also runs at this
        # dtype, which IS a fidelity trade — documented, opt-in.
        dtypes = p.get("data_types", {})
        _ga = dtypes.get("grad_accum_dtype")
        if _ga is not None:
            _ga = {"fp32": "float32", "float32": "float32",
                   "bf16": "bfloat16", "bfloat16": "bfloat16"}.get(
                       str(_ga).lower())
            if _ga is None:
                raise ValueError(
                    f"data_types.grad_accum_dtype="
                    f"{dtypes.get('grad_accum_dtype')!r}: supported values "
                    f"are fp32/bf16 (fp16 grad accumulation is not "
                    f"supported on the TPU build — use bf16)")
        self.grad_accum_dtype: Optional[str] = _ga
        # checkify-style numerics guard (SURVEY §5: the TPU build's answer
        # to the reference's safe_mode/overflow sanitizers): every step also
        # verifies loss/grad finiteness in-graph; a tripped check skips the
        # update and raises host-side
        nchk = p.get("numerics_check", {})
        if isinstance(nchk, bool):
            nchk = {"enabled": nchk}
        self.numerics_check_enabled: bool = nchk.get("enabled", False)

        self.zero_config = DeepSpeedZeroConfig(**p.get("zero_optimization", {}))
        self.fp16 = FP16Config(**p.get("fp16", {}))
        bf16_dict = p.get("bf16", p.get("bfloat16", {}))
        if "enabled" not in bf16_dict and self.fp16.enabled:
            bf16_dict = {**bf16_dict, "enabled": False}
        self.bf16 = BF16Config(**bf16_dict)
        self.optimizer = OptimizerConfig(**p["optimizer"]) if "optimizer" in p else None
        self.scheduler = SchedulerConfig(**p["scheduler"]) if "scheduler" in p else None
        self.mesh = MeshConfig(**p.get("mesh", {}))
        self.activation_checkpointing = ActivationCheckpointingConfig(
            **p.get("activation_checkpointing", {})
        )
        self.tensorboard = TensorboardConfig(**p.get("tensorboard", {}))
        self.wandb = WandbConfig(**p.get("wandb", {}))
        self.csv_monitor = CSVConfig(**p.get("csv_monitor", {}))
        self.jsonl_monitor = JSONLConfig(**p.get("jsonl_monitor", {}))
        self.prometheus_monitor = PrometheusConfig(
            **p.get("prometheus_monitor", {}))
        # dstprof MFU denominator override (TFLOP/s per device); None =
        # the per-platform table in observability/efficiency.py
        self.peak_tflops: Optional[float] = p.get("peak_tflops")
        # dsttrain (docs/OBSERVABILITY.md "Training"): in-graph
        # grad/MoE health stats + step-lane tracing. Default ON — the
        # stats ride the compiled step (comms-free, budget-pinned) and
        # publication is lag-one so the async dispatch pipeline keeps
        # its depth. ``loss_aux`` opts a custom loss_fn into returning
        # ``(loss, {name: scalar})``; the scalars publish as
        # ``train.aux.<name>`` gauges (the MoE gate-telemetry channel).
        tele = p.get("train_telemetry", {})
        if isinstance(tele, bool):
            tele = {"enabled": tele}
        self.train_telemetry_enabled: bool = bool(tele.get("enabled", True))
        self.train_telemetry_trace: bool = bool(tele.get("trace", True))
        self.train_telemetry_trace_capacity: int = int(
            tele.get("trace_capacity", 65536))
        self.train_telemetry_loss_aux: bool = bool(
            tele.get("loss_aux", False))
        # training twin of serve.metrics_port: >0 starts the stdlib
        # Prometheus scrape endpoint over the engine's registry
        self.metrics_port: int = int(p.get("metrics_port", 0) or 0)
        # dstfleet (docs/OBSERVABILITY.md "Fleet"): cross-process metric
        # aggregation over a shared directory. When ``dir`` is set,
        # every rank atomically writes rank<k>.json at its monitor
        # drain (steps_per_print boundaries) and rank 0 merges all rank
        # files (counters sum, gauges per-host labeled + min/mean/max,
        # histograms bucket-wise lossless) + runs straggler detection
        # (fleet.step_time.skew / fleet.collective_wait.skew gauges, ONE
        # structured warning when a host exceeds straggler_threshold x
        # the fleet median for straggler_windows consecutive drains).
        fleet = p.get("fleet", {})
        if isinstance(fleet, str):
            fleet = {"dir": fleet}
        self.fleet_dir: Optional[str] = fleet.get("dir")
        # -1 = resolve from DS_TPU_PROCESS_ID env else jax.process_index()
        self.fleet_rank: int = int(fleet.get("rank", -1))
        self.fleet_straggler_threshold: float = float(
            fleet.get("straggler_threshold", 1.5))
        self.fleet_straggler_windows: int = int(
            fleet.get("straggler_windows", 3))
        self.comms_logger = CommsLoggerConfig(**p.get("comms_logger", {}))
        self.flops_profiler = FlopsProfilerConfig(**p.get("flops_profiler", {}))
        self.pipeline = PipelineConfig(**p.get("pipeline", {}))
        self.moe = MoEConfig(**p.get("moe", {}))
        self.checkpoint_config = CheckpointConfig(**p.get("checkpoint", {}))
        self.hybrid_engine = HybridEngineConfig(**p.get("hybrid_engine", {}))
        # raw dict goes through the model so unknown keys still fail fast
        # (extra='forbid'); the normalized dtype name overrides the alias
        # so the model field and the validated attribute cannot disagree
        self.data_types = DataTypeConfig(
            **{**p.get("data_types", {}),
               "grad_accum_dtype": self.grad_accum_dtype})
        self.aio = AIOConfig(**p.get("aio", {}))
        self.elasticity = ElasticityConfig(**p.get("elasticity", {}))
        self.compression_config = p.get("compression_training", {})
        self.data_efficiency_config = p.get("data_efficiency", {})
        # misc runtime features (reference config.py eigenvalue/pld/quantize)
        self.eigenvalue_config = p.get("eigenvalue", {})
        self.eigenvalue_enabled: bool = self.eigenvalue_config.get("enabled", False)
        self.pld_config = p.get("progressive_layer_drop", {})
        self.pld_enabled: bool = self.pld_config.get("enabled", False)
        self.quantize_training_config = p.get("quantize_training", {})
        self.quantize_training_enabled: bool = \
            self.quantize_training_config.get("enabled", False)
        self.curriculum_learning_legacy = p.get("curriculum_learning", {})
        self.monitor_config_enabled = (
            self.tensorboard.enabled or self.wandb.enabled
            or self.csv_monitor.enabled
            or self.prometheus_monitor.enabled
            # jsonl 'auto' (None) rides along with the sinks above;
            # an explicit true turns monitoring on by itself
            or self.jsonl_monitor.enabled is True
        )

        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")

        self._resolve_batch_config()
        self._do_sanity_check()

    # --- batch triangle (reference config.py:837 _configure_train_batch_size) ---
    def _resolve_batch_config(self) -> None:
        # data-parallel size for the triangle = world / (pipe*tensor*sequence)
        m = self.mesh
        denom = max(1, m.pipe) * max(1, m.tensor) * max(1, m.sequence)
        dp_world = max(1, self.world_size // denom)
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            gas = train // (micro * dp_world)
        elif train is not None and gas is not None:
            micro = train // (gas * dp_world)
        elif micro is not None and gas is not None:
            train = micro * gas * dp_world
        elif train is not None:
            gas = 1
            micro = train // dp_world
        elif micro is not None:
            gas = 1
            train = micro * dp_world
        else:
            micro, gas = 1, 1
            train = dp_world

        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas
        self.data_parallel_size = dp_world

    def _do_sanity_check(self) -> None:
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        dp = self.data_parallel_size
        if train != micro * gas * dp:
            raise DeepSpeedConfigError(
                f"Check batch related parameters. train_batch_size is not equal to "
                f"micro_batch_per_gpu * gradient_accumulation_steps * data_parallel_size: "
                f"{train} != {micro} * {gas} * {dp}"
            )
        if any(v <= 0 for v in (train, micro, gas)):
            raise DeepSpeedConfigError(
                f"Batch parameters must be positive: train={train} micro={micro} gas={gas}"
            )
        if self.optimizer is not None:
            t = self.optimizer.type.lower()
            if t not in DEEPSPEED_OPTIMIZERS:
                logger.warning(
                    f"Optimizer type {self.optimizer.type} is not a built-in; "
                    f"it must be registered via deepspeed_tpu.ops.optimizer_registry"
                )

    # convenience views -----------------------------------------------------
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    @property
    def precision_dtype(self) -> str:
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        return "float32"

    def print_config(self) -> None:
        logger.info(f"DeepSpeedConfig: {json.dumps(self._param_dict, indent=2, default=str)}")
