"""Hessian max-eigenvalue estimation by power iteration
(reference ``runtime/eigenvalue.py:12`` — the MoQ precision-switch signal).

The reference runs power iteration with autograd double-backward per model
block; here Hessian-vector products are a single ``jax.jvp`` through
``jax.grad`` (forward-over-reverse), jitted once and reused across
iterations. Eigenvalues are computed per "block" — a sub-tree of the param
pytree selected by path prefix (the analogue of the reference's per-layer
module walk) — and post-processed the same way: |ev| normalized to [0, 1]
by the block max, with nan/zero mapped to 1.0 (most-sensitive), so
downstream MoQ schedules see stable relative magnitudes.
"""

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _tree_dot(a, b) -> jnp.ndarray:
    leaves = jax.tree_util.tree_map(lambda x, y: jnp.vdot(x, y), a, b)
    return sum(jax.tree_util.tree_leaves(leaves))


def _tree_norm(a) -> jnp.ndarray:
    return jnp.sqrt(_tree_dot(a, a))


def _normalize(a, eps: float = 1e-12):
    n = _tree_norm(a) + eps
    return jax.tree_util.tree_map(lambda x: x / n, a)


def block_paths(params: Any, prefix: str = "layer_") -> List[str]:
    """Top-level block names (reference: the model's layer modules), in
    numeric layer order — ``prefix`` must be followed by the layer index,
    so ``layer_norm`` is not a block and ``layer_10`` sorts after
    ``layer_2``."""
    import re

    pat = re.compile(rf"^{re.escape(prefix)}(\d+)$")
    hits = [(int(m.group(1)), k) for k in params
            if (m := pat.match(str(k)))]
    return [k for _, k in sorted(hits)]


class Eigenvalue:
    """reference ``Eigenvalue`` (eigenvalue.py:12). Same knobs:
    verbose, max_iter, tol, stability (power-iteration normalization epsilon),
    gas_boundary_resolution (how often the engine calls this),
    layer_name/layer_num select the blocks."""

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "layer_", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num
        # per-block jitted HVP programs, compiled once and reused across
        # calls (valid for ONE loss function per Eigenvalue instance)
        self._hvp_cache: Dict[str, Callable] = {}

    def compute_eigenvalue(self, loss_fn: Callable, params: Any,
                           batch: Any, rng_seed: int = 0) -> List[float]:
        """Max |eigenvalue| of the loss Hessian restricted to each block."""
        names = block_paths(params, self.layer_name)
        if self.layer_num:
            names = names[: self.layer_num]

        def make_hvp(name):
            # Hessian restricted to one block: grad wrt the block only, with
            # the rest of the tree substituted in — O(block) tangents, no
            # full-model zero padding
            def hvp(p, b, v):
                def block_grad(bp):
                    return jax.grad(
                        lambda bp2: loss_fn({**p, name: bp2}, b))(bp)
                return jax.jvp(block_grad, (p[name],), (v,))[1]
            return jax.jit(hvp)

        key = jax.random.PRNGKey(rng_seed)
        eigenvalues: List[float] = []
        for name in names:
            if name not in self._hvp_cache:
                self._hvp_cache[name] = make_hvp(name)
            hvp = self._hvp_cache[name]
            block = params[name]
            key, sub = jax.random.split(key)
            leaves, treedef = jax.tree_util.tree_flatten(block)
            ks = jax.random.split(sub, len(leaves))
            v = jax.tree_util.tree_unflatten(treedef, [
                jax.random.normal(k, l.shape, jnp.float32)
                for k, l in zip(ks, leaves)])
            v = _normalize(v, self.stability)

            ev = 0.0
            for it in range(self.max_iter):
                vb = jax.tree_util.tree_map(
                    lambda x, y: y.astype(x.dtype), block, v)
                hv = jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.float32), hvp(params, batch, vb))
                new_ev = float(_tree_dot(v, hv))
                v = _normalize(hv, self.stability)
                if it > 0 and abs(new_ev - ev) <= self.tol * abs(new_ev):
                    ev = new_ev
                    break
                ev = new_ev
            eigenvalues.append(ev if np.isfinite(ev) else np.nan)
            if self.verbose:
                logger.info(f"eigenvalue[{name}] = {ev:.4e}")

        return self.post_process(eigenvalues)

    def post_process(self, eigenvalues: List[float]) -> List[float]:
        """|ev| / blockwise-max → [0, 1]; nan and exact zeros map to 1.0
        (treated as maximally sensitive — reference eigenvalue.py:147)."""
        arr = np.asarray(eigenvalues, dtype=np.float64)
        if not len(arr):
            return []
        finite = arr[np.isfinite(arr)]
        mx = float(np.abs(finite).max()) if len(finite) else 0.0
        if mx <= 0.0:
            return [1.0] * len(arr)
        out = np.where(np.isfinite(arr), np.abs(arr) / mx, 1.0)
        out = np.where(out == 0.0, 1.0, out)
        return [float(x) for x in out]
