"""Activation checkpointing.

TPU-native analogue of reference
``runtime/activation_checkpointing/checkpointing.py`` (Megatron-compatible
``checkpoint()`` :474, ``configure()`` :789, RNG-state tracker :121,
activation partitioning across TP ranks :366). The mechanics collapse on
TPU:

- ``checkpoint(fn, *args)`` → ``jax.checkpoint`` (remat): recompute in
  backward, policy-selectable. No custom autograd Function needed.
- RNG fork tracking → ``jax.random`` keys are values, not global state; a
  rematerialized region replays identical randomness by construction, so
  ``CudaRNGStatesTracker`` ports as a thin key-registry for Megatron-style
  callers.
- activation partitioning across TP ranks → a sharding constraint on the
  saved residuals (XLA stores each shard on its owner).
- CPU checkpointing → `jax.checkpoint` + host offload of residuals
  (policy ``save_and_offload_only_these_names`` when available).
"""

import functools
from typing import Any, Callable, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import logger

_CONFIG: Dict[str, Any] = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,
    "number_checkpoints": None,
    "synchronize": False,
    "profile": False,
    "policy": "nothing_saveable",
}


def _policy(name: str):
    table = {
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "dots_with_no_batch_dims_saveable":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "everything_saveable": jax.checkpoint_policies.everything_saveable,
    }
    return table.get(name, jax.checkpoint_policies.nothing_saveable)


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              policy=None) -> None:
    """reference configure (:789) — records the global remat policy."""
    if deepspeed_config is not None:
        ac = deepspeed_config.activation_checkpointing
        _CONFIG.update(
            partition_activations=ac.partition_activations,
            cpu_checkpointing=ac.cpu_checkpointing,
            contiguous_memory_optimization=ac.contiguous_memory_optimization,
            number_checkpoints=ac.number_checkpoints,
            synchronize=ac.synchronize_checkpoint_boundary,
            profile=ac.profile,
            policy=ac.policy,
        )
    for key, val in [("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("number_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize", synchronize), ("profile", profile),
                     ("policy", policy)]:
        if val is not None:
            _CONFIG[key] = val


def is_configured() -> bool:
    return True


def checkpoint(function: Callable, *args, policy: Optional[str] = None):
    """Megatron-style call-site API: run ``function(*args)`` rematerialized.

    Equivalent of reference ``CheckpointFunction.apply`` — but a pure
    transform: returns outputs; backward recomputes under the configured
    policy.
    """
    pol = _policy(policy or _CONFIG["policy"])
    return jax.checkpoint(function, policy=pol)(*args)


def checkpoint_wrapper(function: Callable, policy: Optional[str] = None) -> Callable:
    """Decorator form used by model code."""
    pol = _policy(policy or _CONFIG["policy"])
    return jax.checkpoint(function, policy=pol)


class CudaRNGStatesTracker:
    """Megatron-compat RNG registry (reference :121). JAX keys are explicit
    values; this tracker hands out named fold-ins of a base key so TP ranks
    can reproduce the reference's 'model-parallel rng' semantics."""

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise Exception(f"cuda rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = "model-parallel-rng"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            if name not in self.states_:
                raise Exception(f"cuda rng state {name} is not added")
            key = self.states_[name]
            self.states_[name], use = tuple(jax.random.split(key))
            yield use

        return ctx()


_CUDA_RNG_TRACKER = CudaRNGStatesTracker()


def get_cuda_rng_tracker() -> CudaRNGStatesTracker:
    return _CUDA_RNG_TRACKER


def model_parallel_cuda_manual_seed(seed: int) -> None:
    """reference :xxx — seed the tracker with a TP-rank-offset seed."""
    tracker = get_cuda_rng_tracker()
    tracker.reset()
    tracker.add("model-parallel-rng", seed + 2718)
