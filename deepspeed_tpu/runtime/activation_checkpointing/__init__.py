from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    CudaRNGStatesTracker,
    checkpoint,
    checkpoint_wrapper,
    configure,
    get_cuda_rng_tracker,
    model_parallel_cuda_manual_seed,
)
