"""LLaMA-family decoder — the flagship training/inference model.

Parity target: the reference supports llama via injection policy
(``deepspeed/module_inject/containers/llama.py``); here the architecture is a
first-class flax module designed for TPU:

- pre-norm RMSNorm + RoPE + SwiGLU, grouped-query attention
- ``lax.scan`` over identical blocks → one compiled block, O(1) compile time
  in depth, and a leading layer axis pipeline/ZeRO can use
- ``jax.checkpoint`` (remat) per block per the activation-checkpointing config
- param names chosen so parallel/partition.py's default TP rules shard
  q/k/v/gate/up column-wise and o/down row-wise
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import (
    GatedMLP, RMSNorm, SelfAttention, make_causal_mask,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None
    max_seq_len: int = 4096
    rope_base: float = 10000.0
    rms_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False
    remat_policy: str = "nothing_saveable"
    # what to rematerialize: "block" (whole layer; max memory saving, +1/3
    # recompute flops), "mlp" (recompute only the gated MLP; keeps attention
    # activations resident), or "attn" (the converse). Partial scopes trade
    # HBM for a lower recompute tax — reference activation-checkpointing
    # granularity knob (runtime/activation_checkpointing/checkpointing.py).
    remat_scope: str = "block"
    scan_layers: bool = True
    attention_impl: str = "auto"   # flash kicks in at long seqlen
    tie_embeddings: bool = False
    # ZeRO-3/FSDP gather discipline for the layer scan: constrain each
    # scan iteration's parameter SLICE to replicated, so the SPMD
    # partitioner all-gathers ONE layer inside the loop body instead of
    # hoisting a loop-invariant gather of the whole stacked tree (at 7B
    # that hoist is a 13.5 GB temp — the difference between ZeRO-3
    # fitting a 16 GB chip and not; see tools/zero3_7b_projection.py).
    # Under block remat the gather itself rematerializes in backward.
    # Off by default: only meaningful when params are sharded over
    # data/mics; skipped automatically under tensor/sequence sharding
    # (the constraint would fight the TP spec).
    fsdp_gather_scan: bool = False

    def __post_init__(self):
        if self.remat_scope not in ("block", "attn", "mlp"):
            raise ValueError(
                f"remat_scope={self.remat_scope!r}: expected 'block', "
                f"'attn', or 'mlp' (an unrecognized value would silently "
                f"disable rematerialization)")

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        base = dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                    num_layers=32, num_heads=32, num_kv_heads=32, max_seq_len=4096)
        base.update(kw)
        return LlamaConfig(**base)


def _remat_policy(name: str):
    policies = {
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "dots_with_no_batch_dims_saveable":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "everything_saveable": jax.checkpoint_policies.everything_saveable,
        # save the per-layer attention outputs only (linear memory); the
        # attention core is still recomputed for its own input gradients
        "save_attn_out":
            jax.checkpoint_policies.save_only_these_names("attn_out"),
        # keep the gate/up MLP activations (the dominant recompute cost of
        # whole-block remat: ~40% of forward FLOPs) — backward then redoes
        # only the attention path + elementwise ops. ~134 MB/layer at
        # 770M/8x1024 vs a ~17% step-time saving; needs the HBM headroom
        # freed by the chunked LM loss
        "save_mlp":
            jax.checkpoint_policies.save_only_these_names(
                "mlp_gate", "mlp_up"),
        # widest partial policy that still fits tight HBM: MLP activations
        # + attention output
        "save_mlp_attn":
            jax.checkpoint_policies.save_only_these_names(
                "mlp_gate", "mlp_up", "attn_out"),
    }
    return policies.get(name, jax.checkpoint_policies.nothing_saveable)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, mask, positions):
        cfg = self.cfg
        attn_cls, mlp_cls = SelfAttention, GatedMLP
        if cfg.remat and cfg.remat_scope == "attn":
            attn_cls = nn.remat(SelfAttention,
                                policy=_remat_policy(cfg.remat_policy))
        elif cfg.remat and cfg.remat_scope == "mlp":
            mlp_cls = nn.remat(GatedMLP,
                               policy=_remat_policy(cfg.remat_policy))
        h = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="input_norm")(x)
        h = attn_cls(
            num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
            use_rope=True, rope_base=cfg.rope_base, dtype=cfg.dtype,
            attention_impl=cfg.attention_impl,
            assume_causal_mask=True,   # LlamaModel passes the pure causal mask
            name="attn",
        )(h, mask, positions)
        # named so remat policies can target it (e.g. "save_attn_out"
        # keeps the [B, S, H] attention outputs; note backward still
        # recomputes attention internals for its own gradients, so this
        # only spares the residual/MLP path — measure before choosing)
        from jax.ad_checkpoint import checkpoint_name
        h = checkpoint_name(h, "attn_out")
        x = x + h
        h = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="post_attn_norm")(x)
        h = mlp_cls(intermediate_size=cfg.intermediate_size, dtype=cfg.dtype,
                    name="mlp")(h)
        return x + h


def _fsdp_gather_leaf(a):
    """Replicate-constrain one per-layer weight slice inside the scan body
    (see LlamaConfig.fsdp_gather_scan). No-op without an ambient mesh or
    when model axes are active."""
    from jax.sharding import PartitionSpec

    from deepspeed_tpu.utils.jax_compat import get_abstract_mesh

    mesh = get_abstract_mesh()
    if mesh is None or not mesh.axis_names:
        return a
    shape = dict(mesh.shape)
    if shape.get("data", 1) <= 1 and shape.get("mics", 1) <= 1:
        return a
    if any(shape.get(ax, 1) > 1 for ax in ("tensor", "sequence", "expert")):
        return a
    return jax.lax.with_sharding_constraint(a, PartitionSpec())


class _ScanLlamaBlock(nn.Module):
    """Scan body: (carry, None) contract over a stack of identical blocks."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, mask, positions):
        cfg = self.cfg
        block_cls = LlamaBlock
        if cfg.fsdp_gather_scan:
            # map the sliced params through the gather constraint ON READ,
            # inside the (possibly rematerialized) body — backward then
            # re-gathers instead of keeping L gathered layers live
            block_cls = nn.map_variables(
                block_cls, "params",
                trans_in_fn=lambda vs: jax.tree_util.tree_map(
                    _fsdp_gather_leaf, vs),
                trans_out_fn=lambda vs: vs,   # init writes pass through
                mutable=True)
        if cfg.remat and cfg.remat_scope == "block":
            block_cls = nn.remat(block_cls, policy=_remat_policy(cfg.remat_policy))
        return block_cls(cfg, name="block")(x, mask, positions), None


class LlamaDecodeBlock(nn.Module):
    """Block with functional KV cache for incremental decoding.

    Same parameter structure as LlamaBlock (name='block' inner modules match),
    so trained params apply directly. The KV workspace contract mirrors the
    reference's preallocated inference cache
    (csrc/transformer/inference/includes/inference_context.h): caches are
    preallocated [B, S_max, n_kv, hd] arrays, new tokens written at
    ``cache_index``.
    """

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, mask, positions, kv_cache, cache_index):
        cfg = self.cfg
        h = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="input_norm")(x)
        h, new_cache = SelfAttention(
            num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
            use_rope=True, rope_base=cfg.rope_base, dtype=cfg.dtype,
            attention_impl="xla", name="attn",
        )(h, mask=mask, positions=positions, kv_cache=kv_cache,
          cache_index=cache_index)
        x = x + h
        h = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="post_attn_norm")(x)
        h = GatedMLP(intermediate_size=cfg.intermediate_size, dtype=cfg.dtype,
                     name="mlp")(h)
        return x + h, new_cache


class _ScanLlamaDecodeBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, mask, positions, kv_cache, cache_index):
        y, new_cache = LlamaDecodeBlock(self.cfg, name="block")(
            x, mask, positions, kv_cache, cache_index)
        return y, new_cache


class PagedLlamaDecodeBlock(nn.Module):
    """Block decoding against the shared paged KV block pool
    (ops/paged_attention): same parameter structure as LlamaBlock /
    LlamaDecodeBlock, so trained params apply directly; only the cache
    layout differs from LlamaDecodeBlock. ``attn_kernel`` selects the
    paged decode arm (serve.attn_kernel): the Pallas ragged kernel or
    the jnp gather reference."""

    cfg: LlamaConfig
    attn_kernel: str = "reference"

    @nn.compact
    def __call__(self, x, mask, positions, kv_pool, block_tables, write_pos,
                 valid_len):
        cfg = self.cfg
        h = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="input_norm")(x)
        h, new_pool = SelfAttention(
            num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
            use_rope=True, rope_base=cfg.rope_base, dtype=cfg.dtype,
            attention_impl="xla", paged_attn_kernel=self.attn_kernel,
            # PagedLlamaDecoderModel passes exactly paged_context_mask —
            # the promise lets the pallas arm skip the mask input (the
            # kernel recomputes causal-context from ctx lengths)
            assume_causal_mask=True,
            name="attn",
        )(h, mask=mask, positions=positions, paged_cache=kv_pool,
          block_tables=block_tables, write_pos=write_pos,
          valid_len=valid_len)
        x = x + h
        h = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="post_attn_norm")(x)
        h = GatedMLP(intermediate_size=cfg.intermediate_size, dtype=cfg.dtype,
                     name="mlp")(h)
        return x + h, new_pool


class _ScanPagedLlamaDecodeBlock(nn.Module):
    cfg: LlamaConfig
    attn_kernel: str = "reference"

    @nn.compact
    def __call__(self, x, mask, positions, kv_pool, block_tables, write_pos,
                 valid_len):
        y, new_pool = PagedLlamaDecodeBlock(
            self.cfg, attn_kernel=self.attn_kernel, name="block")(
            x, mask, positions, kv_pool, block_tables, write_pos, valid_len)
        return y, new_pool


class LlamaModel(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, return_hidden=False):
        cfg = self.cfg
        B, S = input_ids.shape
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=cfg.dtype,
                         name="embed_tokens")
        x = embed(input_ids)
        mask = make_causal_mask(S)
        if positions is None:
            positions = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, axis=0)

        if cfg.scan_layers:
            ScanBlock = nn.scan(
                _ScanLlamaBlock,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = ScanBlock(cfg, name="blocks")(x, mask, positions)
        else:
            block_cls = LlamaBlock
            if cfg.remat and cfg.remat_scope == "block":
                block_cls = nn.remat(LlamaBlock, policy=_remat_policy(cfg.remat_policy))
            for i in range(cfg.num_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, mask, positions)

        x = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="final_norm")(x)
        if return_hidden:
            # final-norm hidden states for fused/chunked LM losses
            # (ops/fused_losses.chunked_lm_xent) — the lm_head matmul then
            # happens inside the loss, streamed over sequence chunks
            return x
        if cfg.tie_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="lm_head")(x)
        return logits.astype(jnp.float32)

    def streamed_twin(self, stream_shardings):
        """Scanned-model streaming protocol (engine
        ``_setup_param_streaming``): the stacked-scan streamed apply-twin,
        or None when the model is not scanned (per-layer named params have
        no stacked tree to stream — use scan_layers=True)."""
        if not self.cfg.scan_layers:
            return None
        return StreamedLlamaModel(self.cfg, stream_shardings)


class LlamaDecoderModel(nn.Module):
    """Decode-mode twin of LlamaModel: same parameter tree, takes and returns
    preallocated KV caches. Apply trained params with this module for
    incremental generation.

    kv_caches: (k, v) arrays of shape [L, B, S_max, n_kv, head_dim].
    cache_index: int32 scalar — write offset (tokens already in cache).
    """

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, kv_caches, cache_index, attn_start=0):
        cfg = self.cfg
        B, T = input_ids.shape
        S_max = kv_caches[0].shape[2]
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=cfg.dtype,
                         name="embed_tokens")
        x = embed(input_ids)
        positions, mask = decode_positions_and_mask(B, T, S_max, cache_index,
                                                    attn_start)

        if cfg.scan_layers:
            ScanBlock = nn.scan(
                _ScanLlamaDecodeBlock,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast, 0, nn.broadcast),
                out_axes=0,
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, new_caches = ScanBlock(cfg, name="blocks")(
                x, mask, positions, kv_caches, cache_index)
        else:
            new_k, new_v = [], []
            for i in range(cfg.num_layers):
                x, (ck, cv) = LlamaDecodeBlock(cfg, name=f"layers_{i}")(
                    x, mask, positions,
                    (kv_caches[0][i], kv_caches[1][i]), cache_index)
                new_k.append(ck)
                new_v.append(cv)
            new_caches = (jnp.stack(new_k), jnp.stack(new_v))

        x = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="final_norm")(x)
        if cfg.tie_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="lm_head")(x)
        return logits.astype(jnp.float32), new_caches


class PagedLlamaDecoderModel(nn.Module):
    """Paged-KV decode twin of :class:`LlamaDecoderModel`: same parameter
    tree, but K/V live in a shared block pool indexed through per-slot
    block tables instead of a dense [L, B, S_max, ...] arena — the layout
    behind the continuous-batching scheduler (inference/scheduler.py).

    kv_pools: (k_pool, v_pool) of [L, num_blocks, block_size, n_kv, hd].
    block_tables: int32 [B, W]. write_pos: int32 [B] — per-slot tokens
    already in cache (0 for a cold prefill; the cached-prefix length for
    an OFFSET prefill, where the serving prefix cache supplies the first
    write_pos tokens' KV through shared table entries and only the tail
    is fed — positions, writes and the causal context mask all derive
    from write_pos, so T > 1 at any offset is first-class).
    valid_len: int32 [B] or None —
    real tokens per row along T (right-padding / inactive slots write to
    the null block). ``attn_kernel``: paged decode arm
    (serve.attn_kernel) — Pallas ragged kernel or jnp gather reference.
    Greedy-exact vs the dense twin
    (tests/unit/inference/test_paged_decode.py).
    """

    cfg: LlamaConfig
    attn_kernel: str = "reference"

    @nn.compact
    def __call__(self, input_ids, kv_pools, block_tables, write_pos,
                 valid_len=None):
        cfg = self.cfg
        B, T = input_ids.shape
        S = block_tables.shape[1] * kv_pools[0].shape[2]
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=cfg.dtype,
                         name="embed_tokens")
        x = embed(input_ids)
        positions = write_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        from deepspeed_tpu.ops.paged_attention import paged_context_mask

        mask = paged_context_mask(positions, S)

        if cfg.scan_layers:
            ScanBlock = nn.scan(
                _ScanPagedLlamaDecodeBlock,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast, 0, nn.broadcast,
                         nn.broadcast, nn.broadcast),
                out_axes=0,
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, new_pools = ScanBlock(cfg, self.attn_kernel, name="blocks")(
                x, mask, positions, kv_pools, block_tables, write_pos,
                valid_len)
        else:
            new_k, new_v = [], []
            for i in range(cfg.num_layers):
                x, (pk, pv) = PagedLlamaDecodeBlock(
                    cfg, attn_kernel=self.attn_kernel,
                    name=f"layers_{i}")(
                    x, mask, positions,
                    (kv_pools[0][i], kv_pools[1][i]), block_tables,
                    write_pos, valid_len)
                new_k.append(pk)
                new_v.append(pv)
            new_pools = (jnp.stack(new_k), jnp.stack(new_v))

        x = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name="final_norm")(x)
        if cfg.tie_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="lm_head")(x)
        return logits.astype(jnp.float32), new_pools


class StreamedLlamaModel:
    """Apply-twin of :class:`LlamaModel` that streams host-resident parameters
    into device memory layer-by-layer — the compute path of ZeRO-3 parameter
    offload (reference ``runtime/zero/parameter_offload.py:201`` streams
    partitioned params per-submodule with fetch/release hooks; here the
    fetch is an explicit ``jax.device_put`` inside a manual ``lax.scan`` over
    the stacked block weights, and the release is XLA freeing the slice when
    its last use ends).

    The master params live in ``pinned_host`` memory (stages.py
    ``offload_param``); XLA cannot compute on host-space operands, so every
    weight is copied to device at its point of use: per-layer for the scanned
    blocks (HBM holds ONE layer's weights at a time), once for
    embed/final-norm/lm-head. The backward pass reverses the copies — grads
    of host-resident inputs land back in host memory when the caller asks
    (engine out_shardings), and the per-layer weight re-fetch in backward is
    scheduled by XLA alongside recompute.

    Math parity: every sub-module is applied through the REAL flax modules
    (``LlamaBlock.apply``, ``nn.Embed``, ``RMSNorm``, ``nn.Dense``) on the
    streamed slices, so logits are bit-identical to ``LlamaModel.apply`` on
    the same weights (pinned by tests/unit/test_param_offload.py).

    Plain class with the flax ``apply`` contract the engine's loss builders
    expect (same pattern as :class:`FusedLlamaDecoderModel`).
    """

    def __init__(self, cfg: LlamaConfig, stream_shardings: Any):
        """``stream_shardings``: pytree shaped like the param tree whose
        ``blocks/block`` leaves carry the DEVICE sharding of one layer
        *slice* (stacked spec minus the leading layer axis) and whose other
        leaves carry their full device sharding — built by the engine from
        its ZeRO plan."""
        assert cfg.scan_layers, \
            "parameter streaming requires scan_layers=True (stacked blocks)"
        self.cfg = cfg
        self._shardings = stream_shardings

    def _stream(self, subtree, shardings):
        return jax.tree_util.tree_map(
            lambda w, sh: jax.device_put(w, sh), subtree, shardings)

    def apply(self, variables, input_ids, positions=None, return_hidden=False,
              rngs=None):
        params = variables["params"]
        cfg = self.cfg
        B, S = input_ids.shape
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, dtype=cfg.dtype,
                         name="embed_tokens")
        emb_p = self._stream(params["embed_tokens"],
                             self._shardings["embed_tokens"])
        x = embed.apply({"params": emb_p}, input_ids)
        mask = make_causal_mask(S)
        if positions is None:
            positions = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, axis=0)

        block = LlamaBlock(cfg, name="block")
        block_shardings = self._shardings["blocks"]["block"]

        def body(x, wslice):
            w = self._stream(wslice, block_shardings)
            return block.apply({"params": w}, x, mask, positions,
                               rngs=rngs), None

        if cfg.remat and cfg.remat_scope == "block":
            body = jax.checkpoint(body, policy=_remat_policy(cfg.remat_policy))
        x, _ = jax.lax.scan(body, x, params["blocks"]["block"])

        final = RMSNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype,
                        name="final_norm")
        x = final.apply({"params": self._stream(
            params["final_norm"], self._shardings["final_norm"])}, x)
        if return_hidden:
            return x
        if cfg.tie_embeddings:
            logits = embed.apply({"params": emb_p}, x.astype(jnp.float32),
                                 method="attend")
        else:
            head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                            param_dtype=jnp.float32, name="lm_head")
            logits = head.apply({"params": self._stream(
                params["lm_head"], self._shardings["lm_head"])}, x)
        return logits.astype(jnp.float32)

    def lm_kernel(self, params):
        """Device-resident [H, V] head kernel for the chunked LM loss
        (engine fused_lm_loss path) — streams the tied embedding or lm_head
        once; the chunked loss then re-reads the device copy per chunk."""
        if self.cfg.tie_embeddings:
            emb = self._stream(params["embed_tokens"],
                               self._shardings["embed_tokens"])
            return emb["embedding"].T
        head = self._stream(params["lm_head"], self._shardings["lm_head"])
        return head["kernel"]


def fuse_decode_params(params: Any, cfg: LlamaConfig) -> Any:
    """Collapse per-layer q/k/v kernels into one [D, (H+2Kv)·hd] matmul and
    gate/up into one [D, 2F] (the reference's fused qkv_gemm / mlp_gemm
    weight layout, csrc/transformer/inference/csrc/pt_binding.cpp): decode
    is latency-bound per kernel launch, so 7 matvecs/layer become 4.

    All matmul weights are cast to ``cfg.dtype`` HERE (params are stored
    fp32): the decode loop must stream 2 bytes/param, and relying on XLA to
    hoist a per-step astype out of the while_loop is not safe. Norm scales
    stay fp32 (the rms math is fp32). Works on scan-stacked params; call
    once (jitted) — the fused copies are what the decode program streams."""
    blocks = params["blocks"]["block"]
    attn = blocks["attn"]
    mlp = blocks["mlp"]
    cast = lambda a: a.astype(cfg.dtype)
    qkv = jnp.concatenate([cast(attn["q_proj"]["kernel"]),
                           cast(attn["k_proj"]["kernel"]),
                           cast(attn["v_proj"]["kernel"])], axis=-1)
    gateup = jnp.concatenate([cast(mlp["gate_proj"]["kernel"]),
                              cast(mlp["up_proj"]["kernel"])], axis=-1)
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["embed_tokens"] = {"embedding":
                           cast(params["embed_tokens"]["embedding"])}
    if "lm_head" in params:
        out["lm_head"] = {"kernel": cast(params["lm_head"]["kernel"])}
    out["blocks"] = {"block": {
        "input_norm": blocks["input_norm"],
        "post_attn_norm": blocks["post_attn_norm"],
        "qkv_proj": qkv,
        "o_proj": cast(attn["o_proj"]["kernel"]),
        "gateup_proj": gateup,
        "down_proj": cast(mlp["down_proj"]["kernel"]),
    }}
    return out


def quantize_fused_rowwise(fused: Any, cfg: LlamaConfig,
                           tiled: bool = True,
                           fused_mlp: bool = False) -> Any:
    """int8 weight-streaming layout for a :func:`fuse_decode_params` tree.

    Every decode matmul weight becomes ``{"q": int8, "scale": f32 rows}``
    (per-input-channel symmetric — ops/int8_matmul.quantize_rowwise;
    stacked block leaves are vmapped over the layer axis). The fused
    decoder dispatches these leaves through the Pallas weight-streaming
    kernel, so each decode step reads HALF the HBM bytes of bf16 — the
    bandwidth (not just capacity) half of the reference's int8 inference
    path (csrc/transformer/inference/csrc/dequantize.cu + pt_binding int8
    GEMMs). Tied-embeddings models get an int8 ``attend_head`` built from
    emb.T for the vocab matmul; the embedding table itself stays dense for
    the lookup.

    ``tiled`` (default): q is additionally re-laid as contiguous
    [nk, nn, bk, bn] DMA tiles (ops/int8_matmul.tile_rowwise) — +44%
    measured weight byte rate over the row-major layout (round-5 probe).
    Leaves whose N divides by no tile panel stay row-major (the kernel
    dispatches per leaf on q.ndim)."""
    from deepspeed_tpu.ops.int8_matmul import (
        pick_tile_block_n, quantize_rowwise, tile_rowwise)

    def maybe_tile(q, s):
        bn = pick_tile_block_n(q.shape[-1]) if tiled else None
        if bn is None:
            return {"q": q, "scale": s}
        qt, st = tile_rowwise(q, s, block_n=bn)
        return {"q": qt, "scale": st}

    def q2(w):
        return maybe_tile(*quantize_rowwise(w.astype(jnp.float32)))

    qstack = jax.vmap(lambda w: quantize_rowwise(w.astype(jnp.float32)))

    def qlayers(w, even_split=False):
        q, s = qstack(w)
        bn = pick_tile_block_n(q.shape[-1]) if tiled else None
        if even_split and bn is not None:
            # fused-MLP eligibility (quant.fused_mlp): the gate|up halves
            # must split at panel granularity — pick the widest panel
            # giving an EVEN panel count (7B: 22016/512=43 odd → 256)
            N = q.shape[-1]
            bn = next((b for b in (512, 256, 128)
                       if N % b == 0 and (N // b) % 2 == 0), bn)
        if bn is None:
            return {"q": q, "scale": s}
        qt, st = jax.vmap(lambda qq, ss: tile_rowwise(qq, ss, block_n=bn))(
            q, s)
        return {"q": qt, "scale": st}

    blk = fused["blocks"]["block"]
    out = {k: v for k, v in fused.items() if k not in ("blocks", "lm_head")}
    out["blocks"] = {"block": {
        "input_norm": blk["input_norm"],
        "post_attn_norm": blk["post_attn_norm"],
        "qkv_proj": qlayers(blk["qkv_proj"]),
        "o_proj": qlayers(blk["o_proj"]),
        "gateup_proj": qlayers(blk["gateup_proj"], even_split=fused_mlp),
        "down_proj": qlayers(blk["down_proj"]),
    }}
    if "lm_head" in fused:
        out["lm_head"] = {"kernel": q2(fused["lm_head"]["kernel"])}
    elif cfg.tie_embeddings:
        out["attend_head"] = q2(fused["embed_tokens"]["embedding"].T)
    return out


def retile_stream_tree(params: Any) -> Any:
    """One-time transform of a row-major int8 streaming tree (offline
    checkpoints, inference/offline_quant.py) to the contiguous-DMA tiled
    layout (ops/int8_matmul.tile_rowwise). MUTATES the dict tree in place,
    one q-leaf at a time, dropping each old leaf's reference before the
    next converts — a functional tree_map would hold old+new full trees
    simultaneously (2x ~7 GB at 7B, the difference between fitting and
    OOM on a 15.75 GB chip). Leaves whose N has no tile panel (or
    already-tiled trees) pass through unchanged."""
    from deepspeed_tpu.ops.int8_matmul import (
        pick_tile_block_n, tile_rowwise)

    def is_qleaf(x):
        return isinstance(x, dict) and set(x) == {"q", "scale"}

    def walk(node):
        if is_qleaf(node):
            q, s = node["q"], node["scale"]
            if q.ndim not in (2, 3):      # already tiled (4/5-dim)
                return
            bn = pick_tile_block_n(q.shape[-1])
            if bn is None:
                return
            fn = lambda qq, ss: tile_rowwise(qq, ss, block_n=bn)
            if q.ndim == 3:               # layer-stacked
                fn = jax.vmap(fn)
            qt, st = jax.jit(fn)(q, s)
            qt.block_until_ready()
            node["q"], node["scale"] = qt, st   # drops the dict's old refs
            del q, s                            # ...and the locals'
            return
        if isinstance(node, dict):
            for v in node.values():
                walk(v)

    walk(params)
    return params


def retile_gateup_for_fused_mlp(params: Any) -> Any:
    """Re-lay ``gateup_proj`` leaves so the gate|up halves split at tile
    PANEL granularity — the eligibility condition of the fused gated-MLP
    kernel (ops/int8_matmul.int8_mlp_fused). At Llama-7B shapes the
    default 512 panel gives 43 panels (odd: 22016/512) so the fused path
    could never engage; 256 gives 86 (43 per half — exact). Pure
    reshape/transpose per leaf (no requantization — tile geometry only).
    Called by the engine when ``quant.fused_mlp`` is enabled.

    PURE: returns a new tree rebuilding only the dicts on the path to a
    re-laid leaf; the caller-supplied tree is never mutated (other
    engine-side transforms may still hold it). Unaffected leaves are
    shared by reference, and each converted leaf's old buffer is only
    kept alive by the INPUT tree — callers that rebind (``params =
    retile_gateup_for_fused_mlp(params)``) keep peak extra memory to the
    gateup leaves alone."""

    from deepspeed_tpu.ops.int8_matmul import tile_rowwise

    def _untile(qt):
        nk, nn, bk, bn = qt.shape
        return qt.transpose(0, 2, 1, 3).reshape(nk * bk, nn * bn)

    def _retile(gu):
        q, s = gu["q"], gu["scale"]
        nn, bn = q.shape[-3], q.shape[-1]
        if not (nn % 2 and bn % 2 == 0 and bn >= 256):
            return gu
        # re-lay through the ONE blocking implementation (tile_rowwise;
        # Kp is already a block_k multiple so the scale passes through
        # unchanged)
        fn = lambda qq, ss: tile_rowwise(_untile(qq), ss, block_n=bn // 2)
        if q.ndim == 5:
            fn = jax.vmap(fn)
        qt, st = jax.jit(fn)(q, s)
        qt.block_until_ready()
        # keep the RETURNED scale: if tile_rowwise K-padded (non-default
        # original block_k), q and scale must stay length-matched or the
        # kernels' Kg_pad asserts fire mid-decode
        return {"q": qt, "scale": st}

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = node
        for key, val in node.items():
            gu = val
            if (key == "gateup_proj" and isinstance(gu, dict)
                    and gu.get("q") is not None and gu["q"].ndim in (4, 5)):
                new = _retile(gu)
            else:
                new = walk(val)
            if new is not val:
                if out is node:
                    out = dict(node)   # copy-on-write along the path
                out[key] = new
        return out

    return walk(params)


def decode_positions_and_mask(batch: int, T: int, S_max: int, cache_index,
                              attn_start=0):
    """Decode-step positions [B, T] and additive mask [1, 1, T, S_max]:
    rows attend to cache slots up to their own absolute position. Shared by
    the baseline and fused decoders so their masking can never diverge.

    ``attn_start`` (traced scalar): first valid cache slot — slots below it
    are LEFT-PADDING and masked out. Rotary/ALiBi attention is invariant to
    a uniform position shift, so left-padded prompts decode identically to
    unpadded ones; this is what lets generate() bucket prompt lengths into
    one compiled program (reference inference_context.h workspace reuse)."""
    positions = cache_index + jnp.arange(T, dtype=jnp.int32)[None, :]
    positions = jnp.broadcast_to(positions, (batch, T))
    row_pos = cache_index + jnp.arange(T)[:, None]          # [T, 1]
    col = jnp.arange(S_max)[None, :]                        # [1, S_max]
    valid = jnp.logical_and(col <= row_pos, col >= attn_start)
    mask = jnp.where(valid, 0.0, jnp.finfo(jnp.float32).min)
    return positions, mask[None, None, :, :]


class FusedLlamaDecoderModel:
    """Decode twin running on :func:`fuse_decode_params` weights — same
    logits as LlamaDecoderModel, fewer kernels per layer. Scan-stacked
    configs only (the only shape the engines produce). Plain class (no
    flax params of its own) with the decoder ``apply`` contract:
    ``apply({"params": fused_tree}, ids, caches, index)``."""

    def __init__(self, cfg: LlamaConfig, int8_block_n: int = 256,
                 w8a8_prefill: bool = False):
        self.cfg = cfg
        # int8-streaming N-panel width — session-tunable (the engine's
        # at-init microbench sets it; docs/PERF_ANALYSIS.md decode notes)
        self.int8_block_n = int8_block_n
        # prefill rows run native s8xs8 dots (int8 MXU) instead of a
        # convert-into-bf16-GEMM — see quant.w8a8_prefill. OPT-IN (the
        # per-token activation rounding is a numerics change; matches
        # the config default). Applied per matmul only above the
        # weight-size threshold where the halved feed bytes beat the
        # per-token quant chain's fixed cost (7B shapes win, 770M
        # shapes lose — measured round 5)
        self.w8a8_prefill = w8a8_prefill
        self.w8a8_min_weight_numel = 16_000_000
        # decode-step matvecs through the s8xs8 kernel (experimental,
        # engine-plumbed from quant.w8a8_decode; default off)
        self.w8a8_decode = False
        # fused gated-MLP decode kernel (quant.fused_mlp; default off)
        self.fused_mlp = False
        # paged attention arm (engine-plumbed from serve.attn_kernel):
        # "pallas" routes EVERY apply_paged call — decode steps, prefill
        # chunks and mixed ragged batches — through the unified ragged
        # Pallas kernel (ops/paged_attention_kernel.py) for both dense
        # and int8 pools; "reference" is the jnp gather path
        self.paged_attn_kernel = "reference"
        # tensor-parallel degree: >1 means this instance computes the
        # Megatron shard of every layer — q/kv heads and MLP columns
        # divided by tp_size (weights pre-permuted+sliced by
        # inference/tp_shard.py), activations replicated — and
        # ``tp_reduce`` (an all-reduce over the tensor axis, fp32 psum
        # or comm.quantized_all_reduce) closes each layer's two
        # row-parallel matmuls at the residual boundary
        self.tp_size = 1
        self.tp_reduce = None

    def _rms(self, x, scale):
        cfg = self.cfg
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        return (x32 * jax.lax.rsqrt(var + cfg.rms_norm_eps)
                * scale).astype(cfg.dtype)

    def _mm(self, x, w):
        """Matmul dispatch: dense kernels use the MXU dot; int8
        weight-streaming leaves (quantize_fused_rowwise) go through the
        Pallas kernel that converts int8→f32 in VMEM, halving the HBM
        bytes per decode step. Shared by the dense-cache ``apply`` and
        the paged ``apply_paged`` so the weight path cannot drift between
        the two serving modes.

        PREFILL rows (T >= 32: prompt processing — decode steps are
        T=1, speculative drafts <= ~16) skip the kernel: at M >> 1 the
        matmul is MXU-bound, not weight-bandwidth-bound, and the
        matvec kernel's VMEM-dequant pipeline only taxes it (measured
        round 4: 7B int8 TTFT 64.2 vs bf16 47.8 ms). Dequantize once
        per call and run the plain XLA GEMM — the convert streams the
        weight once, which prefill pays anyway."""
        cfg = self.cfg
        if isinstance(w, dict) and "q" in w:
            from deepspeed_tpu.ops.int8_matmul import int8_matmul

            Bm, Tm, Km = x.shape
            q, s = w["q"], w["scale"]
            if Tm >= 32:
                Kp = s.shape[0]
                if Kp > Km:                # offline/tile K padding
                    x = jnp.pad(x, ((0, 0), (0, 0), (0, Kp - Km)))
                xs32 = x.astype(jnp.float32) * s[None, None, :]
                # w8a8 only where the weight is big enough for the
                # halved feed bytes to beat the per-token quant
                # chain's fixed cost: 7B matmuls (K*N ~ 50-90M)
                # measured TTFT 80.5 -> 75.0/68.1 ms, while at 770M
                # (K*N ~ 7M) the same routing REGRESSED TTFT 40 ->
                # 50-63 ms — threshold between the two regimes
                _numel = 1
                for _d in q.shape:
                    _numel *= int(_d)
                if self.w8a8_prefill and \
                        _numel >= self.w8a8_min_weight_numel:
                    # w8a8: weight row scales are already folded into
                    # the activation above, so a per-token dynamic
                    # symmetric quant covers the whole contraction and
                    # the dot runs s8xs8->s32 on the int8 MXU (2x the
                    # bf16 systolic rate) with NO weight convert in
                    # the feed — the round-5 TTFT lever
                    # (quant.w8a8_prefill)
                    from deepspeed_tpu.ops.int8_matmul import (
                        quantize_per_row,
                    )

                    xq, sx = quantize_per_row(xs32)
                    if q.ndim == 4:
                        # one einsum over the tiled layout. A/B'd
                        # against unrolled per-k-slice batched dots
                        # (hypothesis: the 2-contracting-dim einsum
                        # re-lays the weight) — the unroll measured
                        # WORSE (7B TTFT 90.1 vs 75.0 ms, compiles
                        # 262 s vs 16) — keep the einsum
                        nk, nn, bk, bn = q.shape
                        x4 = xq.reshape(Bm, Tm, nk, bk)
                        y = jnp.einsum(
                            "mtkb,knbs->mtns", x4, q,
                            preferred_element_type=jnp.int32)
                        y = y.reshape(Bm, Tm, nn * bn)
                    else:
                        y = jax.lax.dot_general(
                            xq, q, (((2,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32)
                    return (y.astype(jnp.float32) * sx
                            ).astype(cfg.dtype)
                xs = xs32.astype(cfg.dtype)
                if q.ndim == 4:
                    # contract straight over the tiled layout — a
                    # row-major untile at 7B is a 6.7 GB int8 shuffle
                    # plus a 13 GB bf16 materialization per prefill
                    # (measured round 5: int8 TTFT 110 vs bf16 45 ms);
                    # the einsum lets XLA convert tile-wise into the
                    # MXU feed instead
                    nk, nn, bk, bn = q.shape
                    x4 = xs.reshape(Bm, Tm, nk, bk)
                    y = jnp.einsum("mtkb,knbs->mtns", x4,
                                   q.astype(cfg.dtype))
                    return y.reshape(Bm, Tm, nn * bn)
                return xs @ q.astype(cfg.dtype)
            if self.w8a8_decode and q.ndim == 4:
                from deepspeed_tpu.ops.int8_matmul import (
                    int8_matmul_tiled_w8a8,
                )

                y = int8_matmul_tiled_w8a8(
                    x.reshape(Bm * Tm, Km), q, s, out_dtype=cfg.dtype)
                return y.reshape(Bm, Tm, -1)
            y = int8_matmul(x.reshape(Bm * Tm, Km), q, s,
                            block_n=self.int8_block_n,
                            out_dtype=cfg.dtype)
            return y.reshape(Bm, Tm, -1)
        return x @ w

    def apply(self, variables, input_ids, kv_caches, cache_index,
              attn_start=0):
        """Dense-cache decode (the original contract): preallocated
        [L, B, S_max, n_kv, hd] caches (int8: 4-array variant), one write
        index for the whole batch."""
        fused_params = variables["params"]
        cfg = self.cfg
        B, T = input_ids.shape
        S_max = kv_caches[0].shape[2]
        n_kv = cfg.num_kv_heads or cfg.num_heads
        hd = cfg.hidden_size // cfg.num_heads
        positions, mask = decode_positions_and_mask(B, T, S_max, cache_index,
                                                    attn_start)
        kv_int8 = len(kv_caches) == 4
        rep = cfg.num_heads // n_kv

        from deepspeed_tpu.models.transformer import dot_product_attention

        def attn_int8(q, kq, ks, vq, vs):
            """dot_product_attention semantics over an int8 cache: the
            per-(slot, head) scales factor out of both dots over D, so
            the cache reads stay 1 byte/elem and dequant is a post-dot
            row multiply (softmax stays fp32, same as the dense core)."""
            scale = float(hd) ** -0.5
            qs = q * jnp.asarray(scale, q.dtype)
            scores = jnp.einsum("bqhd,bkhd->bhqk", qs,
                                kq.astype(q.dtype)).astype(jnp.float32)
            scores = scores * ks.transpose(0, 2, 1)[:, :, None, :]
            scores = scores + mask
            weights = jax.nn.softmax(scores, axis=-1)
            # fold the value scales into the probabilities (rows sum to
            # <= max |v| scale — still bf16-safe magnitudes)
            weights = (weights * vs.transpose(0, 2, 1)[:, :, None, :]
                       ).astype(q.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", weights,
                              vq.astype(q.dtype))

        def attn_core(q, k, v, cache):
            if kv_int8:
                ckq, cks, cvq, cvs = cache
                kq, ksc = quantize_kv_heads(k)
                vq, vsc = quantize_kv_heads(v)
                idx = (0, cache_index, 0)
                ckq = jax.lax.dynamic_update_slice(ckq, kq, idx + (0,))
                cks = jax.lax.dynamic_update_slice(cks, ksc, idx)
                cvq = jax.lax.dynamic_update_slice(cvq, vq, idx + (0,))
                cvs = jax.lax.dynamic_update_slice(cvs, vsc, idx)
                kkq, kks, vvq, vvs = ckq, cks, cvq, cvs
                if rep > 1:
                    kkq = jnp.repeat(kkq, rep, axis=2)
                    kks = jnp.repeat(kks, rep, axis=2)
                    vvq = jnp.repeat(vvq, rep, axis=2)
                    vvs = jnp.repeat(vvs, rep, axis=2)
                a = attn_int8(q, kkq, kks, vvq, vvs)
                return a, (ckq, cks, cvq, cvs)
            ck, cv = cache
            ck = jax.lax.dynamic_update_slice(ck, k,
                                              (0, cache_index, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v,
                                              (0, cache_index, 0, 0))
            kk, vv = ck, cv
            if rep > 1:
                kk = jnp.repeat(kk, rep, axis=2)
                vv = jnp.repeat(vv, rep, axis=2)
            a = dot_product_attention(q, kk, vv, mask=mask)
            return a, (ck, cv)

        return self._forward(fused_params, input_ids, positions, kv_caches,
                             attn_core)

    def apply_paged(self, variables, input_ids, kv_pools, block_tables,
                    write_pos, valid_len=None):
        """Paged-KV twin of :meth:`apply`: K/V live in shared block pools
        ([L, num_blocks, block_size, n_kv, hd]; the int8 variant is the
        4-tuple (kq, kscale, vq, vscale) with per-(token, head) scale
        pools [L, nb, bs, n_kv]) indexed
        through per-slot ``block_tables`` [B, W]. ``write_pos`` [B] is
        each slot's context length before this call — the running
        sequence length for decode steps, 0 for a cold prefill, and the
        cached-prefix offset for prefix-cache-hit prefills
        (the T tail tokens then write/attend from that offset);
        ``valid_len`` [B]
        masks right-padding/inactive slots (their writes land in the null
        block). Same weight path (``_mm``), same attention math — only
        the cache layout differs, which is what the exact-parity tests
        pin (tests/unit/inference/test_paged_decode.py)."""
        fused_params = variables["params"]
        cfg = self.cfg
        B, T = input_ids.shape
        n_kv = cfg.num_kv_heads or cfg.num_heads
        hd = cfg.hidden_size // cfg.num_heads
        positions = write_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        kv_int8 = len(kv_pools) == 4

        from deepspeed_tpu.ops.paged_attention import (
            paged_append, paged_append_scales,
        )
        from deepspeed_tpu.ops.paged_attention_kernel import (
            resolve_paged_attention,
        )

        # ONE dispatch point for the serving attention arm: the unified
        # ragged Pallas kernel streams live pool blocks for decode
        # tokens, prefill chunks and mixed ragged batches alike (no
        # T > 1 reference fallback anymore); the reference materializes
        # the full-width gather. ``valid_len`` doubles as the per-slot
        # query length (padded rows' writes already went to the null
        # block; their attention rows return zeros / garbage nobody
        # reads).
        attn_fn, attn_int8_fn = resolve_paged_attention(
            getattr(self, "paged_attn_kernel", "reference"))

        def attn_core(q, k, v, cache):
            if kv_int8:
                kqp, ksp, vqp, vsp = cache
                kq, ksc = quantize_kv_heads(k)
                vq, vsc = quantize_kv_heads(v)
                kqp, vqp = paged_append(kqp, vqp, kq, vq, block_tables,
                                        write_pos, valid_len)
                ksp = paged_append_scales(ksp, ksc, block_tables,
                                          write_pos, valid_len)
                vsp = paged_append_scales(vsp, vsc, block_tables,
                                          write_pos, valid_len)
                a = attn_int8_fn(q, kqp, ksp, vqp, vsp,
                                 block_tables, positions,
                                 q_lens=valid_len)
                return a, (kqp, ksp, vqp, vsp)
            kp, vp = cache
            kp, vp = paged_append(kp, vp, k, v, block_tables, write_pos,
                                  valid_len)
            a = attn_fn(q, kp, vp, block_tables, positions,
                        q_lens=valid_len)
            return a, (kp, vp)

        return self._forward(fused_params, input_ids, positions, kv_pools,
                             attn_core)

    def _forward(self, fused_params, input_ids, positions, caches,
                 attn_core):
        """Shared fused-decode body: embed → scan(blocks) → norm → head.
        ``attn_core(q, k, v, layer_cache) -> (ctx [B, T, H, hd],
        new_layer_cache)`` is the only seam between the dense-cache and
        paged-KV paths; everything else (weight dispatch, RoPE, fused
        MLP, head) is one implementation."""
        cfg = self.cfg
        assert cfg.scan_layers, "fused decode expects scan-stacked params"
        B, T = input_ids.shape
        # tensor parallelism: this body computes 1/tp of the heads and
        # MLP columns (weights pre-sliced on those axes); activations
        # (x, h) are replicated, and `reduce` closes the two row-parallel
        # matmuls per layer so the residual stream stays replicated —
        # everything downstream (norms, head, sampling) is unchanged
        tp = self.tp_size
        n_heads = cfg.num_heads // tp
        n_kv = (cfg.num_kv_heads or cfg.num_heads) // tp
        hd = cfg.hidden_size // cfg.num_heads
        reduce = self.tp_reduce if self.tp_reduce is not None else (
            lambda y: y)
        emb = fused_params["embed_tokens"]["embedding"]
        x = emb[input_ids].astype(cfg.dtype)
        mm, rms = self._mm, self._rms

        from deepspeed_tpu.models.transformer import rotary_embedding

        def block(x, layer):
            h = rms(x, layer["input_norm"]["scale"])
            qkv = mm(h, layer["qkv_proj"])
            q_sz = n_heads * hd
            q = qkv[..., :q_sz].reshape(B, T, n_heads, hd)
            k = qkv[..., q_sz:q_sz + n_kv * hd].reshape(B, T, n_kv, hd)
            v = qkv[..., q_sz + n_kv * hd:].reshape(B, T, n_kv, hd)
            q = rotary_embedding(q, positions, cfg.rope_base)
            k = rotary_embedding(k, positions, cfg.rope_base)
            a, new_cache = attn_core(q, k, v, layer["_cache"])
            a = a.reshape(B, T, q_sz)
            x = x + reduce(mm(a, layer["o_proj"]))
            h = rms(x, layer["post_attn_norm"]["scale"])
            guw, dw = layer["gateup_proj"], layer["down_proj"]
            # B*T bound sized by the kernel's VMEM h-scratch
            # (block_m x Kd_pad bf16): 64 rows x 22528 at 7B = 2.8 MB,
            # comfortably inside budget; 512 rows would need 23 MB and
            # fail at compile, not fall back
            if (self.fused_mlp and T < 32 and B * T <= 64
                    and isinstance(guw, dict) and isinstance(dw, dict)
                    and guw.get("q") is not None and guw["q"].ndim == 4
                    and dw.get("q") is not None and dw["q"].ndim == 4
                    # gate|up halves must split at panel granularity
                    and guw["q"].shape[1] % 2 == 0
                    and (guw["q"].shape[1] // 2) * guw["q"].shape[3]
                    == cfg.intermediate_size
                    # Mosaic lane alignment: every tile edge that becomes
                    # a traced slice offset must be 128-aligned (fall
                    # back gracefully, do not trip the kernel assert)
                    and all(d % 128 == 0
                            for d in (guw["q"].shape[2], guw["q"].shape[3],
                                      dw["q"].shape[2], dw["q"].shape[3]))):
                from deepspeed_tpu.ops.int8_matmul import int8_mlp_fused

                y = int8_mlp_fused(
                    h.reshape(B * T, h.shape[-1]), guw["q"], guw["scale"],
                    dw["q"], dw["scale"], out_dtype=cfg.dtype)
                x = x + reduce(y.reshape(B, T, -1))
            else:
                gu = mm(h, guw)
                g, u = jnp.split(gu, 2, axis=-1)
                x = x + reduce(mm(nn.silu(g) * u, dw))
            return x, new_cache

        def scan_body(x, layer_and_cache):
            layer, cache = layer_and_cache[0], layer_and_cache[1:]
            layer = dict(layer, _cache=cache)
            x, new_cache = block(x, layer)
            return x, new_cache

        x, new_caches = jax.lax.scan(
            scan_body, x,
            (fused_params["blocks"]["block"],) + tuple(caches))

        scale = fused_params["final_norm"]["scale"]
        x = rms(x, scale)
        if "attend_head" in fused_params:    # int8-streaming tied head
            logits = mm(x, fused_params["attend_head"])
        elif cfg.tie_embeddings:
            # matches the baseline's Embed.attend: both operands in
            # cfg.dtype (fp32 logits would double the vocab-matmul bytes)
            logits = x @ emb.T.astype(cfg.dtype)
        else:
            logits = mm(x, fused_params["lm_head"]["kernel"])
        return logits.astype(jnp.float32), new_caches


def init_kv_caches(cfg: LlamaConfig, batch_size: int, max_seq_len: int,
                   dtype=None, int8: bool = False):
    """Preallocated KV workspace (reference inference_context.h allocates one
    arena sized from max_out_tokens; here it is an explicit pytree the engine
    shards/donates).

    ``int8`` (``quant.kv_cache``): K/V store as int8 with per-(token, head)
    symmetric scales — a 4-tuple (kq, kscale, vq, vscale). Halves the
    per-step cache read, which DOMINATES weight traffic at long context /
    large batch (the reference's int8 inference cache paths,
    csrc/transformer/inference/csrc/dequantize.cu)."""
    n_kv = cfg.num_kv_heads or cfg.num_heads
    head_dim = cfg.hidden_size // cfg.num_heads
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, batch_size, max_seq_len, n_kv, head_dim)
    if int8:
        sshape = shape[:-1]
        return (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32),
                jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32))
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def init_paged_kv_pools(cfg: LlamaConfig, num_blocks: int, block_size: int,
                        dtype=None, int8: bool = False):
    """Shared K/V block pools for the paged decode paths
    (:class:`PagedLlamaDecoderModel` / ``FusedLlamaDecoderModel.apply_paged``).

    ``int8`` (``quant.kv_cache``): payloads store int8 with per-(token,
    head) symmetric scale pools — the paged analogue of the dense int8
    cache, sharing its dequant math (quantize_kv_heads)."""
    from deepspeed_tpu.ops.paged_attention import init_paged_pool

    n_kv = cfg.num_kv_heads or cfg.num_heads
    head_dim = cfg.hidden_size // cfg.num_heads
    return init_paged_pool(cfg.num_layers, num_blocks, block_size, n_kv,
                           head_dim, dtype or cfg.dtype, int8=int8)


def quantize_kv_heads(x: jnp.ndarray):
    """[B, T, H, D] float → (int8, scale [B, T, H]): symmetric absmax per
    appended (token, head) row. The scale factors out of the attention
    dots over D, so dequant is a post-dot multiply — the cache read
    itself stays int8."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax / 127.0, 1e-10)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def loss_fn(logits, labels, ignore_index: int = -100):
    """Causal LM cross-entropy with label masking."""
    valid = labels != ignore_index
    labels_safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels_safe[..., None], axis=-1)[..., 0]
    ll = jnp.where(valid, ll, 0.0)
    count = jnp.maximum(valid.sum(), 1)
    return -ll.sum() / count
