"""Transformer building blocks, TPU-first.

Functional replacement for the reference's fused transformer kernels
(``csrc/transformer/`` train kernels, ``csrc/transformer/inference/`` op set,
exposed as ``DeepSpeedTransformerLayer`` / ``DeepSpeedTransformerInference``).
On TPU the layer is expressed as plain traced ops — XLA fuses LN/bias/gelu/
softmax into the matmuls the way the reference's hand-fused kernels do — with
an optional Pallas flash-attention path for the attention core
(deepspeed_tpu/ops/flash_attention.py).

Layers are deliberately shape-static and batch-friendly: no data-dependent
Python control flow, so the whole stack jits into a single XLA program.
"""

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
from deepspeed_tpu.utils.jax_compat import shard_map
import jax.numpy as jnp

Dtype = Any


def make_causal_mask(seq_len: int, dtype=jnp.float32) -> jnp.ndarray:
    """[1, 1, S, S] additive causal mask."""
    mask = jnp.tril(jnp.ones((seq_len, seq_len), dtype=bool))
    return jnp.where(mask, 0.0, jnp.finfo(dtype).min)[None, None, :, :]


def rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def rotary_embedding(x: jnp.ndarray, positions: jnp.ndarray, base: float = 10000.0,
                     rotary_dim: Optional[int] = None, interleaved: bool = False):
    """RoPE applied over the last dim of [B, S, H, D] given positions [B, S].

    Analogue of the reference's in-kernel rotary
    (csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu), traced so XLA
    fuses it into the QK matmuls. ``rotary_dim`` rotates only the leading
    slice of each head (GPT-J/NeoX partial rotary); ``interleaved`` uses the
    rotate-every-two pairing (GPT-J) instead of the half-split pairing.
    """
    dim = x.shape[-1]
    rot = rotary_dim or dim
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    inv_freq = 1.0 / (base ** (jnp.arange(0, rot, 2, dtype=jnp.float32) / rot))
    freqs = positions[..., None].astype(jnp.float32) * inv_freq[None, None, :]
    if interleaved:
        # pairs are (x0,x1),(x2,x3),… — duplicate each freq for its pair
        cos = jnp.repeat(jnp.cos(freqs), 2, axis=-1)[:, :, None, :]
        sin = jnp.repeat(jnp.sin(freqs), 2, axis=-1)[:, :, None, :]
        x1 = x_rot[..., 0::2]
        x2 = x_rot[..., 1::2]
        rotated = jnp.stack([-x2, x1], axis=-1).reshape(x_rot.shape)
    else:
        emb = jnp.concatenate([freqs, freqs], axis=-1)  # [B, S, rot]
        cos = jnp.cos(emb)[:, :, None, :]
        sin = jnp.sin(emb)[:, :, None, :]
        rotated = rotate_half(x_rot)
    out = (x_rot * cos + rotated * sin).astype(x.dtype)
    if rot == dim:
        return out
    return jnp.concatenate([out, x_pass], axis=-1)


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """Per-head ALiBi slopes (BLOOM; reference builds these host-side in
    module_inject/containers/bloom.py and applies them in the softmax kernel)."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    n = 2 ** int(math.floor(math.log2(num_heads)))
    slopes = pow2_slopes(n)
    if n < num_heads:
        extra = pow2_slopes(2 * n)[0::2][:num_heads - n]
        slopes += extra
    return jnp.asarray(slopes, dtype=jnp.float32)


def alibi_bias(num_heads: int, q_len: int, k_len: int) -> jnp.ndarray:
    """[1, H, Q, K] additive attention bias, slope * -(relative distance)."""
    slopes = alibi_slopes(num_heads)  # [H]
    qpos = jnp.arange(k_len - q_len, k_len, dtype=jnp.float32)[:, None]
    kpos = jnp.arange(k_len, dtype=jnp.float32)[None, :]
    rel = kpos - qpos  # <=0 in the causal region
    return (slopes[None, :, None, None] * rel[None, None, :, :])


def dot_product_attention(q, k, v, mask=None, dropout_rng=None, dropout_rate=0.0,
                          deterministic=True, dtype=jnp.float32, scale=None):
    """Reference attention core in pure XLA ops.

    [B, S, H, D] layout. Softmax in fp32 for stability regardless of compute
    dtype (matches the reference kernels' fp32 accumulation). ``scale``
    overrides the default 1/sqrt(head_dim) (GPT-Neo uses 1.0).
    """
    depth = q.shape[-1]
    if scale is None:
        scale = float(depth) ** -0.5
    q = q * jnp.asarray(scale, dtype=q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if mask is not None:
        scores = scores + mask
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, weights.shape)
        weights = weights * keep / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _sequence_parallel_attention(q, k, v, impl: str):
    """Dispatch to Ulysses / ring context parallelism over the ambient mesh's
    ``sequence`` axis (requires the engine's mesh context; [B,S,H,D] logical
    arrays are mapped to per-device [B, S/P, H, D] shards)."""
    from jax.sharding import PartitionSpec

    from deepspeed_tpu.utils.jax_compat import get_abstract_mesh

    mesh = get_abstract_mesh()
    if mesh is None or "sequence" not in mesh.axis_names or \
            mesh.shape["sequence"] <= 1:
        # no sequence axis active — plain causal attention
        return dot_product_attention(q, k, v,
                                     mask=make_causal_mask(q.shape[1]))
    batch_axis = "data" if "data" in mesh.axis_names and \
        q.shape[0] % mesh.shape["data"] == 0 and mesh.shape["data"] > 1 else None
    spec = PartitionSpec(batch_axis, "sequence", None, None)

    if impl == "ulysses":
        from deepspeed_tpu.ops.ulysses import ulysses_attention
        inner = lambda q_, k_, v_: ulysses_attention(q_, k_, v_, causal=True)
    elif impl == "ring_flash":
        # flash kernel per ring block (O(block) memory per device even for
        # huge local shards) — ops/ring_attention.ring_flash_attention
        from deepspeed_tpu.ops.ring_attention import ring_flash_attention
        inner = lambda q_, k_, v_: ring_flash_attention(q_, k_, v_, True)
    else:
        from deepspeed_tpu.ops.ring_attention import ring_attention
        inner = lambda q_, k_, v_: ring_attention(q_, k_, v_, causal=True)

    # check_vma=False: the ring/ulysses cores carry cond-guarded psums
    # whose replication typing the checker cannot prove (same escape
    # hatch the op tests use; jax_compat maps it to check_rep on old jax)
    return shard_map(
        inner, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False)(q, k, v)


class RMSNorm(nn.Module):
    """RMS layernorm (reference csrc/transformer/inference/csrc/rms_norm.cu)."""

    epsilon: float = 1e-6
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.epsilon)
        return (y * scale).astype(self.dtype)


class SelfAttention(nn.Module):
    """Multi-head (optionally grouped-query) causal self-attention.

    TPU-native stand-in for the reference inference attention composition
    (``qkv_gemm`` → ``softmax_context`` → ``vector_matmul``,
    ops/transformer/inference/ds_attention.py:125). The KV-cache path for
    decoding lives in deepspeed_tpu/inference (functional cache arrays),
    not here.
    """

    num_heads: int
    num_kv_heads: Optional[int] = None
    head_dim: Optional[int] = None
    use_rope: bool = True
    rope_base: float = 10000.0
    rotary_dim: Optional[int] = None      # partial rotary (GPT-J/NeoX rotary_pct)
    rotary_interleaved: bool = False      # GPT-J rotate-every-two pairing
    dropout_rate: float = 0.0
    dtype: Dtype = jnp.bfloat16
    attention_impl: str = "auto"  # auto | xla | flash | ulysses | ring | ring_flash
    # the caller promises `mask` is exactly the causal mask (no padding /
    # ALiBi / windows) — required before "auto" may route to the flash
    # kernel, which implements causal masking internally and ignores `mask`
    assume_causal_mask: bool = False
    # "auto" crossover, measured on v5e. With the Pallas flash backward
    # (O(S) memory, blocked dq/dkv) the training crossover drops to ~1k:
    # full 770M train step measured +14% at S=1024 (15.0k vs 13.1k tok/s)
    # and 6.7x faster attention fwd+bwd at S=8192; below 1k the XLA
    # attention path still wins (S^2 traffic is small enough to fuse well).
    flash_min_seqlen: int = 1024
    use_bias: bool = False
    out_bias: Optional[bool] = None       # None → use_bias; GPT-Neo: qkv no, out yes
    attn_scale: Optional[float] = None    # None → 1/sqrt(head_dim); GPT-Neo: 1.0
    # paged decode arm (serve.attn_kernel): "pallas" routes EVERY paged
    # step — decode tokens, prefill chunks and mixed ragged batches —
    # through the unified ragged Pallas kernel (one live pool block at a
    # time in VMEM, per-row causal masking, GQA by indexing —
    # ops/paged_attention_kernel.py); the reference path materializes
    # the full-width pool gather.
    paged_attn_kernel: str = "reference"

    @nn.compact
    def __call__(self, x, mask=None, positions=None, deterministic=True,
                 kv_cache=None, cache_index=None, paged_cache=None,
                 block_tables=None, write_pos=None, valid_len=None):
        features = x.shape[-1]
        n_kv = self.num_kv_heads or self.num_heads
        head_dim = self.head_dim or features // self.num_heads
        dense = functools.partial(nn.Dense, use_bias=self.use_bias,
                                  dtype=self.dtype, param_dtype=jnp.float32)

        q = dense(self.num_heads * head_dim, name="q_proj")(x)
        k = dense(n_kv * head_dim, name="k_proj")(x)
        v = dense(n_kv * head_dim, name="v_proj")(x)

        B, S = x.shape[0], x.shape[1]
        q = q.reshape(B, S, self.num_heads, head_dim)
        k = k.reshape(B, S, n_kv, head_dim)
        v = v.reshape(B, S, n_kv, head_dim)

        if positions is None:
            positions = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, axis=0)
        if self.use_rope:
            q = rotary_embedding(q, positions, self.rope_base,
                                 self.rotary_dim, self.rotary_interleaved)
            k = rotary_embedding(k, positions, self.rope_base,
                                 self.rotary_dim, self.rotary_interleaved)

        updated_cache = None
        out = None
        if paged_cache is not None:
            # paged decode: scatter new k/v into the shared block pool
            # through this slot batch's block tables, then attend over the
            # per-slot view (ops/paged_attention; the caller's mask covers
            # context length + architecture terms)
            from deepspeed_tpu.ops.paged_attention import (
                paged_append, paged_gather,
            )

            kp, vp = paged_cache
            kp, vp = paged_append(kp, vp, k, v, block_tables, write_pos,
                                  valid_len)
            updated_cache = (kp, vp)
            if self.paged_attn_kernel == "pallas":
                # unified ragged Pallas attention (decode T=1, prefill
                # chunks T>1, mixed ragged batches): the kernel streams
                # live pool blocks and applies the per-row causal-context
                # mask itself; the caller's mask rides along as additive
                # extra terms (ALiBi, local windows) — its causal
                # component is redundant with the kernel's own and its
                # fully-masked entries stay consistent with the ragged
                # skip. When the caller PROMISES a pure causal-context
                # mask (assume_causal_mask — the paged llama blocks),
                # skip the mask input entirely: streaming a [B, H, T, S]
                # fp32 mask per step per layer is exactly the
                # max_context-width traffic the ragged kernel exists to
                # avoid. ``valid_len`` doubles as the per-slot query
                # length: padded rows return zeros (their KV writes
                # already went to the null block) and do not extend the
                # streamed context.
                from deepspeed_tpu.ops.paged_attention_kernel import (
                    paged_attention_pallas,
                )

                extra = None if self.assume_causal_mask else mask
                out = paged_attention_pallas(
                    q, kp, vp, block_tables, positions, mask_extra=extra,
                    scale=self.attn_scale, q_lens=valid_len)
            else:
                k = paged_gather(kp, block_tables)
                v = paged_gather(vp, block_tables)
        elif kv_cache is not None:
            # decode: append new k/v at cache_index (functional KV cache)
            ck, cv = kv_cache
            ck = jax.lax.dynamic_update_slice(ck, k, (0, cache_index, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, cache_index, 0, 0))
            k, v = ck, cv
            updated_cache = (ck, cv)

        if out is None:
            # grouped-query: repeat kv heads
            if n_kv != self.num_heads:
                rep = self.num_heads // n_kv
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)

            # "auto": XLA attention for short sequences (fusion wins), the
            # Pallas flash kernel (fwd + FlashAttention-2 bwd) once the S^2
            # score traffic dominates — measured training crossover ~1k on
            # v5e (see flash_min_seqlen).
            # flash implements ONLY causal masking at default scale, so auto
            # requires the caller's promise that `mask` is pure-causal and no
            # custom scale / active dropout is in play.
            impl = self.attention_impl
            if impl == "auto":
                flash_ok = (self.assume_causal_mask
                            and self.attn_scale is None
                            and (self.dropout_rate == 0.0 or deterministic))
                impl = "flash" if (flash_ok
                                   and x.shape[1] >= self.flash_min_seqlen) \
                    else "xla"
            caching = kv_cache is not None or paged_cache is not None
            if impl == "flash" and not caching:
                from deepspeed_tpu.ops.flash_attention import flash_attention

                out = flash_attention(q, k, v, causal=True)
            elif impl in ("ulysses", "ring", "ring_flash") and not caching:
                out = _sequence_parallel_attention(q, k, v, impl)
            else:
                dropout_rng = None
                if self.dropout_rate > 0.0 and not deterministic:
                    dropout_rng = self.make_rng("dropout")
                out = dot_product_attention(
                    q, k, v, mask=mask, dropout_rng=dropout_rng,
                    dropout_rate=self.dropout_rate,
                    deterministic=deterministic,
                    dtype=self.dtype, scale=self.attn_scale)

        out = out.reshape(B, S, self.num_heads * head_dim)
        o_bias = self.use_bias if self.out_bias is None else self.out_bias
        out = nn.Dense(features, use_bias=o_bias, dtype=self.dtype,
                       param_dtype=jnp.float32, name="o_proj")(out)
        if updated_cache is not None:
            return out, updated_cache
        return out


class GatedMLP(nn.Module):
    """SwiGLU MLP (reference gated_activation kernels / gated_mlp feature)."""

    intermediate_size: int
    dtype: Dtype = jnp.bfloat16
    use_bias: bool = False
    activation: Callable = nn.silu

    @nn.compact
    def __call__(self, x):
        from jax.ad_checkpoint import checkpoint_name

        features = x.shape[-1]
        dense = functools.partial(nn.Dense, use_bias=self.use_bias,
                                  dtype=self.dtype, param_dtype=jnp.float32)
        # named for remat policies: "save_mlp" keeps gate/up resident so the
        # backward recomputes only cheap elementwise ops + the attention
        # path — the two [tokens, intermediate] matmuls are the single
        # biggest recompute cost of whole-block remat
        gate = checkpoint_name(
            dense(self.intermediate_size, name="gate_proj")(x), "mlp_gate")
        up = checkpoint_name(
            dense(self.intermediate_size, name="up_proj")(x), "mlp_up")
        return dense(features, name="down_proj")(self.activation(gate) * up)


class MLP(nn.Module):
    """GELU MLP (GPT-2 style; reference csrc/transformer gelu kernels)."""

    intermediate_size: int
    dtype: Dtype = jnp.bfloat16
    use_bias: bool = True
    activation: Callable = functools.partial(nn.gelu, approximate=True)

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        dense = functools.partial(nn.Dense, use_bias=self.use_bias,
                                  dtype=self.dtype, param_dtype=jnp.float32)
        h = dense(self.intermediate_size, name="c_fc")(x)
        h = self.activation(h)
        return dense(features, name="c_proj")(h)
