from deepspeed_tpu.models.diffusion import (
    DiffusersAttention, DiffusersTransformerBlock, Diffusers2DTransformerConfig,
    DiffusionModelWrapper, DSUNet, DSVAE, SpatialTransformer2D,
)
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel, loss_fn

__all__ = ["GPT2Config", "GPT2Model", "LlamaConfig", "LlamaModel", "loss_fn",
           "DiffusersAttention", "DiffusersTransformerBlock",
           "Diffusers2DTransformerConfig", "DiffusionModelWrapper",
           "DSUNet", "DSVAE", "SpatialTransformer2D"]
