"""GPT-2-family decoder (learned positions, GELU MLP, LayerNorm).

Parity target: reference injection containers ``gpt2``/``gptneo``/``opt``
(deepspeed/module_inject/containers/). Also the BASELINE config #1 model
("GPT-2 125M ZeRO-1 single-host").
"""

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import MLP, SelfAttention, make_causal_mask


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    mlp_ratio: int = 4
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False
    tie_embeddings: bool = True

    @staticmethod
    def tiny(**kw) -> "GPT2Config":
        base = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=128)
        base.update(kw)
        return GPT2Config(**base)

    @staticmethod
    def gpt2_125m(**kw) -> "GPT2Config":
        return GPT2Config(**kw)


class GPT2Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="ln_1")(x)
        h = SelfAttention(num_heads=cfg.num_heads, use_rope=False,
                          dtype=cfg.dtype, use_bias=True, name="attn")(h, mask=mask)
        x = x + h
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="ln_2")(x)
        h = MLP(intermediate_size=cfg.mlp_ratio * cfg.hidden_size,
                dtype=cfg.dtype, name="mlp")(h)
        return x + h


class GPT2Model(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        B, S = input_ids.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="wte")
        wpe = nn.Embed(cfg.max_seq_len, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="wpe")
        if positions is None:
            positions = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, axis=0)
        x = wte(input_ids) + wpe(positions)
        mask = make_causal_mask(S)

        block_cls = GPT2Block
        if cfg.remat:
            block_cls = nn.remat(GPT2Block)
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"h_{i}")(x, mask)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        if cfg.tie_embeddings:
            logits = wte.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="lm_head")(x)
        return logits.astype(jnp.float32)
