"""Unified parametric transformer — the conversion target for HF model families.

Where the reference ships one injection container per architecture
(deepspeed/module_inject/containers/{gpt2,gptj,gptneo,gptneox,opt,bloom,
bert,distil_bert,…}.py) each copying weights into the same fused
``DeepSpeedTransformerInference`` module, the TPU build ships one parametric
flax model whose config spans the same architecture space:

- positions: learned (GPT-2/OPT/BERT), rotary incl. partial/interleaved
  (GPT-J/NeoX), ALiBi (BLOOM), or none
- norms: LayerNorm / RMSNorm, pre- or post-LN (BERT is post-LN)
- MLP: GELU (exact or tanh-approx) / ReLU / SiLU, gated (LLaMA) or plain
- residual topology: sequential, or parallel attention+MLP with shared
  (GPT-J) or separate (GPT-NeoX) input norms
- attention: MHA/GQA, per-layer local windows (GPT-Neo), causal or
  bidirectional (BERT), optional no-scaling (GPT-Neo)

``module_inject`` policies map an HF config + torch state_dict onto
(TransformerConfig, params) — see deepspeed_tpu/module_inject/.
"""

import dataclasses
import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import (
    MLP, GatedMLP, RMSNorm, SelfAttention, alibi_bias, alibi_slopes,
    make_causal_mask,
)

Dtype = Any


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    hidden_size: int = 64
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: Optional[int] = None
    intermediate_size: Optional[int] = None      # default 4*hidden
    max_seq_len: int = 128

    pos_emb: str = "learned"                     # learned|rotary|alibi|none
    pos_offset: int = 0                          # OPT stores positions at +2
    pos_from_mask: bool = False                  # OPT: positions = cumsum(mask)-1
    rope_base: float = 10000.0
    rotary_dim: Optional[int] = None             # partial rotary
    rotary_interleaved: bool = False             # GPT-J pairing

    norm: str = "layernorm"                      # layernorm|rmsnorm
    norm_eps: float = 1e-5
    pre_ln: bool = True                          # False → post-LN (BERT)
    final_norm: bool = True

    activation: str = "gelu_new"                 # gelu|gelu_new|relu|silu
    gated_mlp: bool = False

    parallel_attn: bool = False                  # GPT-J / GPT-NeoX topology
    parallel_shared_ln: bool = True              # GPT-J shares ln_1; NeoX doesn't

    causal: bool = True                          # False → encoder (BERT)
    attn_windows: Optional[Tuple[Optional[int], ...]] = None  # per-layer local window
    attn_scale: Optional[float] = None           # None → 1/sqrt(d); GPT-Neo: 1.0

    attn_bias: bool = True                       # bias on qkv projections
    attn_out_bias: Optional[bool] = None         # None → attn_bias (GPT-Neo differs)
    mlp_bias: bool = True
    tie_embeddings: bool = True
    token_type_vocab: int = 0                    # >0 → BERT token_type embeddings
    embed_ln: bool = False                       # BLOOM word_embeddings_layernorm
    lm_head: bool = True                         # False → encoder output only
    lm_head_bias: bool = False                   # GPT-J's untied head has bias

    # MoE blocks (Mixtral-style; reference containers/base_moe.py target)
    moe_num_experts: int = 0                     # 0 → dense MLP everywhere
    moe_top_k: int = 2
    moe_layer_freq: int = 1                      # every Nth layer is MoE
    moe_norm_topk: bool = True                   # renormalize top-k weights
    # "swiglu" (Mixtral: gate/up/down, no bias) or "mlp" (Megatron-DS
    # experts: c_fc → activation → c_proj with biases — the layout of
    # reference moe/experts.py expert copies)
    moe_expert_style: str = "swiglu"

    dtype: Any = jnp.float32
    remat: bool = False
    # streamed twin only: hoist the per-layer host→device parameter fetch
    # OUT of the jax.checkpoint region. Inside-fetch (default) re-fetches
    # each layer's weights during backward — the best memory profile (one
    # layer's device copy live at any instant) — but the axon tunnel's
    # AOT helper refuses the rematerialized fetch's transposed program
    # ("layout for this output is not set to host memory", round-5
    # bisect: remat alone triggers it, tie/pos/bias do not). Outside-
    # fetch makes the device copy a saved remat residual: every layer's
    # bf16 copy stays HBM-resident fwd→bwd (~2 B/param — fine at the
    # 1-3B scales this tier serves on one chip), and the program
    # compiles through the tunnel.
    stream_fetch_outside_remat: bool = False

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    def is_moe_layer(self, layer_idx: int) -> bool:
        return (self.moe_num_experts > 0
                and layer_idx % max(self.moe_layer_freq, 1) == 0)

    @staticmethod
    def tiny(**kw) -> "TransformerConfig":
        return TransformerConfig(**kw)


def _act(name: str):
    return {"gelu": lambda x: nn.gelu(x, approximate=False),
            "gelu_new": lambda x: nn.gelu(x, approximate=True),
            "quick_gelu": lambda x: x * nn.sigmoid(1.702 * x),  # CLIP
            "relu": nn.relu,
            "silu": nn.silu}[name]


def _norm(cfg: TransformerConfig, name: str):
    if cfg.norm == "rmsnorm":
        return RMSNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype, name=name)
    return nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name=name)


class DenseRoutedMoE(nn.Module):
    """Mixtral-exact top-k routed expert MLP (softmax-over-all → top-k →
    optional renormalize → weighted sum of selected SwiGLU experts).

    Dense dispatch: every expert runs on every token and non-selected
    contributions are zero-weighted — exact for inference injection and
    correctness tests. The capacity-based all_to_all dispatch for efficient
    expert-parallel training/serving is deepspeed_tpu.moe.layer.MoE; this
    module exists so converted HF MoE checkpoints reproduce reference
    logits bit-for-bit in routing.
    """

    num_experts: int
    top_k: int
    intermediate_size: int
    norm_topk: bool = True
    dtype: Any = jnp.float32
    # "swiglu": gate/up/down einsum stacks, no bias (Mixtral). "mlp":
    # c_fc → activation → c_proj with biases — the Megatron-DS expert
    # layout (reference moe/experts.py holds num_experts copies of the
    # dense MLP; here they run as ONE batched einsum over the E axis)
    expert_style: str = "swiglu"
    activation: Any = None                       # "mlp" style only

    @nn.compact
    def __call__(self, x):                      # [B, S, D]
        B, S, D = x.shape
        E, F, K = self.num_experts, self.intermediate_size, self.top_k
        t = x.reshape(B * S, D)
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="gate")(
            t.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        vals, idx = jax.lax.top_k(probs, K)     # [T, K]
        if self.norm_topk:
            vals = vals / jnp.maximum(vals.sum(-1, keepdims=True), 1e-20)
        w = (jax.nn.one_hot(idx, E, dtype=jnp.float32)
             * vals[..., None]).sum(axis=1)     # [T, E]

        init = nn.initializers.lecun_normal()
        td = t.astype(self.dtype)
        if self.expert_style == "mlp":
            wf = self.param("c_fc", init, (E, D, F), jnp.float32)
            bf = self.param("c_fc_bias", nn.initializers.zeros, (E, F),
                            jnp.float32)
            wp = self.param("c_proj", init, (E, F, D), jnp.float32)
            bp = self.param("c_proj_bias", nn.initializers.zeros, (E, D),
                            jnp.float32)
            act = self.activation or (lambda v: nn.gelu(v,
                                                        approximate=False))
            h = (jnp.einsum("td,edf->tef", td, wf.astype(self.dtype))
                 + bf.astype(self.dtype)[None])
            y = (jnp.einsum("tef,efd->ted", act(h), wp.astype(self.dtype))
                 + bp.astype(self.dtype)[None])
        else:
            wg = self.param("gate_proj", init, (E, D, F), jnp.float32)
            wu = self.param("up_proj", init, (E, D, F), jnp.float32)
            wd = self.param("down_proj", init, (E, F, D), jnp.float32)
            g = jnp.einsum("td,edf->tef", td, wg.astype(self.dtype))
            u = jnp.einsum("td,edf->tef", td, wu.astype(self.dtype))
            h = nn.silu(g) * u
            y = jnp.einsum("tef,efd->ted", h, wd.astype(self.dtype))
        out = jnp.einsum("ted,te->td", y.astype(jnp.float32), w)
        return out.reshape(B, S, D).astype(x.dtype)


def _derive_positions(cfg: TransformerConfig, input_ids, positions,
                      attention_mask):
    """Position ids for the LM forward — shared by :class:`TransformerLM`
    and its streamed twin so the two can never drift."""
    if positions is not None:
        return positions
    B, S = input_ids.shape
    if cfg.pos_from_mask and attention_mask is not None:
        # HF OPT: positions count real tokens only, so left-padded
        # batches start at position 0 (OPTLearnedPositionalEmbedding)
        am = attention_mask.astype(jnp.int32)
        return jnp.clip(jnp.cumsum(am, axis=-1) - 1, 0, None)
    return jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, axis=0)


def _derive_base_mask(cfg: TransformerConfig, S: int, attention_mask):
    """Additive attention mask before per-layer windows — shared by
    :class:`TransformerLM` and its streamed twin."""
    if cfg.causal:
        base_mask = make_causal_mask(S)
    else:
        base_mask = jnp.zeros((1, 1, S, S), dtype=jnp.float32)
    if attention_mask is not None:
        pad = jnp.where(attention_mask[:, None, None, :].astype(bool),
                        0.0, jnp.finfo(jnp.float32).min)
        base_mask = base_mask + pad
    if cfg.pos_emb == "alibi":
        base_mask = base_mask + alibi_bias(cfg.num_heads, S, S)
    return base_mask


class UnifiedBlock(nn.Module):
    """One block spanning the policy zoo's topology space.

    With ``kv_cache``/``cache_index`` the attention appends to a functional
    KV cache and the block returns ``(out, new_cache)`` — the decode-mode
    contract mirroring the reference's preallocated inference arena
    (csrc/transformer/inference/includes/inference_context.h); without, it
    is the training/prefill forward returning ``out``.
    """

    cfg: TransformerConfig
    layer_idx: int = 0
    # paged decode arm (serve.attn_kernel) — forwarded to SelfAttention;
    # inert outside the paged-cache path
    attn_kernel: str = "reference"

    @nn.compact
    def __call__(self, x, mask, positions, kv_cache=None, cache_index=None,
                 paged_cache=None, block_tables=None, write_pos=None,
                 valid_len=None):
        cfg = self.cfg
        attn = SelfAttention(
            num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
            use_rope=cfg.pos_emb == "rotary", rope_base=cfg.rope_base,
            rotary_dim=cfg.rotary_dim, rotary_interleaved=cfg.rotary_interleaved,
            dtype=cfg.dtype, use_bias=cfg.attn_bias,
            out_bias=cfg.attn_out_bias, attn_scale=cfg.attn_scale,
            paged_attn_kernel=self.attn_kernel,
            name="attn")
        if cfg.is_moe_layer(self.layer_idx):
            mlp = DenseRoutedMoE(
                num_experts=cfg.moe_num_experts, top_k=cfg.moe_top_k,
                intermediate_size=cfg.ffn_size, norm_topk=cfg.moe_norm_topk,
                expert_style=cfg.moe_expert_style,
                activation=(_act(cfg.activation)
                            if cfg.moe_expert_style == "mlp" else None),
                dtype=cfg.dtype, name="moe")
        elif cfg.gated_mlp:
            mlp = GatedMLP(intermediate_size=cfg.ffn_size, dtype=cfg.dtype,
                           use_bias=cfg.mlp_bias, activation=_act(cfg.activation),
                           name="mlp")
        else:
            mlp = MLP(intermediate_size=cfg.ffn_size, dtype=cfg.dtype,
                      use_bias=cfg.mlp_bias, activation=_act(cfg.activation),
                      name="mlp")

        caching = kv_cache is not None or paged_cache is not None

        def attend(h):
            # SelfAttention returns (out, cache) iff a cache is given
            return attn(h, mask=mask, positions=positions,
                        kv_cache=kv_cache, cache_index=cache_index,
                        paged_cache=paged_cache, block_tables=block_tables,
                        write_pos=write_pos, valid_len=valid_len)

        new_cache = None
        if cfg.parallel_attn:
            # x + attn(ln1(x)) + mlp(ln1(x) or ln2(x))  (GPT-J / GPT-NeoX)
            h1 = _norm(cfg, "ln_1")(x)
            h2 = h1 if cfg.parallel_shared_ln else _norm(cfg, "ln_2")(x)
            a = attend(h1)
            if caching:
                a, new_cache = a
            out = x + a + mlp(h2)
        elif cfg.pre_ln:
            a = attend(_norm(cfg, "ln_1")(x))
            if caching:
                a, new_cache = a
            x = x + a
            out = x + mlp(_norm(cfg, "ln_2")(x))
        else:
            # post-LN (BERT): ln(x + sub(x))
            a = attend(x)
            if caching:
                a, new_cache = a
            x = _norm(cfg, "ln_1")(x + a)
            out = _norm(cfg, "ln_2")(x + mlp(x))
        if caching:
            return out, new_cache
        return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _fetch_leaf(w, sharding):
    """host→device parameter fetch whose VJP does NOT transpose into a
    device→host move: the cotangent passes through device-resident and the
    engine moves the assembled grad tree host-side at the PROGRAM boundary
    (jit out_shardings), outside AD.

    Why: differentiating a plain ``jax.device_put(w_host, device)`` makes
    AD emit the transposed copy — an output pinned to host memory in the
    middle of the backward — which the axon tunnel's AOT helper refuses
    for unrolled programs ("layout for this output is not set to host
    memory", round-4 scope note). The grouped-stream tier proves
    host-memory moves at program boundaries DO work on this path; this
    custom_vjp keeps all mid-graph values device-resident."""
    return jax.device_put(w, sharding)


def _fetch_leaf_fwd(w, sharding):
    return jax.device_put(w, sharding), None


def _fetch_leaf_bwd(sharding, _res, g):
    return (g,)


_fetch_leaf.defvjp(_fetch_leaf_fwd, _fetch_leaf_bwd)


def _fetch_tree(tree, shardings):
    return jax.tree_util.tree_map(_fetch_leaf, tree, shardings)


class StreamedTransformerLM:
    """Apply-twin of :class:`TransformerLM` that streams host-resident
    parameters into device memory at each submodule's point of use — the
    MODEL-AGNOSTIC ZeRO-3 parameter-offload compute path (reference
    ``runtime/zero/parameter_offload.py:201``'s fetch/release hooks work on
    any ``nn.Module``; this twin gives the same generality to every
    architecture the 13 injection policies produce, including MoE layers).

    Unlike :class:`~deepspeed_tpu.models.llama.StreamedLlamaModel` (stacked
    ``lax.scan`` over homogeneous blocks), the unified model's layers are
    heterogeneous (per-layer attention windows, interleaved MoE), so the
    fetch is an explicit per-layer ``jax.device_put`` of ``layer_{i}``'s
    subtree inside an unrolled loop: each layer's weights become device-
    resident at their first use and XLA frees them after their last, so
    peak HBM holds ONE layer's weights (+ activations), never the tree.

    Math parity: every submodule is applied through the REAL flax modules
    (``UnifiedBlock.apply``, ``nn.Embed``, ``_norm``, ``nn.Dense``) on the
    streamed subtrees, so outputs are bit-identical to
    ``TransformerLM.apply`` on the same weights
    (tests/unit/test_param_offload.py).
    """

    def __init__(self, cfg: TransformerConfig, stream_shardings: Any):
        self.cfg = cfg
        self._shardings = stream_shardings

    def _stream(self, params, key):
        return _fetch_tree(params[key], self._shardings[key])

    def apply(self, variables, input_ids, positions=None,
              attention_mask=None, token_type_ids=None, rngs=None,
              return_hidden=False):
        params = variables["params"]
        cfg = self.cfg
        B, S = input_ids.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="wte")
        wte_p = self._stream(params, "wte")
        x = wte.apply({"params": wte_p}, input_ids)
        positions = _derive_positions(cfg, input_ids, positions,
                                      attention_mask)
        if cfg.pos_emb == "learned":
            wpe = nn.Embed(cfg.max_seq_len + cfg.pos_offset, cfg.hidden_size,
                           dtype=cfg.dtype, param_dtype=jnp.float32,
                           name="wpe")
            x = x + wpe.apply({"params": self._stream(params, "wpe")},
                              positions + cfg.pos_offset)
        if cfg.token_type_vocab:
            tte = nn.Embed(cfg.token_type_vocab, cfg.hidden_size,
                           dtype=cfg.dtype, param_dtype=jnp.float32,
                           name="wtte")
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + tte.apply({"params": self._stream(params, "wtte")},
                              token_type_ids)
        if cfg.embed_ln or not cfg.pre_ln:
            x = _norm(cfg, "ln_emb").apply(
                {"params": self._stream(params, "ln_emb")}, x)

        base_mask = _derive_base_mask(cfg, S, attention_mask)

        for i in range(cfg.num_layers):
            mask = base_mask
            if cfg.attn_windows is not None and cfg.attn_windows[i]:
                mask = mask + _window_mask(S, cfg.attn_windows[i])
            block = UnifiedBlock(cfg, layer_idx=i)
            sh = self._shardings[f"layer_{i}"]

            if cfg.remat and cfg.stream_fetch_outside_remat:
                # fetch OUTSIDE the remat region (see the config field):
                # the device copy is a saved residual — resident fwd→bwd —
                # and the checkpointed body itself touches no host memory
                def body(h, w, block=block, mask=mask):
                    return block.apply({"params": w}, h, mask, positions,
                                       rngs=rngs)

                x = jax.checkpoint(body)(
                    x, _fetch_tree(params[f"layer_{i}"], sh))
            else:
                def body(h, w_host, block=block, mask=mask, sh=sh):
                    # fetch INSIDE the (possibly rematerialized) body: the
                    # host tree is the saved residual, and backward
                    # re-fetches the device copy instead of keeping every
                    # layer HBM-resident
                    w = _fetch_tree(w_host, sh)
                    return block.apply({"params": w}, h, mask, positions,
                                       rngs=rngs)

                if cfg.remat:
                    body = jax.checkpoint(body)
                x = body(x, params[f"layer_{i}"])

        if cfg.final_norm:
            x = _norm(cfg, "ln_f").apply(
                {"params": self._stream(params, "ln_f")}, x)
        if return_hidden or not cfg.lm_head:
            return x if return_hidden else x.astype(jnp.float32)
        if cfg.tie_embeddings:
            logits = wte.apply({"params": wte_p}, x.astype(jnp.float32),
                               method="attend")
        else:
            head = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                            dtype=cfg.dtype, param_dtype=jnp.float32,
                            name="lm_head")
            logits = head.apply(
                {"params": self._stream(params, "lm_head")}, x)
        return logits.astype(jnp.float32)

    def lm_kernel(self, params):
        """Device-resident [H, V] head kernel for the chunked LM loss."""
        if self.cfg.tie_embeddings:
            return self._stream(params, "wte")["embedding"].T
        return self._stream(params, "lm_head")["kernel"]


def _window_mask(seq_len: int, window: int) -> jnp.ndarray:
    """Additive causal mask restricted to a local window (GPT-Neo local attn)."""
    i = jnp.arange(seq_len)[:, None]
    j = jnp.arange(seq_len)[None, :]
    ok = (j <= i) & (j > i - window)
    return jnp.where(ok, 0.0, jnp.finfo(jnp.float32).min)[None, None, :, :]


class TransformerLM(nn.Module):
    """Decoder/encoder LM over UnifiedBlocks.

    Returns fp32 logits (``lm_head``) or final hidden states (encoder mode).
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, attention_mask=None,
                 token_type_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="wte")
        x = wte(input_ids)
        positions = _derive_positions(cfg, input_ids, positions,
                                      attention_mask)
        if cfg.pos_emb == "learned":
            wpe = nn.Embed(cfg.max_seq_len + cfg.pos_offset, cfg.hidden_size,
                           dtype=cfg.dtype, param_dtype=jnp.float32, name="wpe")
            x = x + wpe(positions + cfg.pos_offset)
        if cfg.token_type_vocab:
            tte = nn.Embed(cfg.token_type_vocab, cfg.hidden_size, dtype=cfg.dtype,
                           param_dtype=jnp.float32, name="wtte")
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + tte(token_type_ids)
        if cfg.embed_ln or not cfg.pre_ln:
            # BLOOM word_embeddings_layernorm / BERT embeddings.LayerNorm
            x = _norm(cfg, "ln_emb")(x)

        base_mask = _derive_base_mask(cfg, S, attention_mask)

        block_cls = nn.remat(UnifiedBlock) if cfg.remat else UnifiedBlock
        for i in range(cfg.num_layers):
            mask = base_mask
            if cfg.attn_windows is not None and cfg.attn_windows[i]:
                mask = mask + _window_mask(S, cfg.attn_windows[i])
            x = block_cls(cfg, layer_idx=i, name=f"layer_{i}")(x, mask, positions)

        if cfg.final_norm:
            x = _norm(cfg, "ln_f")(x)
        if not cfg.lm_head:
            return x.astype(jnp.float32)
        if cfg.tie_embeddings:
            logits = wte.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                              dtype=cfg.dtype, param_dtype=jnp.float32,
                              name="lm_head")(x)
        return logits.astype(jnp.float32)

    def streamed_twin(self, stream_shardings):
        """Scanned-model streaming protocol (engine
        ``_setup_param_streaming``): an apply-twin that fetches host-
        resident params per submodule — ZeRO-3 parameter offload for every
        policy architecture, MoE layers included."""
        return StreamedTransformerLM(self.cfg, stream_shardings)


class TransformerDecoderModel(nn.Module):
    """Decode-mode twin of :class:`TransformerLM`: same parameter tree, takes
    and returns preallocated KV caches — this is what makes
    ``init_inference(...).generate()`` work for every converted architecture
    (gpt2/gptj/gptneo/gptneox/opt/bloom/mixtral/…), matching the breadth of
    the reference's ``InferenceEngine.generate()``
    (deepspeed/inference/engine.py:614) over its 18 injection policies.

    kv_caches: (k, v) arrays of shape [L, B, S_max, n_kv, head_dim].
    cache_index: int32 scalar — write offset (tokens already in cache).
    Prompts are assumed unpadded (positions = cache_index + arange), the
    same contract as generation through the reference's fused kernels.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, input_ids, kv_caches, cache_index, attn_start=0):
        cfg = self.cfg
        if not cfg.causal or not cfg.lm_head:
            raise ValueError(
                "TransformerDecoderModel requires a causal LM config "
                "(encoder architectures cannot generate)")
        B, T = input_ids.shape
        S_max = kv_caches[0].shape[2]
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="wte")
        x = wte(input_ids)
        positions = cache_index + jnp.arange(T, dtype=jnp.int32)[None, :]
        positions = jnp.broadcast_to(positions, (B, T))
        if cfg.pos_emb == "learned":
            wpe = nn.Embed(cfg.max_seq_len + cfg.pos_offset, cfg.hidden_size,
                           dtype=cfg.dtype, param_dtype=jnp.float32, name="wpe")
            x = x + wpe(positions + cfg.pos_offset)
        if cfg.token_type_vocab:
            tte = nn.Embed(cfg.token_type_vocab, cfg.hidden_size, dtype=cfg.dtype,
                           param_dtype=jnp.float32, name="wtte")
            x = x + tte(jnp.zeros_like(input_ids))
        if cfg.embed_ln or not cfg.pre_ln:
            x = _norm(cfg, "ln_emb")(x)

        # rows attend to cache slots up to their own absolute position;
        # slots below attn_start are left-padding (prompt bucketing —
        # rotary/alibi are shift-invariant; learned positions never pad)
        row_pos = cache_index + jnp.arange(T)[:, None]           # [T, 1]
        col = jnp.arange(S_max)[None, :]                         # [1, S_max]
        neg = jnp.finfo(jnp.float32).min
        base_mask = jnp.where(
            jnp.logical_and(col <= row_pos, col >= attn_start), 0.0,
            neg)[None, None, :, :]
        if cfg.pos_emb == "alibi":
            slopes = alibi_slopes(cfg.num_heads)
            rel = (col - row_pos).astype(jnp.float32)            # [T, S_max]
            base_mask = base_mask + (slopes[None, :, None, None]
                                     * rel[None, None, :, :])

        new_k, new_v = [], []
        for i in range(cfg.num_layers):
            mask = base_mask
            if cfg.attn_windows is not None and cfg.attn_windows[i]:
                w = cfg.attn_windows[i]
                mask = mask + jnp.where(col > row_pos - w, 0.0,
                                        neg)[None, None, :, :]
            x, (ck, cv) = UnifiedBlock(cfg, layer_idx=i, name=f"layer_{i}")(
                x, mask, positions,
                kv_cache=(kv_caches[0][i], kv_caches[1][i]),
                cache_index=cache_index)
            new_k.append(ck)
            new_v.append(cv)
        new_caches = (jnp.stack(new_k), jnp.stack(new_v))

        if cfg.final_norm:
            x = _norm(cfg, "ln_f")(x)
        if cfg.tie_embeddings:
            logits = wte.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                              dtype=cfg.dtype, param_dtype=jnp.float32,
                              name="lm_head")(x)
        return logits.astype(jnp.float32), new_caches


class PagedTransformerDecoderModel(nn.Module):
    """Paged-KV decode twin of :class:`TransformerDecoderModel`: same
    parameter tree, but K/V live in a shared block pool indexed through
    per-slot block tables (ops/paged_attention) instead of a dense
    [L, B, S_max, ...] arena — the layout that lets the continuous-batching
    scheduler recycle cache capacity at sequence granularity while this
    module's shapes stay static (fixed slot count, fixed table width).

    kv_pools: (k_pool, v_pool) of [L, num_blocks, block_size, n_kv, hd].
    block_tables: int32 [B, W]; write_pos: int32 [B] — per-slot context
    length before this call (0 for a cold prefill; the cached-prefix
    length for an offset prefill under the serving prefix cache — all
    position/mask/learned-embedding math derives from it, so a T > 1
    tail at any offset attends the shared prefix correctly);
    valid_len: int32 [B] or None —
    tokens of the T axis that are real per row (right-padding/inactive
    slots write to the null block). ``attn_kernel``: paged decode arm
    (serve.attn_kernel) — the Pallas ragged kernel consumes the SAME
    additive mask terms (ALiBi, per-layer windows) as extra bias on top
    of its own context masking, so the architecture zoo serves through
    either arm. Exact same mask/position math as the dense twin, only
    over the gathered block axis.
    """

    cfg: TransformerConfig
    attn_kernel: str = "reference"

    @nn.compact
    def __call__(self, input_ids, kv_pools, block_tables, write_pos,
                 valid_len=None):
        cfg = self.cfg
        if not cfg.causal or not cfg.lm_head:
            raise ValueError(
                "PagedTransformerDecoderModel requires a causal LM config "
                "(encoder architectures cannot generate)")
        B, T = input_ids.shape
        S = block_tables.shape[1] * kv_pools[0].shape[2]
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="wte")
        x = wte(input_ids)
        positions = write_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        if cfg.pos_emb == "learned":
            wpe = nn.Embed(cfg.max_seq_len + cfg.pos_offset, cfg.hidden_size,
                           dtype=cfg.dtype, param_dtype=jnp.float32, name="wpe")
            # clamp: padded/inactive rows may carry positions past the
            # table; their outputs are masked/ignored, but the gather
            # must not hit XLA OOB semantics mid-batch
            safe = jnp.clip(positions + cfg.pos_offset, 0,
                            cfg.max_seq_len + cfg.pos_offset - 1)
            x = x + wpe(safe)
        if cfg.token_type_vocab:
            tte = nn.Embed(cfg.token_type_vocab, cfg.hidden_size, dtype=cfg.dtype,
                           param_dtype=jnp.float32, name="wtte")
            x = x + tte(jnp.zeros_like(input_ids))
        if cfg.embed_ln or not cfg.pre_ln:
            x = _norm(cfg, "ln_emb")(x)

        # same semantics as the dense twin's mask, over the gathered axis:
        # column j of the per-slot view IS logical position j (the ONE
        # causal-context rule, shared with the llama paged twins)
        from deepspeed_tpu.ops.paged_attention import paged_context_mask

        row_pos = positions                                      # [B, T]
        col = jnp.arange(S, dtype=jnp.int32)[None, None, None, :]
        neg = jnp.finfo(jnp.float32).min
        base_mask = paged_context_mask(row_pos, S)
        if cfg.pos_emb == "alibi":
            slopes = alibi_slopes(cfg.num_heads)
            rel = (col[0, 0] - row_pos[:, :, None]).astype(jnp.float32)
            base_mask = base_mask + (slopes[None, :, None, None]
                                     * rel[:, None, :, :])

        new_k, new_v = [], []
        for i in range(cfg.num_layers):
            mask = base_mask
            if cfg.attn_windows is not None and cfg.attn_windows[i]:
                w = cfg.attn_windows[i]
                mask = mask + jnp.where(col > row_pos[:, None, :, None] - w,
                                        0.0, neg)
            x, (ck, cv) = UnifiedBlock(cfg, layer_idx=i,
                                       attn_kernel=self.attn_kernel,
                                       name=f"layer_{i}")(
                x, mask, positions,
                paged_cache=(kv_pools[0][i], kv_pools[1][i]),
                block_tables=block_tables, write_pos=write_pos,
                valid_len=valid_len)
            new_k.append(ck)
            new_v.append(cv)
        new_pools = (jnp.stack(new_k), jnp.stack(new_v))

        if cfg.final_norm:
            x = _norm(cfg, "ln_f")(x)
        if cfg.tie_embeddings:
            logits = wte.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                              dtype=cfg.dtype, param_dtype=jnp.float32,
                              name="lm_head")(x)
        return logits.astype(jnp.float32), new_pools


def init_kv_caches(cfg: TransformerConfig, batch_size: int, max_seq_len: int,
                   dtype=None):
    """Preallocated KV workspace for :class:`TransformerDecoderModel` (the
    reference sizes one arena from max_out_tokens,
    inference_context.h:129-141)."""
    n_kv = cfg.num_kv_heads or cfg.num_heads
    head_dim = cfg.hidden_size // cfg.num_heads
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, batch_size, max_seq_len, n_kv, head_dim)
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def init_paged_kv_pools(cfg: TransformerConfig, num_blocks: int,
                        block_size: int, dtype=None):
    """Shared K/V block pools for :class:`PagedTransformerDecoderModel`."""
    from deepspeed_tpu.ops.paged_attention import init_paged_pool

    n_kv = cfg.num_kv_heads or cfg.num_heads
    head_dim = cfg.hidden_size // cfg.num_heads
    return init_paged_pool(cfg.num_layers, num_blocks, block_size, n_kv,
                           head_dim, dtype or cfg.dtype)
