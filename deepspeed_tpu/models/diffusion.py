"""Diffusion-model (stable-diffusion) inference modules — the TPU pillar for
the reference's diffusers path:

- ``DeepSpeedDiffusersAttention`` (ops/transformer/inference/diffusers_attention.py:98)
- ``DeepSpeedDiffusersTransformerBlock`` (…/diffusers_transformer_block.py:36)
- ``Diffusers2DTransformerConfig`` (…/diffusers_2d_transformer.py)
- ``DSUNet`` / ``DSVAE`` wrappers (model_implementations/diffusers/{unet,vae}.py)
- injected via ``generic_injection`` (module_inject/replace_module.py:187)

The reference swaps every diffusers ``BasicTransformerBlock`` /
``CrossAttention`` for fused-CUDA equivalents and wraps UNet/VAE forwards in
CUDA graphs. The TPU design: one flax ``DiffusersTransformerBlock`` covering
self-attn → cross-attn → GEGLU feed-forward (the BasicTransformerBlock
topology), with weights converted straight from a diffusers ``state_dict``
(pure tensor-name mapping — no diffusers import), attention running through
the Pallas flash kernel when profitable, and jit compilation standing in for
CUDA-graph capture (``wrap_diffusion_model``). Conv stacks stay in the
user's flax UNet — XLA already fuses the reference's ``csrc/spatial`` bias
ops (see deepspeed_tpu/ops/spatial.py).
"""

import dataclasses
from typing import Any, Callable, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

Dtype = Any


@dataclasses.dataclass(frozen=True)
class Diffusers2DTransformerConfig:
    """Reference ops/transformer/inference/diffusers_2d_transformer.py —
    carries the int8 flag; the TPU port also records the block geometry
    (inferred from the state_dict by :func:`convert_diffusers_block`)."""

    hidden_size: int = 320
    num_heads: int = 8
    context_dim: Optional[int] = 768      # None → self-attention only
    int8_quantization: bool = False
    dtype: Dtype = jnp.bfloat16
    norm_eps: float = 1e-5


def _attend(q, k, v, scale):
    """[B, S, H, D] bidirectional attention. Uses the Pallas flash kernel for
    long self-attention sequences; plain einsum otherwise (cross-attention
    context is ~77 tokens for SD — flash buys nothing there)."""
    if q.shape[1] == k.shape[1] and q.shape[1] >= 512 and q.shape[-1] >= 64:
        from deepspeed_tpu.ops.flash_attention import flash_attention
        try:
            return flash_attention(q, k, v, causal=False, sm_scale=scale)
        except Exception:  # unsupported geometry → dense fallback
            pass
    w = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    w = jax.nn.softmax(w.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


class DiffusersAttention(nn.Module):
    """Self- or cross-attention as in diffusers ``CrossAttention`` /
    reference ``DeepSpeedDiffusersAttention`` (diffusers_attention.py:98):
    no causal mask, no attention bias on q/k/v, bias on the out projection.
    Self-attention uses one fused qkv matmul (the reference's ``attn_qkvw``
    packing, diffusers_attention.py:140-160); cross-attention keeps separate
    q and kv projections because context dim ≠ hidden dim."""

    hidden_size: int
    num_heads: int
    context_dim: Optional[int] = None     # None → self-attention
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, context=None):
        b, s, d = x.shape
        h = self.num_heads
        hd = d // h
        scale = 1.0 / float(np.sqrt(hd))
        if self.context_dim is None:
            qkv = nn.Dense(3 * d, use_bias=False, dtype=self.dtype,
                           name="qkv")(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            ctx_len = s
        else:
            ctx = x if context is None else context
            q = nn.Dense(d, use_bias=False, dtype=self.dtype, name="q")(x)
            kv = nn.Dense(2 * d, use_bias=False, dtype=self.dtype,
                          name="kv")(ctx)
            k, v = jnp.split(kv, 2, axis=-1)
            ctx_len = ctx.shape[1]
        q = q.reshape(b, s, h, hd)
        k = k.reshape(b, ctx_len, h, hd)
        v = v.reshape(b, ctx_len, h, hd)
        o = _attend(q, k, v, scale).reshape(b, s, d)
        return nn.Dense(d, use_bias=True, dtype=self.dtype, name="out")(o)


class GEGLU(nn.Module):
    """diffusers ``GEGLU`` feed-forward gate — the reference computes it as a
    fused gated-activation epilogue (``ActivationFuncType.GATED_GELU``,
    diffusers_transformer_block.py:100-120)."""

    inner_dim: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        hg = nn.Dense(2 * self.inner_dim, dtype=self.dtype, name="proj")(x)
        hidden, gate = jnp.split(hg, 2, axis=-1)
        return hidden * jax.nn.gelu(gate, approximate=False)


class DiffusersTransformerBlock(nn.Module):
    """diffusers ``BasicTransformerBlock`` topology, as fused by the
    reference's ``DeepSpeedDiffusersTransformerBlock``
    (diffusers_transformer_block.py:36-130):

        x = x + self_attn(LN1(x))
        x = x + cross_attn(LN2(x), context)
        x = x + ff2(geglu(ff1(LN3(x))))
    """

    cfg: Diffusers2DTransformerConfig

    @nn.compact
    def __call__(self, x, context=None):
        c = self.cfg
        ln = lambda name: nn.LayerNorm(epsilon=c.norm_eps, dtype=c.dtype,
                                       name=name)
        x = x + DiffusersAttention(c.hidden_size, c.num_heads, None,
                                   dtype=c.dtype, name="attn1")(ln("norm1")(x))
        x = x + DiffusersAttention(c.hidden_size, c.num_heads, c.context_dim,
                                   dtype=c.dtype,
                                   name="attn2")(ln("norm2")(x), context)
        h = GEGLU(4 * c.hidden_size, dtype=c.dtype,
                  name="ff1")(ln("norm3")(x))
        x = x + nn.Dense(c.hidden_size, dtype=c.dtype, name="ff2")(h)
        return x


class SpatialTransformer2D(nn.Module):
    """diffusers ``Transformer2DModel`` body over NHWC feature maps:
    groupnorm → 1×1 proj_in → N transformer blocks over the flattened
    [B, H·W, C] sequence → 1×1 proj_out → residual. The attention interior
    is what the reference injects; the NHWC plumbing matches the layout its
    spatial kernels assume (csrc/spatial)."""

    cfg: Diffusers2DTransformerConfig
    depth: int = 1
    groups: int = 32

    @nn.compact
    def __call__(self, x, context=None):      # x: [B, H, W, C]
        c = self.cfg
        b, hh, ww, ch = x.shape
        res = x
        h = nn.GroupNorm(num_groups=min(self.groups, ch), epsilon=1e-6,
                         dtype=c.dtype, name="norm")(x)
        h = nn.Dense(c.hidden_size, dtype=c.dtype, name="proj_in")(h)
        h = h.reshape(b, hh * ww, c.hidden_size)
        for i in range(self.depth):
            h = DiffusersTransformerBlock(c, name=f"block_{i}")(h, context)
        h = nn.Dense(ch, dtype=c.dtype, name="proj_out")(h)
        return h.reshape(b, hh, ww, ch) + res


# --------------------------------------------------------------------------
# diffusers state_dict → flax params (name-based; no diffusers dependency)
# --------------------------------------------------------------------------

def _t(sd, key):
    v = sd[key]
    a = v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v)
    return a


def _dense(sd, key, bias=True):
    p = {"kernel": _t(sd, f"{key}.weight").T}
    if bias and f"{key}.bias" in sd:
        p["bias"] = _t(sd, f"{key}.bias")
    return p


def _ln(sd, key):
    return {"scale": _t(sd, f"{key}.weight"), "bias": _t(sd, f"{key}.bias")}


def convert_diffusers_block(sd: Dict[str, Any], prefix: str = ""
                            ) -> Dict[str, Any]:
    """Map one diffusers ``BasicTransformerBlock`` state_dict subtree
    (``attn1.to_q/to_k/to_v/to_out.0``, ``attn2.*``, ``ff.net.0.proj``,
    ``ff.net.2``, ``norm1/2/3``) onto :class:`DiffusersTransformerBlock`
    params — the weight collection the reference's container performs in
    diffusers_transformer_block.py:44-88, including the qkv fuse for attn1."""
    p = prefix
    qkv = np.concatenate([_t(sd, f"{p}attn1.to_q.weight").T,
                          _t(sd, f"{p}attn1.to_k.weight").T,
                          _t(sd, f"{p}attn1.to_v.weight").T], axis=1)
    kv = np.concatenate([_t(sd, f"{p}attn2.to_k.weight").T,
                         _t(sd, f"{p}attn2.to_v.weight").T], axis=1)
    return {
        "norm1": _ln(sd, f"{p}norm1"),
        "norm2": _ln(sd, f"{p}norm2"),
        "norm3": _ln(sd, f"{p}norm3"),
        "attn1": {"qkv": {"kernel": qkv},
                  "out": _dense(sd, f"{p}attn1.to_out.0")},
        "attn2": {"q": _dense(sd, f"{p}attn2.to_q", bias=False),
                  "kv": {"kernel": kv},
                  "out": _dense(sd, f"{p}attn2.to_out.0")},
        "ff1": {"proj": _dense(sd, f"{p}ff.net.0.proj")},
        "ff2": _dense(sd, f"{p}ff.net.2"),
    }


def block_config_from_state_dict(sd: Dict[str, Any], prefix: str = "",
                                 num_heads: Optional[int] = None,
                                 head_dim: int = 64,
                                 dtype: Dtype = jnp.bfloat16
                                 ) -> Diffusers2DTransformerConfig:
    """Infer hidden/context dims from a BasicTransformerBlock subtree.

    Head count is NOT recoverable from the weights; diffusers UNets vary it
    per block (SD2/SDXL fix head_dim=64, so a 320-dim block has 5 heads and
    a 1280-dim one has 20). When ``num_heads`` is None it is derived as
    ``hidden // head_dim``; pass an explicit ``num_heads`` only for models
    whose head count really is uniform."""
    hidden = _t(sd, f"{prefix}attn1.to_q.weight").shape[0]
    ctx = _t(sd, f"{prefix}attn2.to_k.weight").shape[1]
    if num_heads is None:
        num_heads = max(1, hidden // head_dim)
        if hidden % num_heads:
            raise ValueError(
                f"hidden {hidden} not divisible by inferred num_heads "
                f"{num_heads} (head_dim={head_dim}); pass num_heads=")
    return Diffusers2DTransformerConfig(hidden_size=hidden,
                                        num_heads=num_heads,
                                        context_dim=ctx, dtype=dtype)


# --------------------------------------------------------------------------
# UNet / VAE wrappers (model_implementations/diffusers/{unet,vae}.py)
# --------------------------------------------------------------------------

class DiffusionModelWrapper:
    """TPU stand-in for ``DSUNet``/``DSVAE``: the reference wraps the
    diffusers module to capture/replay a CUDA graph per input signature
    (unet.py:28-60); under XLA the jit cache *is* the graph cache, so the
    wrapper jits the apply fn (weights donated out of the hot path are
    unnecessary — params are captured constants), casts activations to the
    configured dtype, and exposes the same call surface."""

    def __init__(self, apply_fn: Callable, params: Dict[str, Any],
                 dtype: Dtype = jnp.bfloat16):
        self.dtype = dtype
        # cast + transfer ONCE; jit arguments that are already committed
        # device arrays are not re-uploaded per call
        self.params = jax.device_put(jax.tree.map(
            lambda a: jnp.asarray(a, dtype=dtype)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a, params))
        self._fn = jax.jit(lambda p, *a, **kw: apply_fn(p, *a, **kw))

    def __call__(self, *args, **kwargs):
        def cast(a):
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.inexact):
                return jnp.asarray(a, dtype=self.dtype)
            return a

        args = tuple(cast(a) for a in args)
        kwargs = {k: cast(v) for k, v in kwargs.items()}
        return self._fn(self.params, *args, **kwargs)


DSUNet = DiffusionModelWrapper   # name parity, model_implementations/diffusers/unet.py:13
DSVAE = DiffusionModelWrapper    # name parity, model_implementations/diffusers/vae.py:13
