from deepspeed_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    EXPERT_AXIS,
    MESH_AXES,
    PIPE_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    data_parallel_size,
    make_mesh,
    mesh_axis_size,
    resolve_mesh_dims,
    single_device_mesh,
)
from deepspeed_tpu.parallel.partition import (  # noqa: F401
    DEFAULT_TP_RULES,
    batch_spec,
    infer_param_spec,
    replicated,
    tree_param_specs,
    tree_shardings,
)
from deepspeed_tpu.parallel.topology import (  # noqa: F401
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    ProcessTopology,
)
