"""Named-axis cartesian rank grid.

TPU-native analogue of reference ``deepspeed/runtime/pipe/topology.py``
(``ProcessTopology`` :12, ``PipeDataParallelTopology`` :232,
``PipeModelDataParallelTopology`` :244). On TPU the grid *is* the
``jax.sharding.Mesh``; this class provides the same rank-mapping queries the
reference exposes (rank <-> coordinate, filtered rank lists per axis) for the
launcher, checkpoint naming, and tests, without owning any process groups —
groups are mesh axes.
"""

from collections import namedtuple
from itertools import product
from typing import Dict, List


class ProcessTopology:
    """Maps n-dimensional axis coordinates to linear ranks, axes-major order.

    The first axis in ``axes`` has the largest stride (outermost), matching
    the reference's convention (pipe/topology.py:24-36).
    """

    def __init__(self, axes: List[str], dims: List[int]):
        assert len(axes) == len(dims), "axes and dims must align"
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        for global_rank, coord in enumerate(product(*[range(d) for d in self.dims])):
            key = dict(zip(self.axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, got {coord_kwargs}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_rank_repr(self, rank: int, omit_axes: List[str] = None, inner_sep: str = "_",
                      outer_sep: str = "-") -> str:
        """e.g. 'pipe_0-data_1' — used in checkpoint file naming."""
        omit_axes = omit_axes if omit_axes is not None else ["data"]
        coord = self.get_coord(rank)
        return outer_sep.join(
            f"{ax}{inner_sep}{getattr(coord, ax)}"
            for ax in self.axes if ax not in omit_axes
        )

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_coord(self, rank: int):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Lists of ranks that would form a communicator along ``axis``."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for other_coord in product(*[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, other_coord))
            ranks = [self.get_rank(**{axis: i, **fixed}) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """All ranks whose coordinates match the given axis=value filters."""
        def matches(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return sorted(rank for coord, rank in self.mapping.items() if matches(coord))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    def world_size(self) -> int:
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """pipe × data grid (reference pipe/topology.py:232)."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """pipe × data × model grid for 3D parallelism (reference :244)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])
