"""Sharding-rules engine: parameter path patterns -> PartitionSpec.

This replaces three reference mechanisms at once:
- Megatron-style ``mpu`` tensor-parallel layouts consumed by training
  (deepspeed/utils/groups.py:59),
- the auto-TP parser that walks a model finding linears to shard
  (deepspeed/module_inject/auto_tp.py:84),
- ZeRO parameter partitioning (runtime/zero/partition_parameters.py:603).

Here all three are one thing: every parameter gets a ``PartitionSpec`` built
from (a) regex rules mapping parameter paths to tensor-parallel dims, and
(b) the ZeRO stage, which additionally shards a remaining dim along the
``data`` axis (stage 3 shards parameters; stages 1-2 shard only optimizer
state and gradients). XLA's SPMD partitioner then inserts the all-gathers /
reduce-scatters the reference issues by hand.
"""

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import DATA_AXIS, TENSOR_AXIS, mesh_axis_size

# A rule: (path_regex, spec) where spec is a tuple of axis names / None per dim.
Rule = Tuple[str, Tuple[Optional[str], ...]]

# Default tensor-parallel rules for the transformer models in
# deepspeed_tpu/models: column-parallel QKV/up-proj, row-parallel out/down-proj,
# vocab-sharded embedding. Matches what kernel-injection TP does per weight
# class in the reference (module_inject/containers/base.py:215-242).
DEFAULT_TP_RULES: List[Rule] = [
    # expert-parallel: leading expert dim of batched expert stacks shards over
    # the dedicated expert axis when the mesh has one, else over data
    # (EP groups ⊂ DP group, reference utils/groups.py:108). "a|b" in a rule
    # = first listed axis alive on this mesh wins.
    (r".*experts/(gate_proj|up_proj|down_proj|kernel).*",
     ("expert|data", None, None)),
    (r".*(wte|embed_tokens|word_embeddings|embedding)\b.*", (TENSOR_AXIS, None)),
    (r".*(q_proj|k_proj|v_proj|qkv|query_key_value|c_attn).*kernel", (None, TENSOR_AXIS)),
    (r".*(o_proj|out_proj|dense(?!_h)|c_proj(?=.*attn)|attn_out).*kernel", (TENSOR_AXIS, None)),
    (r".*(gate_proj|up_proj|fc_in|c_fc|dense_h_to_4h|w1|w3).*kernel", (None, TENSOR_AXIS)),
    (r".*(down_proj|fc_out|dense_4h_to_h|w2|mlp.*c_proj).*kernel", (TENSOR_AXIS, None)),
    (r".*(lm_head|output_proj|final_proj).*kernel", (None, TENSOR_AXIS)),
]


def path_str(path: Tuple) -> str:
    """Flatten a jax tree path into 'a/b/c' form."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _match_tp_rule(path: str, shape: Sequence[int], rules: List[Rule],
                   mesh: Mesh) -> List[Optional[str]]:
    spec: List[Optional[str]] = [None] * len(shape)
    for pattern, rule_spec in rules:
        if re.fullmatch(pattern, path) or re.match(pattern + r"$", path):
            # align rule to trailing dims (rules written for 2D kernels apply
            # to the last dims of higher-rank params)
            offset = len(shape) - len(rule_spec)
            if offset < 0:
                continue
            ok = True
            applied = [None] * len(rule_spec)
            for i, axis in enumerate(rule_spec):
                if axis is None:
                    continue
                # "a|b": first candidate axis alive on this mesh
                candidates = axis.split("|") if isinstance(axis, str) else [axis]
                axis = next((a for a in candidates
                             if mesh_axis_size(mesh, a) > 1), None)
                if axis is None:
                    continue  # all collapsed on this mesh; leave unsharded
                if shape[offset + i] % mesh_axis_size(mesh, axis) != 0:
                    ok = False
                    break
                applied[i] = axis
            if not ok:
                continue
            for i, axis in enumerate(applied):
                if axis is not None:
                    spec[offset + i] = axis
            break
    return spec


def _maybe_shard_data_axis(spec: List[Optional[str]], shape: Sequence[int],
                           mesh: Mesh, min_size: int = 2,
                           axis: str = DATA_AXIS) -> List[Optional[str]]:
    """ZeRO-3: shard the largest free dim along the data axis when divisible.

    Equivalent of partition_parameters.py's flat-partition over the DP group —
    except the partition stays tied to the logical dim so resharding on load
    is metadata-only. With MiCS, ``axis="mics"`` shards within the sub-group
    only (reference zero/mics.py bounded sharding).
    """
    dp = mesh_axis_size(mesh, axis)
    # expert stacks already shard over expert/data — exempt them from the
    # ZeRO axis whether that axis is "data" or the MiCS sub-axis
    if dp <= 1 or axis in spec or DATA_AXIS in spec or "expert" in spec:
        return spec
    # pick the largest dim not already sharded whose size divides by dp
    candidates = [
        (shape[i], i) for i in range(len(shape))
        if spec[i] is None and shape[i] % dp == 0 and shape[i] >= min_size
    ]
    if not candidates:
        return spec
    _, dim = max(candidates)
    spec = list(spec)
    spec[dim] = axis
    return spec


def infer_param_spec(path: str, shape: Sequence[int], mesh: Mesh,
                     rules: Optional[List[Rule]] = None,
                     shard_data_axis: bool = False,
                     zero_axis: str = DATA_AXIS) -> PartitionSpec:
    """PartitionSpec for one parameter.

    ``shard_data_axis=True`` adds ZeRO-3-style sharding over ``zero_axis``
    (the data axis; "mics" for MiCS sub-group sharding).
    """
    rules = DEFAULT_TP_RULES if rules is None else rules
    spec = _match_tp_rule(path, shape, rules, mesh)
    if shard_data_axis:
        spec = _maybe_shard_data_axis(spec, shape, mesh, axis=zero_axis)
    return PartitionSpec(*spec)


def tree_param_specs(params: Any, mesh: Mesh, rules: Optional[List[Rule]] = None,
                     shard_data_axis: bool = False) -> Any:
    """Pytree of PartitionSpecs matching ``params``."""
    def spec_for(path, leaf):
        if not hasattr(leaf, "shape") or leaf.ndim == 0:
            return PartitionSpec()
        return infer_param_spec(path_str(path), leaf.shape, mesh, rules, shard_data_axis)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def tree_shardings(params: Any, mesh: Mesh, rules: Optional[List[Rule]] = None,
                   shard_data_axis: bool = False) -> Any:
    specs = tree_param_specs(params, mesh, rules, shard_data_axis)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                  is_leaf=lambda x: isinstance(x, PartitionSpec))


def data_axes(mesh: Mesh):
    """The batch-sharding axes: expert/MiCS sub-axes are carved out of
    data, and their sub-groups are still data-parallel over the batch."""
    axes = [DATA_AXIS]
    if mesh_axis_size(mesh, "expert") > 1:
        axes.append("expert")
    if mesh_axis_size(mesh, "mics") > 1:
        axes.append("mics")
    return tuple(axes) if len(axes) > 1 else DATA_AXIS


def batch_spec(mesh: Mesh, sequence_sharded: bool = False) -> PartitionSpec:
    """Inputs: batch dim over data axis; optionally seq dim over sequence axis."""
    if sequence_sharded and mesh_axis_size(mesh, "sequence") > 1:
        return PartitionSpec(data_axes(mesh), "sequence")
    return PartitionSpec(data_axes(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
