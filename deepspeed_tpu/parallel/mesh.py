"""Device-mesh construction.

The reference builds NCCL process groups lazily per parallel mode
(``deepspeed/utils/groups.py``, ``runtime/pipe/topology.py``); on TPU all of
those become axes of one ``jax.sharding.Mesh``. Axis layout convention:

    ("pipe", "data", "expert", "sequence", "tensor")

outermost → innermost device order, so that tensor/sequence collectives (the
chattiest) ride the innermost ICI links, and pipe (point-to-point only)
crosses DCN when multi-slice. The "fsdp"/ZeRO axis is the same devices as
"data": ZeRO shards over the data-parallel group exactly as the reference
does (stage_1_and_2.py partitions over the DP group).

The expert axis is folded out of the data axis at MoE layers via axis
reshaping inside shard_map, matching the reference's expert-parallel groups
being subsets of the DP group (utils/groups.py:108).
"""

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from deepspeed_tpu.utils.logging import logger

# canonical axis names, outermost first
MESH_AXES = ("pipe", "data", "expert", "sequence", "tensor")

DATA_AXIS = "data"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"
SEQUENCE_AXIS = "sequence"
TENSOR_AXIS = "tensor"


def resolve_mesh_dims(mesh_config, n_devices: int) -> Dict[str, int]:
    """Resolve -1 ('all remaining devices') and validate the product.

    ``expert`` is NOT a device-consuming axis: expert groups are sub-groups
    of the data axis (reference utils/groups.py:108), so it is excluded from
    the device product and only validated for divisibility.
    """
    dims = {
        "pipe": mesh_config.pipe,
        "data": mesh_config.data,
        "expert": mesh_config.expert,
        "sequence": mesh_config.sequence,
        "tensor": mesh_config.tensor,
    }
    device_axes = ("pipe", "data", "sequence", "tensor")
    wildcard = [k for k in device_axes if dims[k] == -1]
    fixed = int(np.prod([dims[k] for k in device_axes if dims[k] != -1]))
    if len(wildcard) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {wildcard}")
    if wildcard:
        if n_devices % fixed != 0:
            raise ValueError(
                f"Device count {n_devices} not divisible by fixed mesh axes product {fixed}"
            )
        dims[wildcard[0]] = n_devices // fixed
    total = int(np.prod([dims[k] for k in device_axes]))
    if total != n_devices:
        raise ValueError(
            f"Mesh {dims} requires {total} devices but {n_devices} are available"
        )
    for k, v in dims.items():
        if v < 1:
            raise ValueError(f"Mesh axis {k} must be >= 1, got {v}")
    # expert axis must divide the ZeRO/data axis: expert groups are carved out
    # of the DP group (reference utils/groups.py:108)
    if dims["expert"] > 1 and dims["data"] % dims["expert"] != 0:
        raise ValueError(
            f"expert axis ({dims['expert']}) must divide data axis ({dims['data']})"
        )
    return dims


def make_mesh(mesh_config=None, devices: Optional[Sequence] = None,
              dims: Optional[Dict[str, int]] = None,
              mics_shard_size: int = 0) -> Mesh:
    """Build the global Mesh with axes
    (pipe, data, expert, mics, sequence, tensor).

    ``expert`` and ``mics`` are both carved OUT OF the data-parallel group
    (they don't consume extra devices): expert-parallel groups are
    sub-groups of DP exactly as in the reference (utils/groups.py:108 —
    ranks [i*ep, (i+1)*ep)), and ``mics`` is the MiCS bounded-sharding
    sub-group (reference runtime/zero/mics.py; the hierarchical inter-node
    allgather falls out of XLA reducing over ``data`` while gathering over
    ``mics``). Both default to 1."""
    if devices is None:
        devices = jax.devices()
    if dims is None:
        assert mesh_config is not None
        dims = resolve_mesh_dims(mesh_config, len(devices))
    dims = dict(dims)
    expert = dims.get("expert", 1) or 1
    if expert > 1:
        if dims["data"] % expert != 0:
            raise ValueError(
                f"expert axis ({expert}) must divide the data axis "
                f"({dims['data']})")
        dims["data"] = dims["data"] // expert
    mics = dims.get("mics", 1)
    if mics_shard_size and mics_shard_size > 0:
        if dims["data"] % mics_shard_size != 0:
            raise ValueError(
                f"mics_shard_size {mics_shard_size} must divide the data "
                f"axis ({dims['data']})")
        mics = mics_shard_size
        dims["data"] = dims["data"] // mics_shard_size
    axis_names = ("pipe", "data", "expert", "mics", "sequence", "tensor")
    shape = (dims["pipe"], dims["data"], expert, mics, dims["sequence"],
             dims["tensor"])
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != device count {len(devices)}")
    dev_array = np.asarray(devices).reshape(shape)
    logger.info(f"Created device mesh pipe={shape[0]} data={shape[1]} "
                f"expert={shape[2]} mics={shape[3]} sequence={shape[4]} "
                f"tensor={shape[5]}")
    return Mesh(dev_array, axis_names)


def single_device_mesh() -> Mesh:
    """Trivial mesh over one device (single-chip debugging)."""
    dev = np.asarray(jax.devices()[:1]).reshape(1, 1, 1, 1, 1, 1)
    return Mesh(dev, ("pipe", "data", "expert", "mics", "sequence", "tensor"))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.axis_names else 1


def data_parallel_size(mesh: Mesh) -> int:
    return mesh_axis_size(mesh, DATA_AXIS)
