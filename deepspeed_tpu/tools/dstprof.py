"""``bin/dst prof`` — one-shot dstprof resource-observability report.

Spins up a tiny self-contained serving engine (or adopts none and
reports process-level state with ``--no-serve``), drives a short
request burst through the REAL compiled serving path, and prints what
the observability layer saw:

- **compile caches**: per-program hit/miss/compile counts, compile
  seconds, and cost analysis (FLOPs / bytes accessed) for every
  compiled-program cache the run touched;
- **memory**: per-device bytes (allocator stats or the live-buffer
  walk), KV pool bytes allocated/cached/peak, host-tier watermarks;
- **efficiency**: FLOPs-per-token, roofline intensity, achieved model
  FLOP/s and MFU against the platform peak table.

Text by default, ``--json`` for machines. This is a smoke/diagnostic
tool (is the telemetry wired on THIS box, what does a compile cost
here) — production numbers come from ``engine.serve_metrics()`` /
the ``serve.metrics_port`` scrape endpoint on a real engine.
"""

import argparse
import json
import sys


def _fmt_bytes(n) -> str:
    n = float(n)
    for scale, unit in ((1 << 30, "GiB"), (1 << 20, "MiB"),
                        (1 << 10, "KiB")):
        if abs(n) >= scale:
            return f"{n / scale:.2f} {unit}"
    return f"{int(n)} B"


def _fmt_num(n) -> str:
    n = float(n)
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= scale:
            return f"{n / scale:.2f}{suffix}"
    return f"{n:.4g}"


def build_report(requests: int = 6, host_cache_gb: float = 0.0) -> dict:
    """Run the tiny-engine exercise and collect the report dict."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.inference.scheduler import Request
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engine = deepspeed_tpu.init_inference(
        model=model, config={"dtype": "float32"}, params=params,
        model_config=cfg)
    rng = np.random.default_rng(0)
    reqs = [Request(rid=i, prompt=rng.integers(1, 256, 5 + (i % 3) * 4),
                    max_new_tokens=4 + (i % 3) * 3)
            for i in range(int(requests))]
    comps = engine.serve(reqs, num_slots=2, block_size=4,
                         host_cache_gb=host_cache_gb or None)
    snap = engine.serve_metrics()
    # a short speculative session on repetitive prompts so the
    # serve.spec acceptance counters carry real numbers in the report
    spec_reqs = [Request(rid=100 + i,
                         prompt=np.tile(rng.integers(1, 256, 3 + i % 3), 4),
                         max_new_tokens=12)
                 for i in range(4)]
    engine.serve(spec_reqs, num_slots=2, block_size=4,
                 speculative="prompt_lookup", draft_len=4, draft_ngram=2)
    spec_snap = engine.serve_metrics().get("serve.spec", {})
    return {
        "backend": jax.default_backend(),
        "requests": len(comps),
        "statuses": sorted(c.status for c in comps),
        "compile": snap.get("compile", {}),
        "compile_counters": {k: v for k, v in snap["counters"].items()
                             if k.startswith("compile.")},
        "memory": snap.get("memory", {}),
        "serve_memory": snap.get("serve.memory", {}),
        "static_memory": _static_memory(cfg, reqs, params,
                                        snap.get("serve.memory", {})),
        "mem_budgets": _mem_budget_table(),
        "efficiency": snap.get("serve.efficiency", {}),
        "speculative": spec_snap,
    }


def _static_memory(cfg, reqs, params, serve_mem) -> dict:
    """dstmem static prediction vs the measured ``serve.memory`` gauges
    for THIS engine's serving shape — the budget-headroom columns."""
    import jax.numpy as jnp

    from deepspeed_tpu.tools.dstlint import mempass

    pred = mempass.predict_serve_memory(
        cfg, num_slots=2, block_size=4,
        max_context=max(len(r.prompt) + r.max_new_tokens for r in reqs),
        dtype=jnp.float32, params=params)
    return {
        quantity: {
            "static": cmp["static"],
            "measured": cmp["measured"],
            "agreement_pct": round(cmp["agreement"] * 100, 2),
        }
        for quantity, cmp in mempass.compare_serve_memory(
            pred, serve_mem).items()
    }


def _mem_budget_table() -> dict:
    """The checked-in static peak-bytes table (mem_budgets.json) —
    rendered so operators see each budgeted program's footprint next to
    the live gauges."""
    import os

    import deepspeed_tpu
    from deepspeed_tpu.tools.dstlint import mempass

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(deepspeed_tpu.__file__)))
    path = os.path.join(root, "tools", "dstlint", "mem_budgets.json")
    return mempass.static_peak_table(mempass.load_budgets(path))


def build_train_report(steps: int = 3) -> dict:
    """``--train``: run a tiny REAL train engine for a few steps and
    collect what the dsttrain layer saw — step/phase timing, gradient
    health, compile cost, MFU, and the flops-profiler registry section
    (docs/OBSERVABILITY.md "Training")."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    model = LlamaModel(LlamaConfig.tiny(dtype=jnp.float32))
    rng = np.random.default_rng(0)

    def batch(n):
        t = rng.integers(0, 256, size=(n, 17))
        return {"input_ids": t[:, :-1], "labels": t[:, 1:]}

    engine = deepspeed_tpu.initialize(
        model=model, sample_batch=batch(2),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "flops_profiler": {"enabled": True, "profile_step": 1,
                                   "top_modules": 3, "module_depth": 2},
                "steps_per_print": 10_000})
    for _ in range(max(int(steps), 1)):
        loss = engine.train_batch(batch(engine.train_batch_size()))
    loss = float(loss)
    snap = engine.train_metrics()
    hists = snap["histograms"]
    return {
        "backend": jax.default_backend(),
        "steps": int(steps),
        "loss": loss,
        "step_s": hists.get("train.step_s", {}),
        "phases": {k.split(".")[-1].removesuffix("_s"): v
                   for k, v in hists.items()
                   if k.startswith("train.phase.")},
        "health": {
            "grad_norm": hists.get("train.grad_norm", {}),
            "grad_norm_by_group": {
                k.split(".", 2)[2]: v for k, v in snap["gauges"].items()
                if k.startswith("train.grad_norm.")},
            "nonfinite_grads": snap["gauges"].get(
                "train.nonfinite_grads", 0.0),
            "overflow_steps": snap["counters"].get(
                "train.overflow_steps", 0),
        },
        "compile": snap.get("compile", {}),
        "efficiency": snap.get("train.efficiency", {}),
        "profiling": snap.get("profiling", {}),
        "zero_reduction": {k: v for k, v in snap["counters"].items()
                           if k.startswith("train.zero.")},
        "memory": snap.get("memory", {}),
    }


def render_train_text(report: dict) -> str:
    lines = ["========================= dstprof train report "
             "======================="]
    lines.append(f"backend: {report['backend']}   steps: "
                 f"{report['steps']}   final loss: {report['loss']:.4f}")
    lines.append("")
    lines.append("-- step & phases "
                 "----------------------------------------------------")
    rows = [("step", report.get("step_s", {}))]
    rows += sorted(report.get("phases", {}).items())
    lines.append(f"{'phase':<12}{'count':>7}{'mean_s':>10}{'p50_s':>10}"
                 f"{'p95_s':>10}")
    for name, h in rows:
        if not h or not h.get("count"):
            continue
        lines.append(f"{name:<12}{h['count']:>7}{h['mean']:>10.4f}"
                     f"{h['p50']:>10.4f}{h['p95']:>10.4f}")
    lines.append("")
    lines.append("-- gradient health "
                 "--------------------------------------------------")
    health = report.get("health", {})
    gn = health.get("grad_norm", {})
    if gn.get("count"):
        lines.append(f"grad_norm: mean {gn['mean']:.4f}  p50 "
                     f"{gn['p50']:.4f}  max {gn['max']:.4f}  "
                     f"({gn['count']} finite steps)")
    for grp, v in sorted(health.get("grad_norm_by_group", {}).items()):
        lines.append(f"  {grp:<32}{v:>12.4f}")
    lines.append(f"overflow_steps: {int(health.get('overflow_steps', 0))}"
                 f"   nonfinite_grads(last): "
                 f"{int(health.get('nonfinite_grads', 0))}")
    lines.append("")
    lines.append("-- compile & efficiency "
                 "---------------------------------------------")
    for cache in sorted(report.get("compile", {})):
        for key, e in sorted(report["compile"][cache].items()):
            lines.append(f"{cache + '/' + key:<34}"
                         f"compiles={e.get('compiles', 0)} "
                         f"last_s={e.get('last_s', 0.0):.3f} "
                         f"flops={_fmt_num(e.get('flops', 0))}")
    eff = report.get("efficiency", {})
    if eff:
        lines.append(f"mfu: {eff.get('mfu', 0.0):.4%}   "
                     f"model_flops/step: "
                     f"{_fmt_num(eff.get('model_flops_per_step', 0))}   "
                     f"peak: {eff.get('peak_source', '?')}/"
                     f"{eff.get('device_kind', '?')}")
    zr = report.get("zero_reduction", {})
    if zr:
        lines.append("zero reduction: " + "  ".join(
            f"{k.rsplit('.', 1)[1]}={_fmt_num(v)}"
            for k, v in sorted(zr.items())))
    prof = report.get("profiling", {})
    if prof:
        lines.append("")
        lines.append("-- flops profiler (registry section) "
                     "--------------------------------")
        for k in sorted(prof):
            lines.append(f"  {k:<44}{_fmt_num(prof[k]):>12}")
    lines.append("=" * 69)
    return "\n".join(lines)


def render_text(report: dict) -> str:
    lines = ["=========================== dstprof report "
             "==========================="]
    lines.append(f"backend: {report['backend']}   requests served: "
                 f"{report['requests']}")
    lines.append("")
    lines.append("-- compile caches "
                 "---------------------------------------------------")
    lines.append(f"{'program':<34}{'compiles':>9}{'last_s':>10}"
                 f"{'flops':>10}{'bytes':>10}")
    for cache in sorted(report.get("compile", {})):
        for key, e in sorted(report["compile"][cache].items()):
            lines.append(
                f"{cache + '/' + key:<34}{e.get('compiles', 0):>9}"
                f"{e.get('last_s', 0.0):>10.3f}"
                f"{_fmt_num(e.get('flops', 0)):>10}"
                f"{_fmt_num(e.get('bytes_accessed', 0)):>10}")
    hits = {k: v for k, v in report.get("compile_counters", {}).items()
            if k.endswith((".hits", ".misses", ".evictions"))}
    if hits:
        lines.append("counters: " + "  ".join(
            f"{k.split('.', 1)[1]}={int(v)}" for k, v in sorted(
                hits.items())))
    lines.append("")
    lines.append("-- memory "
                 "-----------------------------------------------------------")
    mem = report.get("memory", {})
    lines.append(f"devices: {mem.get('devices', '?')}  "
                 f"(source: {mem.get('source', '?')})")
    for k in sorted(mem):
        if k.endswith(("bytes_in_use", "peak_bytes_in_use", "bytes_limit")):
            lines.append(f"  {k:<34}{_fmt_bytes(mem[k]):>14}")
    sm = report.get("serve_memory", {})
    for k in sorted(sm):
        lines.append(f"  serve.{k:<28}{_fmt_bytes(sm[k]):>14}")
    static = report.get("static_memory", {})
    if static:
        lines.append("")
        lines.append("-- static vs measured (dstmem) "
                     "--------------------------------------")
        lines.append(f"{'quantity':<20}{'static':>14}{'measured':>14}"
                     f"{'agree':>9}")
        for q in sorted(static):
            e = static[q]
            lines.append(f"{q:<20}{_fmt_bytes(e['static']):>14}"
                         f"{_fmt_bytes(e['measured']):>14}"
                         f"{e['agreement_pct']:>8.1f}%")
    budgets = report.get("mem_budgets", {})
    if budgets:
        lines.append("")
        lines.append("-- static peak budgets (tools/dstlint/"
                     "mem_budgets.json) ------------")
        for name in sorted(budgets):
            lines.append(f"  {name:<36}"
                         f"{_fmt_bytes(budgets[name]):>14}")
    lines.append("")
    lines.append("-- efficiency "
                 "-------------------------------------------------------")
    eff = report.get("efficiency", {})
    for k in ("model_flops_per_token", "achieved_model_flops_per_sec",
              "peak_flops_per_device", "roofline_intensity_flops_per_byte",
              "mfu"):
        if k in eff:
            v = eff[k]
            lines.append(f"  {k:<38}"
                         f"{_fmt_num(v) if k != 'mfu' else f'{v:.4%}':>14}")
    lines.append(f"  {'peak source / device kind':<38}"
                 f"{eff.get('peak_source', '?')} / "
                 f"{eff.get('device_kind', '?')}")
    sp = report.get("speculative", {})
    if sp:
        lines.append("")
        lines.append("-- speculative decoding (prompt-lookup) "
                     "-----------------------------")
        lines.append(
            f"  drafted={int(sp.get('drafted_tokens', 0))}  "
            f"accepted={int(sp.get('accepted_tokens', 0))}  "
            f"rejected={int(sp.get('rejected_tokens', 0))}  "
            f"rounds={int(sp.get('rounds', 0))}  "
            f"plain_rows={int(sp.get('plain_rows', 0))}")
        lines.append(
            f"  acceptance_rate={sp.get('acceptance_rate', 0.0):.4f}  "
            f"mean_accepted_per_round="
            f"{sp.get('mean_accepted_per_round', 0.0):.4f}  "
            f"(draft_len={int(sp.get('draft_len', 0))}, "
            f"ngram={int(sp.get('draft_ngram', 0))})")
    lines.append("=" * 69)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dst prof",
        description="one-shot dstprof report (compile caches, memory, "
                    "FLOPs/efficiency) from a tiny real serving run")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON instead of the table")
    ap.add_argument("--requests", type=int, default=6,
                    help="requests to drive through the tiny engine")
    ap.add_argument("--host-cache-gb", type=float, default=0.0,
                    help="also exercise the host KV tier at this size")
    ap.add_argument("--train", action="store_true",
                    help="one-shot TRAINING-step report (dsttrain) from "
                         "a tiny real train run instead of the serving "
                         "report")
    ap.add_argument("--steps", type=int, default=3,
                    help="train steps to run with --train")
    args = ap.parse_args(argv)
    if args.train:
        report = build_train_report(steps=args.steps)
        print(json.dumps(report, indent=1, default=str) if args.json
              else render_train_text(report))
        return 0
    report = build_report(requests=args.requests,
                          host_cache_gb=args.host_cache_gb)
    if args.json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print(render_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
