"""dstlint SPMD pass — static sharding & collective-cost analysis.

The jaxpr pass (:mod:`.jaxprpass`) budgets *how much compute* the hot
programs trace to; this pass budgets *how much communication* the
sharded programs imply. It traces the repo's real multi-device entry
points under **abstract meshes** (``jax.sharding.AbstractMesh`` +
``ShapeDtypeStruct``s — no devices, runs on the CPU tier-1 host):

- the ZeRO stage 1/2/3 train steps (``runtime/zero/stages.py``
  ``build_zero_train_step`` — the same ``constrain_gradients`` boundary
  the engine's fused programs use),
- the pipeline 1F1B schedule (``runtime/pipe/interpreter.py``
  ``make_1f1b_lm_loss`` over a pipe×data×tensor mesh),
- MoE top-2 dispatch (``moe/sharded_moe.moe_dispatch_combine``),
- ring and Ulysses sequence-parallel attention (``ops/``),
- the paged serving executors (decode/prefill via
  :mod:`.jaxprpass`'s abstract serving pieces),

and derives a per-program **collective inventory**: every collective
equation (psum / all_gather / reduce_scatter / ppermute / all_to_all),
classified by mesh axes, dtype and per-device wire bytes per step — the
bytes arithmetic is the SAME shared table the runtime comms logger uses
(``comm/collective_cost.py``), so static and runtime accounting cannot
drift apart.

Two kinds of collectives are inventoried:

- **explicit** — collective equations inside ``shard_map`` bodies
  (pipeline ppermute, Ulysses all_to_all, TP psum, ...);
- **inferred** — collectives XLA's SPMD partitioner will synthesize for
  ``jit``-with-shardings programs: the pass runs a conservative GSPMD-
  style sharding propagation over the jaxpr (elementwise merge,
  dot_general contractions over sharded dims → psum, scatter-add of
  sharded updates into replicated operands → psum, sharding-constraint
  boundaries classified as all_gather / reduce_scatter / all_to_all /
  free reshard). Propagation is zero-false-positive-biased: anything it
  cannot prove becomes UNKNOWN and fires no rule.

The inventory is pinned in ``tools/dstlint/comms_budgets.json``
(regenerate with ``bin/dst lint --update-budgets``) and checked by six
rules:

- ``spmd-implicit-collective``   a collective key present in the trace
  but absent from the checked-in budget — the "XLA silently inserted an
  all-gather" class; regen the budget if the change is intentional.
- ``spmd-comms-budget``   bytes/count drift beyond ±25% of the budget, a
  budgeted collective disappearing, or an entry failing to trace.
- ``spmd-replication``   an entry output DECLARED sharded whose
  propagated sharding provably collapsed to fully-replicated with no
  ``with_sharding_constraint`` re-sharding it — the whole buffer
  materializes on every device before XLA re-slices it.
- ``spmd-collective-dtype``   a reduction boundary — or, when the entry
  declares a ``reduction_dtype``, an explicit decode-loop collective —
  moving a wider float than the configured communication dtype (the
  EQuARX guardrail: an fp32 decode/grad all-reduce where the config
  says bf16/int8). The quantized ring's fp32 *scale* hops are allow-
  listed by exact key (``collective_dtype_allow``), not exempted.
- ``spmd-wrong-axis``   a collective inside a ``shard_map`` body over a
  mesh axis none of the body's inputs vary over (psum over a replicated
  value multiplies it by the axis size — a silent numerics bug).
- ``spmd-decode-collective``   collectives inside a serving
  ``while_loop`` decode body beyond the entry's per-step allowance. The
  single-replica executors keep a zero allowance; the TP entries
  (``serve_decode_tp2/fp32``, ``serve_decode_tp2/int8``) carry the real
  per-step budget — 2 residual-boundary all-reduces per layer, as psums
  or as the quantized ring's ppermute hops.
"""

import dataclasses
import json
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.comm.collective_cost import (
    REDUCTION_KINDS, collective_kind, payload_bytes_from_shape, wire_bytes,
)
from deepspeed_tpu.tools.dstlint.core import Finding

SPMD_RULES = ("spmd-implicit-collective", "spmd-comms-budget",
              "spmd-replication", "spmd-collective-dtype",
              "spmd-wrong-axis", "spmd-decode-collective")

DEFAULT_TOLERANCE_PCT = 25

#: boundary kinds whose dtype the spmd-collective-dtype rule audits —
#: REDUCTION boundaries only (communication_data_type governs gradient
#: reduction comms; the optimizer's param all-gather epilogue re-gathers
#: fp32 master weights by design and is budgeted, not dtype-audited)
_BOUNDARY_DTYPE_KINDS = set(REDUCTION_KINDS) | {"shard", "reshard"}

#: explicit collective kinds audited inside a decode while_loop when the
#: entry declares a reduction_dtype — the TP serving hot path (psum, and
#: the quantized ring's ppermute hops)
_WHILE_DTYPE_KINDS = set(REDUCTION_KINDS) | {"ppermute"}

_FLOAT_BITS = {"bfloat16": 16, "float16": 16, "float32": 32,
               "float64": 64}


# ---------------------------------------------------------------------------
# sharding specs: per-dim tuples of mesh axis names; UNKNOWN is the
# conservative "cannot prove" element that absorbs everything.
# ---------------------------------------------------------------------------

class _UnknownSpec:
    def __repr__(self):
        return "UNKNOWN"


UNKNOWN = _UnknownSpec()


def _replicated(rank: int) -> Tuple:
    return ((),) * rank


def _spec_axes(spec) -> frozenset:
    if spec is UNKNOWN:
        return frozenset()
    return frozenset(a for dim in spec for a in dim)


def _is_replicated(spec) -> bool:
    return spec is not UNKNOWN and all(not dim for dim in spec)


def _pspec_to_spec(pspec, rank: int, unconstrained_dims=(),
                   old_spec=None):
    """PartitionSpec → internal spec, honoring unconstrained dims (keep
    the propagated sharding there when known)."""
    entries = list(pspec) if pspec is not None else []
    entries += [None] * (rank - len(entries))
    out = []
    for i, e in enumerate(entries[:rank]):
        if i in (unconstrained_dims or ()):
            if old_spec is not None and old_spec is not UNKNOWN:
                out.append(tuple(old_spec[i]))
            else:
                out.append(())
        elif e is None:
            out.append(())
        elif isinstance(e, str):
            out.append((e,))
        else:
            try:
                out.append(tuple(e))
            except TypeError:
                out.append(())
    return tuple(out)


def _names_to_spec(names: Dict[int, Tuple[str, ...]], rank: int) -> Tuple:
    """shard_map in_names/out_names dict (dim → axis tuple) → spec."""
    return tuple(tuple(names.get(i, ())) for i in range(rank))


def _merge_dim(a, b):
    if tuple(a) == tuple(b):
        return tuple(a)
    if not a:
        return tuple(b)
    if not b:
        return tuple(a)
    return None  # conflict


def _merge_specs(specs: Sequence) -> Any:
    """Elementwise-merge same-rank specs; conflicting dims → UNKNOWN."""
    specs = [s for s in specs if s is not None]
    if not specs:
        return UNKNOWN
    if any(s is UNKNOWN for s in specs):
        return UNKNOWN
    rank = len(specs[0])
    if any(len(s) != rank for s in specs):
        return UNKNOWN
    out = []
    for i in range(rank):
        dim = specs[0][i]
        for s in specs[1:]:
            dim = _merge_dim(dim, s[i])
            if dim is None:
                return UNKNOWN
        out.append(tuple(dim))
    return tuple(out)


def _join_fixpoint(a, b):
    """Loop-carry join: equal keeps, anything else degrades to UNKNOWN
    (per-dim) so the fixpoint terminates in one extra iteration."""
    if a is UNKNOWN or b is UNKNOWN:
        return UNKNOWN
    if len(a) != len(b):
        return UNKNOWN
    if a == b:
        return a
    out = []
    for da, db in zip(a, b):
        if tuple(da) == tuple(db):
            out.append(tuple(da))
        else:
            return UNKNOWN
    return tuple(out)


# ---------------------------------------------------------------------------
# collective events
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CollectiveEvent:
    kind: str                 # canonical kind (collective_cost table)
    axes: Tuple[str, ...]     # mesh axes, sorted
    dtype: str
    count: int                # occurrences per entry call (loop-scaled)
    bytes: int                # per-device wire bytes per entry call
    payload: int              # per-device payload bytes (one occurrence)
    group: int                # collective group size
    origin: str               # 'explicit' | 'inferred'
    context: str              # 'top' | 'while_loop'
    boundary: bool = False    # sits at a sharding/output boundary

    def key(self) -> str:
        return f"{self.kind}@{'+'.join(self.axes)}:{self.dtype}"


@dataclasses.dataclass
class SpmdReport:
    name: str
    events: List[CollectiveEvent] = dataclasses.field(default_factory=list)
    replication: List[str] = dataclasses.field(default_factory=list)
    wrong_axis: List[str] = dataclasses.field(default_factory=list)
    notes: List[str] = dataclasses.field(default_factory=list)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    error: Optional[str] = None

    def inventory(self) -> Dict[str, Dict[str, int]]:
        inv: Dict[str, Dict[str, int]] = {}
        for ev in self.events:
            rec = inv.setdefault(ev.key(), {"count": 0, "bytes": 0})
            rec["count"] += ev.count
            rec["bytes"] += ev.bytes
        return inv


# ---------------------------------------------------------------------------
# the jaxpr walker: explicit collection + conservative GSPMD propagation
# ---------------------------------------------------------------------------

_ELEMENTWISE = {
    "add", "sub", "mul", "div", "rem", "max", "min", "pow", "atan2",
    "and", "or", "xor", "not", "neg", "sign", "abs", "floor", "ceil",
    "round", "exp", "exp2", "log", "expm1", "log1p", "sin", "cos", "tan",
    "asin", "acos", "atan", "sinh", "cosh", "tanh", "logistic", "rsqrt",
    "sqrt", "cbrt", "erf", "erfc", "erf_inv", "integer_pow", "is_finite",
    "eq", "ne", "lt", "le", "gt", "ge", "select_n", "clamp", "nextafter",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "population_count", "clz", "real", "imag", "conj", "square",
    "reduce_precision", "copy", "stop_gradient",
}

#: single-input identity-spec primitives that also carry pending-psum
_PENDING_CARRIERS = {"convert_element_type", "neg", "transpose",
                     "reduce_precision", "copy", "reshape",
                     "broadcast_in_dim"}

_CALL_PRIMS = {"pjit", "closed_call", "core_call", "xla_call", "remat",
               "remat2", "checkpoint", "custom_jvp_call",
               "custom_jvp_call_jaxpr", "custom_vjp_call",
               "custom_vjp_call_jaxpr", "custom_lin"}

_SUM_REDUCES = {"reduce_sum": "psum", "reduce_prod": "psum",
                "reduce_max": "pmax", "reduce_min": "pmin",
                "reduce_and": "pmax", "reduce_or": "pmax",
                "argmax": "psum", "argmin": "psum"}


def _aval_bytes(aval) -> int:
    try:
        return payload_bytes_from_shape(aval.shape, aval.dtype)
    except Exception:
        return 0


def _closed(j):
    """Normalize Jaxpr/ClosedJaxpr → (jaxpr, constvar_count)."""
    inner = getattr(j, "jaxpr", j)
    return inner


@dataclasses.dataclass
class _Ctx:
    mult: int = 1
    in_while: bool = False
    manual_axes: Optional[frozenset] = None   # inside shard_map: varying axes
    mesh_shape: Dict[str, int] = dataclasses.field(default_factory=dict)

    def child(self, **kw):
        return dataclasses.replace(self, **kw)


class ProgramAnalyzer:
    """Analyze one traced program: collect explicit collectives, run the
    conservative sharding propagation, classify constraint boundaries."""

    def __init__(self, mesh_shape: Dict[str, int], report: SpmdReport):
        self.mesh = dict(mesh_shape)
        self.report = report

    # -- events ---------------------------------------------------------------
    def _group_size(self, axes, ctx: _Ctx) -> int:
        size = 1
        for a in axes:
            size *= (ctx.mesh_shape or self.mesh).get(a, 1)
        return size

    def _emit(self, kind, axes, dtype, payload, ctx: _Ctx, origin,
              boundary=False) -> CollectiveEvent:
        axes = tuple(sorted(str(a) for a in axes))
        group = self._group_size(axes, ctx)
        ev = CollectiveEvent(
            kind=kind, axes=axes, dtype=str(dtype), count=ctx.mult,
            bytes=wire_bytes(kind, payload, group) * ctx.mult,
            payload=int(payload), group=group, origin=origin,
            context="while_loop" if ctx.in_while else "top",
            boundary=boundary)
        self.report.events.append(ev)
        return ev

    def _reclassify_pending(self, events: List[CollectiveEvent],
                            new_kind: str, dtype) -> None:
        """Pending psum consumed by a sharding boundary over its own
        reduced axes: XLA fuses reduce+reshard into one reduce_scatter;
        the boundary dtype (post communication_data_type cast) is what
        moves on the wire."""
        for ev in events:
            ev.kind = new_kind
            ev.dtype = str(dtype)
            ev.bytes = wire_bytes(new_kind, ev.payload, ev.group) \
                * ev.count
            ev.boundary = True

    # -- main walk ------------------------------------------------------------
    def analyze(self, closed_jaxpr, in_specs_flat: List) -> List:
        jaxpr = closed_jaxpr.jaxpr
        env: Dict[Any, Any] = {}
        pending: Dict[Any, Tuple[frozenset, List[CollectiveEvent]]] = {}
        for v in jaxpr.constvars:
            env[v] = _replicated(len(getattr(v.aval, "shape", ())))
        if len(in_specs_flat) != len(jaxpr.invars):
            self.report.notes.append(
                f"in_specs arity {len(in_specs_flat)} != invars "
                f"{len(jaxpr.invars)}; treating inputs as UNKNOWN")
            in_specs_flat = [UNKNOWN] * len(jaxpr.invars)
        for v, s in zip(jaxpr.invars, in_specs_flat):
            env[v] = s
        ctx = _Ctx(mesh_shape=self.mesh)
        self._eval_jaxpr(jaxpr, env, pending, ctx)
        return [env.get(v, UNKNOWN) if not _is_literal(v)
                else _replicated(len(getattr(v.aval, "shape", ())))
                for v in jaxpr.outvars]

    def _read(self, env, atom):
        if _is_literal(atom):
            return _replicated(len(getattr(atom.aval, "shape", ())))
        return env.get(atom, UNKNOWN)

    def _eval_jaxpr(self, jaxpr, env, pending, ctx: _Ctx):
        for eqn in jaxpr.eqns:
            self._eval_eqn(eqn, env, pending, ctx)

    # -- one equation ---------------------------------------------------------
    def _eval_eqn(self, eqn, env, pending, ctx: _Ctx):
        name = eqn.primitive.name
        kind = collective_kind(name)
        if kind is not None:
            self._handle_collective(eqn, kind, ctx)
            for v in eqn.outvars:
                env[v] = UNKNOWN
            return

        if name == "shard_map":
            self._handle_shard_map(eqn, env, ctx)
            return
        if name == "sharding_constraint":
            self._handle_constraint(eqn, env, pending, ctx)
            return
        if name == "scan":
            self._handle_scan(eqn, env, pending, ctx)
            return
        if name == "while":
            self._handle_while(eqn, env, pending, ctx)
            return
        if name == "cond":
            self._handle_cond(eqn, env, pending, ctx)
            return
        if name in _CALL_PRIMS:
            sub = self._sub_jaxpr(eqn)
            if sub is not None:
                self._handle_call(eqn, sub, env, pending, ctx)
                return
        if name == "pallas_call":
            # kernel bodies hold no lax collectives; outputs shaped by
            # the wrapper — treat like an opaque elementwise-ish op
            self._default_prop(eqn, env, pending, ctx)
            return

        handler = getattr(self, f"_prop_{name}", None)
        if handler is not None:
            handler(eqn, env, pending, ctx)
        elif name in _ELEMENTWISE:
            self._prop_elementwise(eqn, env, pending, ctx)
        elif name in _SUM_REDUCES or name.startswith("reduce_"):
            self._prop_reduce(eqn, env, pending, ctx)
        else:
            # unknown prim: still sweep nested jaxprs for collectives so
            # nothing escapes the inventory, then propagate by default
            for sub in _nested_jaxprs(eqn.params):
                subenv = {}
                self._eval_jaxpr(sub, subenv, {}, ctx)
            self._default_prop(eqn, env, pending, ctx)

    # -- collectives (explicit: shard_map bodies) -----------------------------
    def _collective_axes(self, eqn) -> Tuple[str, ...]:
        axes = eqn.params.get("axes")
        if axes is None:
            axes = eqn.params.get("axis_name")
        if axes is None:
            return ()
        if isinstance(axes, (str, int)):
            axes = (axes,)
        return tuple(a for a in axes if isinstance(a, str))

    def _handle_collective(self, eqn, kind, ctx: _Ctx):
        axes = self._collective_axes(eqn)
        if not axes:
            return
        aval = eqn.invars[0].aval
        ev = self._emit(kind, axes, aval.dtype, _aval_bytes(aval), ctx,
                        origin="explicit")
        if ctx.manual_axes is not None:
            stray = [a for a in axes if a not in ctx.manual_axes
                     and (ctx.mesh_shape or self.mesh).get(a, 1) > 1]
            if stray:
                self.report.wrong_axis.append(
                    f"{kind} over axis {stray} inside a shard_map whose "
                    f"inputs only vary over "
                    f"{sorted(ctx.manual_axes)} — reducing a replicated "
                    f"value over an unmapped axis multiplies it by the "
                    f"axis size")
        return ev

    def _handle_shard_map(self, eqn, env, ctx: _Ctx):
        params = eqn.params
        mesh = params.get("mesh")
        mesh_shape = dict(getattr(mesh, "shape", {}) or {})
        in_names = params.get("in_names", ())
        varying = set()
        for names in in_names:
            for axes in (names or {}).values():
                varying.update(axes)
        sub = params.get("jaxpr")
        if sub is not None:
            # axis_index makes values vary over its axis with no input
            # varying there (the masked-psum broadcast idiom) — count
            # those axes as varying so wrong-axis keeps its zero-FP bias
            varying.update(_axis_index_axes(_closed(sub)))
        if sub is not None:
            inner = _closed(sub)
            subenv = {}
            subctx = ctx.child(manual_axes=frozenset(varying),
                               mesh_shape=mesh_shape or ctx.mesh_shape)
            self._eval_jaxpr(inner, subenv, {}, subctx)
        out_names = params.get("out_names", ())
        for v, names in zip(eqn.outvars, out_names):
            rank = len(getattr(v.aval, "shape", ()))
            env[v] = _names_to_spec(dict(names or {}), rank)

    # -- sharding constraints (the jit-with-shardings boundary) ---------------
    def _handle_constraint(self, eqn, env, pending, ctx: _Ctx):
        invar = eqn.invars[0]
        aval = invar.aval
        rank = len(aval.shape)
        sharding = eqn.params.get("sharding")
        pspec = getattr(sharding, "spec", None)
        new_spec = _pspec_to_spec(pspec, rank,
                                  eqn.params.get("unconstrained_dims"),
                                  self._read(env, invar))
        old_spec = self._read(env, invar)
        self._boundary_events(old_spec, new_spec, aval,
                              pending.get(invar), ctx, where="constraint")
        env[eqn.outvars[0]] = new_spec
        pending.pop(invar, None)

    def _boundary_events(self, old_spec, new_spec, aval, pending_rec,
                         ctx: _Ctx, where: str):
        """Classify a sharding transition into collective events."""
        dtype = aval.dtype
        total = _aval_bytes(aval)
        if old_spec is UNKNOWN:
            # cannot classify; still record the boundary (0 wire bytes)
            # so its DTYPE is budgeted — the communication_data_type cast
            # shows up as the key's dtype suffix
            axes = _spec_axes(new_spec)
            if axes:
                self._emit("reshard", axes, dtype, 0, ctx,
                           origin="inferred", boundary=True)
            return
        old_axes = _spec_axes(old_spec)
        new_axes = _spec_axes(new_spec)
        removed = old_axes - new_axes
        added = new_axes - old_axes
        moved = set()
        if old_axes & new_axes:
            for i, (da, db) in enumerate(zip(old_spec, new_spec)):
                for a in da:
                    if a in new_axes and a not in db:
                        moved.add(a)
        shard_count = self._group_size(old_axes, ctx)
        per_device = max(total // max(shard_count, 1), 0)
        for a in sorted(moved):
            self._emit("all_to_all", (a,), dtype, per_device, ctx,
                       origin="inferred", boundary=True)
        for a in sorted(removed - moved):
            self._emit("all_gather", (a,), dtype, per_device, ctx,
                       origin="inferred", boundary=True)
        pure_added = added - moved
        if pure_added:
            if pending_rec is not None and \
                    pure_added <= set(pending_rec[0]):
                # reduce immediately re-sharded over its own axis: XLA
                # fuses into a reduce_scatter at this boundary's dtype
                self._reclassify_pending(pending_rec[1],
                                         "reduce_scatter", dtype)
            else:
                self._emit("shard", sorted(pure_added), dtype, 0, ctx,
                           origin="inferred", boundary=True)

    # -- control flow ---------------------------------------------------------
    def _sub_jaxpr(self, eqn):
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            if key in eqn.params:
                return eqn.params[key]
        return None

    def _handle_call(self, eqn, sub, env, pending, ctx: _Ctx):
        inner = _closed(sub)
        subenv = {}
        for v in getattr(inner, "constvars", ()):
            subenv[v] = _replicated(len(getattr(v.aval, "shape", ())))
        invars = list(inner.invars)
        args = list(eqn.invars)
        # call prims may bury consts in leading invars; align from the
        # RIGHT (trailing args correspond) and replicate the rest
        offset = len(invars) - len(args)
        for i, v in enumerate(invars):
            j = i - offset
            subenv[v] = self._read(env, args[j]) if 0 <= j < len(args) \
                else _replicated(len(getattr(v.aval, "shape", ())))
        subpending: Dict = {}
        for a in args:
            if not _is_literal(a) and a in pending:
                k = invars[args.index(a) + offset] \
                    if 0 <= args.index(a) + offset < len(invars) else None
                if k is not None:
                    subpending[k] = pending[a]
        self._eval_jaxpr(inner, subenv, subpending, ctx)
        for v, ov in zip(eqn.outvars, inner.outvars):
            env[v] = subenv.get(ov, UNKNOWN) if not _is_literal(ov) \
                else _replicated(len(getattr(ov.aval, "shape", ())))
            if not _is_literal(ov) and ov in subpending:
                pending[v] = subpending[ov]

    def _handle_scan(self, eqn, env, pending, ctx: _Ctx):
        params = eqn.params
        inner = _closed(params["jaxpr"])
        n_consts = params.get("num_consts", 0)
        n_carry = params.get("num_carry", 0)
        length = int(params.get("length", 1) or 1)
        args = list(eqn.invars)
        const_specs = [self._read(env, a) for a in args[:n_consts]]
        carry_specs = [self._read(env, a)
                       for a in args[n_consts:n_consts + n_carry]]
        xs_specs = []
        for a in args[n_consts + n_carry:]:
            s = self._read(env, a)
            xs_specs.append(UNKNOWN if s is UNKNOWN else tuple(s[1:]))

        out_specs = None
        for attempt in range(3):
            mark = len(self.report.events)
            subenv = {}
            for v in getattr(inner, "constvars", ()):
                subenv[v] = _replicated(len(getattr(v.aval, "shape", ())))
            for v, s in zip(inner.invars,
                            const_specs + carry_specs + xs_specs):
                subenv[v] = s
            self._eval_jaxpr(inner, subenv, {},
                             ctx.child(mult=ctx.mult * length))
            outs = [subenv.get(ov, UNKNOWN) if not _is_literal(ov)
                    else _replicated(len(getattr(ov.aval, "shape", ())))
                    for ov in inner.outvars]
            new_carry = [_join_fixpoint(a, b)
                         for a, b in zip(carry_specs, outs[:n_carry])]
            if new_carry == carry_specs or attempt == 2:
                out_specs = outs
                break
            carry_specs = new_carry
            del self.report.events[mark:]   # re-run with joined carries

        ys = out_specs[n_carry:]
        ys = [UNKNOWN if s is UNKNOWN else ((),) + tuple(s) for s in ys]
        for v, s in zip(eqn.outvars, list(out_specs[:n_carry]) + ys):
            env[v] = s

    def _handle_while(self, eqn, env, pending, ctx: _Ctx):
        params = eqn.params
        cond_j = _closed(params["cond_jaxpr"])
        body_j = _closed(params["body_jaxpr"])
        cn = params.get("cond_nconsts", 0)
        bn = params.get("body_nconsts", 0)
        args = list(eqn.invars)
        cond_consts = [self._read(env, a) for a in args[:cn]]
        body_consts = [self._read(env, a) for a in args[cn:cn + bn]]
        carry = [self._read(env, a) for a in args[cn + bn:]]
        wctx = ctx.child(in_while=True)

        for attempt in range(3):
            mark = len(self.report.events)
            subenv = dict(zip(body_j.invars, body_consts + carry))
            self._eval_jaxpr(body_j, subenv, {}, wctx)
            outs = [subenv.get(ov, UNKNOWN) if not _is_literal(ov)
                    else _replicated(len(getattr(ov.aval, "shape", ())))
                    for ov in body_j.outvars]
            new_carry = [_join_fixpoint(a, b) for a, b in zip(carry, outs)]
            if new_carry == carry or attempt == 2:
                break
            carry = new_carry
            del self.report.events[mark:]
        cenv = dict(zip(cond_j.invars, cond_consts + carry))
        self._eval_jaxpr(cond_j, cenv, {}, wctx)
        for v, s in zip(eqn.outvars, carry):
            env[v] = s

    def _handle_cond(self, eqn, env, pending, ctx: _Ctx):
        branches = eqn.params.get("branches", ())
        args = [self._read(env, a) for a in eqn.invars[1:]]
        branch_outs = []
        for br in branches:
            inner = _closed(br)
            subenv = {}
            for v in getattr(inner, "constvars", ()):
                subenv[v] = _replicated(len(getattr(v.aval, "shape", ())))
            for v, s in zip(inner.invars, args):
                subenv[v] = s
            self._eval_jaxpr(inner, subenv, {}, ctx)
            branch_outs.append(
                [subenv.get(ov, UNKNOWN) if not _is_literal(ov)
                 else _replicated(len(getattr(ov.aval, "shape", ())))
                 for ov in inner.outvars])
        for i, v in enumerate(eqn.outvars):
            env[v] = _merge_specs([outs[i] for outs in branch_outs]) \
                if branch_outs else UNKNOWN

    # -- propagation handlers -------------------------------------------------
    def _all_inputs_replicated(self, eqn, env) -> bool:
        return all(_is_replicated(self._read(env, a)) for a in eqn.invars)

    def _default_prop(self, eqn, env, pending, ctx: _Ctx):
        if self._all_inputs_replicated(eqn, env):
            for v in eqn.outvars:
                env[v] = _replicated(len(getattr(v.aval, "shape", ())))
            return
        candidates = []
        for a in eqn.invars:
            s = self._read(env, a)
            if s is UNKNOWN:
                for v in eqn.outvars:
                    env[v] = UNKNOWN
                return
            if not _is_replicated(s):
                candidates.append((getattr(a.aval, "shape", ()), s))
        uniq = {s for _, s in candidates}
        for v in eqn.outvars:
            shape = tuple(getattr(v.aval, "shape", ()))
            if len(uniq) == 1:
                shp, s = candidates[0]
                env[v] = s if tuple(shp) == shape else UNKNOWN
            else:
                env[v] = UNKNOWN
        self._carry_pending(eqn, env, pending)

    def _carry_pending(self, eqn, env, pending):
        if eqn.primitive.name not in _PENDING_CARRIERS:
            return
        srcs = [a for a in eqn.invars
                if not _is_literal(a) and a in pending]
        if len(srcs) == 1 and len(eqn.outvars) == 1:
            pending[eqn.outvars[0]] = pending[srcs[0]]

    def _prop_elementwise(self, eqn, env, pending, ctx: _Ctx):
        out_shapes = {tuple(getattr(v.aval, "shape", ()))
                      for v in eqn.outvars}
        out_shape = next(iter(out_shapes)) if len(out_shapes) == 1 else None
        specs = []
        for a in eqn.invars:
            s = self._read(env, a)
            shape = tuple(getattr(a.aval, "shape", ()))
            if not shape:            # scalars broadcast freely
                continue
            if out_shape is None:
                specs.append(s)
            elif shape == out_shape:
                specs.append(s)
            elif s is UNKNOWN or len(shape) != len(out_shape):
                specs.append(UNKNOWN)
            else:
                # rank-equal implicit broadcast (size-1 dims stretch):
                # a size-1 dim is never meaningfully sharded, so it
                # contributes no constraint; full-size dims keep theirs
                aligned = []
                for d in range(len(shape)):
                    if shape[d] == out_shape[d]:
                        aligned.append(tuple(s[d]))
                    elif shape[d] == 1:
                        aligned.append(())
                    else:
                        aligned = None
                        break
                specs.append(tuple(aligned) if aligned is not None
                             else UNKNOWN)
        merged = _merge_specs(specs) if specs else None
        for v in eqn.outvars:
            rank = len(getattr(v.aval, "shape", ()))
            if merged is None:
                env[v] = _replicated(rank)
            elif merged is UNKNOWN or len(merged) != rank:
                env[v] = UNKNOWN if merged is UNKNOWN else _replicated(rank)
            else:
                env[v] = merged
        # add of two same-axes pendings stays pending (grad accumulation)
        if eqn.primitive.name in ("add", "sub", "mul", "div"):
            srcs = [a for a in eqn.invars
                    if not _is_literal(a) and a in pending]
            others = [a for a in eqn.invars
                      if not _is_literal(a) and a not in pending
                      and len(getattr(a.aval, "shape", ()))]
            if srcs and not others and len(eqn.outvars) == 1:
                axes_sets = {pending[s][0] for s in srcs}
                if len(axes_sets) == 1:
                    evs = [e for s in srcs for e in pending[s][1]]
                    pending[eqn.outvars[0]] = (srcs and
                                               next(iter(axes_sets)), evs)

    def _prop_convert_element_type(self, eqn, env, pending, ctx: _Ctx):
        env[eqn.outvars[0]] = self._read(env, eqn.invars[0])
        self._carry_pending(eqn, env, pending)

    def _prop_broadcast_in_dim(self, eqn, env, pending, ctx: _Ctx):
        s = self._read(env, eqn.invars[0])
        out = eqn.outvars[0]
        rank = len(out.aval.shape)
        if s is UNKNOWN:
            env[out] = UNKNOWN
            return
        dims = eqn.params.get("broadcast_dimensions", ())
        spec = [()] * rank
        in_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        for i, d in enumerate(dims):
            if i < len(s) and i < len(in_shape) and \
                    in_shape[i] == out.aval.shape[d]:
                spec[d] = tuple(s[i])
        env[out] = tuple(spec)
        self._carry_pending(eqn, env, pending)

    def _prop_transpose(self, eqn, env, pending, ctx: _Ctx):
        s = self._read(env, eqn.invars[0])
        out = eqn.outvars[0]
        if s is UNKNOWN:
            env[out] = UNKNOWN
            return
        perm = eqn.params.get("permutation", ())
        env[out] = tuple(tuple(s[p]) for p in perm)
        self._carry_pending(eqn, env, pending)

    def _prop_reshape(self, eqn, env, pending, ctx: _Ctx):
        s = self._read(env, eqn.invars[0])
        out = eqn.outvars[0]
        if s is UNKNOWN:
            env[out] = UNKNOWN
            return
        in_shape = tuple(eqn.invars[0].aval.shape)
        out_shape = tuple(out.aval.shape)
        spec = _map_reshape_spec(s, in_shape, out_shape)
        env[out] = spec
        if spec is not UNKNOWN:
            self._carry_pending(eqn, env, pending)

    def _prop_squeeze(self, eqn, env, pending, ctx: _Ctx):
        s = self._read(env, eqn.invars[0])
        out = eqn.outvars[0]
        if s is UNKNOWN:
            env[out] = UNKNOWN
            return
        drop = set(eqn.params.get("dimensions", ()))
        env[out] = tuple(tuple(d) for i, d in enumerate(s)
                         if i not in drop)

    def _prop_reduce(self, eqn, env, pending, ctx: _Ctx):
        s = self._read(env, eqn.invars[0])
        out = eqn.outvars[0]
        axes = set(eqn.params.get("axes", ()))
        if s is UNKNOWN:
            env[out] = UNKNOWN
            return
        reduced_axes = set()
        for i in axes:
            if i < len(s):
                reduced_axes.update(s[i])
        keep = tuple(tuple(d) for i, d in enumerate(s) if i not in axes)
        env[out] = keep
        if reduced_axes:
            kind = _SUM_REDUCES.get(eqn.primitive.name, "psum")
            per_device = _aval_bytes(out.aval) // max(
                self._group_size(_spec_axes(keep), ctx), 1)
            ev = self._emit(kind, sorted(reduced_axes), out.aval.dtype,
                            per_device, ctx, origin="inferred")
            if kind == "psum":
                pending[out] = (frozenset(reduced_axes), [ev])

    def _prop_dot_general(self, eqn, env, pending, ctx: _Ctx):
        lhs, rhs = eqn.invars[:2]
        ls, rs = self._read(env, lhs), self._read(env, rhs)
        out = eqn.outvars[0]
        if ls is UNKNOWN or rs is UNKNOWN:
            env[out] = UNKNOWN
            return
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        contracted = set()
        for i in lc:
            if i < len(ls):
                contracted.update(ls[i])
        for i in rc:
            if i < len(rs):
                contracted.update(rs[i])
        l_free = [i for i in range(len(ls)) if i not in set(lc) | set(lb)]
        r_free = [i for i in range(len(rs)) if i not in set(rc) | set(rb)]
        spec = []
        for li, ri in zip(lb, rb):
            m = _merge_dim(ls[li], rs[ri])
            spec.append(m if m is not None else ())
        spec += [tuple(ls[i]) for i in l_free]
        spec += [tuple(rs[i]) for i in r_free]
        if len(spec) != len(out.aval.shape):
            env[out] = UNKNOWN
            return
        env[out] = tuple(spec)
        if contracted:
            per_device = _aval_bytes(out.aval) // max(
                self._group_size(_spec_axes(tuple(spec)), ctx), 1)
            ev = self._emit("psum", sorted(contracted), out.aval.dtype,
                            per_device, ctx, origin="inferred")
            pending[out] = (frozenset(contracted), [ev])

    def _prop_gather(self, eqn, env, pending, ctx: _Ctx):
        operand, indices = eqn.invars[:2]
        os, isx = self._read(env, operand), self._read(env, indices)
        out = eqn.outvars[0]
        if not _is_replicated(os) or isx is UNKNOWN:
            env[out] = UNKNOWN
            return
        dn = eqn.params.get("dimension_numbers")
        offset_dims = set(getattr(dn, "offset_dims", ()) or ())
        rank = len(out.aval.shape)
        batch_dims = [i for i in range(rank) if i not in offset_dims]
        spec = [()] * rank
        for bi, d in enumerate(batch_dims):
            if bi < len(isx):
                spec[d] = tuple(isx[bi])
        env[out] = tuple(spec)

    def _prop_scatter_add(self, eqn, env, pending, ctx: _Ctx):
        operand, _indices, updates = eqn.invars[:3]
        os = self._read(env, operand)
        us = self._read(env, updates)
        out = eqn.outvars[0]
        if os is UNKNOWN:
            env[out] = UNKNOWN
            return
        env[out] = os
        if us is not UNKNOWN:
            extra = _spec_axes(us) - _spec_axes(os)
            if extra:
                # sharded contributions accumulated into a less-sharded
                # buffer: XLA synthesizes the cross-shard reduction (the
                # embedding-gradient all-reduce)
                per_device = _aval_bytes(out.aval) // max(
                    self._group_size(_spec_axes(os), ctx), 1)
                ev = self._emit("psum", sorted(extra), out.aval.dtype,
                                per_device, ctx, origin="inferred")
                pending[out] = (frozenset(extra), [ev])

    def _prop_concatenate(self, eqn, env, pending, ctx: _Ctx):
        specs = [self._read(env, a) for a in eqn.invars]
        out = eqn.outvars[0]
        dim = eqn.params.get("dimension", 0)
        merged = _merge_specs(specs)
        if merged is UNKNOWN or (len(merged) > dim and merged[dim]):
            env[out] = UNKNOWN
        else:
            env[out] = merged

    def _prop_slice(self, eqn, env, pending, ctx: _Ctx):
        self._prop_shrink(eqn, env)

    def _prop_dynamic_slice(self, eqn, env, pending, ctx: _Ctx):
        self._prop_shrink(eqn, env)

    def _prop_shrink(self, eqn, env):
        s = self._read(env, eqn.invars[0])
        out = eqn.outvars[0]
        if s is UNKNOWN:
            env[out] = UNKNOWN
            return
        in_shape = tuple(eqn.invars[0].aval.shape)
        out_shape = tuple(out.aval.shape)
        if len(in_shape) != len(out_shape) or len(s) != len(in_shape):
            env[out] = UNKNOWN
            return
        spec = []
        for i in range(len(s)):
            if in_shape[i] == out_shape[i]:
                spec.append(tuple(s[i]))
            elif s[i]:
                env[out] = UNKNOWN
                return
            else:
                spec.append(())
        env[out] = tuple(spec)

    def _prop_dynamic_update_slice(self, eqn, env, pending, ctx: _Ctx):
        os = self._read(env, eqn.invars[0])
        us = self._read(env, eqn.invars[1])
        out = eqn.outvars[0]
        if os is UNKNOWN:
            env[out] = UNKNOWN
        elif _is_replicated(us) or us == os:
            env[out] = os
        else:
            env[out] = UNKNOWN

    def _prop_pad(self, eqn, env, pending, ctx: _Ctx):
        s = self._read(env, eqn.invars[0])
        out = eqn.outvars[0]
        if s is UNKNOWN:
            env[out] = UNKNOWN
            return
        in_shape = tuple(eqn.invars[0].aval.shape)
        out_shape = tuple(out.aval.shape)
        spec = []
        for i in range(len(s)):
            if in_shape[i] == out_shape[i]:
                spec.append(tuple(s[i]))
            elif s[i]:
                env[out] = UNKNOWN
                return
            else:
                spec.append(())
        env[out] = tuple(spec)

    def _prop_iota(self, eqn, env, pending, ctx: _Ctx):
        env[eqn.outvars[0]] = _replicated(len(eqn.outvars[0].aval.shape))


def _is_literal(atom) -> bool:
    import jax

    return isinstance(atom, jax.core.Literal)


def _axis_index_axes(jaxpr) -> set:
    """Axes any ``axis_index``/``iota``-derived index varies over inside
    ``jaxpr`` (recursing through nested jaxprs)."""
    axes: set = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in getattr(j, "eqns", ()):
            if eqn.primitive.name == "axis_index":
                a = eqn.params.get("axis_name")
                if isinstance(a, (str, int)):
                    a = (a,)
                axes.update(x for x in (a or ()) if isinstance(x, str))
            stack.extend(_nested_jaxprs(eqn.params))
    return axes


def _nested_jaxprs(params):
    out = []
    stack = list(params.values())
    while stack:
        v = stack.pop()
        if isinstance(v, (list, tuple)):
            stack.extend(v)
        elif hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
            out.append(v.jaxpr)
        elif hasattr(v, "eqns"):
            out.append(v)
    return out


def _map_reshape_spec(spec, in_shape, out_shape):
    """Map a spec across reshape: sharded dims survive only through 1:1
    size-preserved groups; any sharded dim in a merged/split group →
    UNKNOWN (conservative)."""
    i = j = 0
    out_spec = [()] * len(out_shape)
    while i < len(in_shape) or j < len(out_shape):
        # skip size-1 dims (never meaningfully sharded)
        if i < len(in_shape) and in_shape[i] == 1 and not spec[i]:
            i += 1
            continue
        if j < len(out_shape) and out_shape[j] == 1:
            j += 1
            continue
        if i >= len(in_shape) or j >= len(out_shape):
            return UNKNOWN
        if in_shape[i] == out_shape[j]:
            out_spec[j] = tuple(spec[i])
            i += 1
            j += 1
            continue
        # grouped dims: accumulate products until they match
        pi, pj = in_shape[i], out_shape[j]
        gi, gj = [i], [j]
        while pi != pj:
            if pi < pj:
                i += 1
                if i >= len(in_shape):
                    return UNKNOWN
                pi *= in_shape[i]
                gi.append(i)
            else:
                j += 1
                if j >= len(out_shape):
                    return UNKNOWN
                pj *= out_shape[j]
                gj.append(j)
        if any(spec[k] for k in gi):
            return UNKNOWN
        i += 1
        j += 1
    return tuple(out_spec)


# ---------------------------------------------------------------------------
# entry-point registry — the repo's real sharded programs
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SpmdEntry:
    name: str
    build: Any     # () -> dict(fn, avals, in_specs, out_specs, mesh, meta)


def _tiny_lm_pieces():
    """(loss_fn, abstract params, abstract batch) for a tiny Llama causal
    LM — the model family every training entry point in-tree trains."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = jax.eval_shape(lambda r, x: model.init(r, x)["params"],
                            rng, ids)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["input_ids"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, batch["labels"][..., None],
                                 axis=-1)[..., 0]
        return -jnp.mean(ll)

    sds = jax.ShapeDtypeStruct
    batch = {"input_ids": sds((8, 16), jnp.int32),
             "labels": sds((8, 16), jnp.int32)}
    return cfg, loss_fn, params, batch


def _zero_entry(stage: int, with_stats: bool = True):
    import jax
    import optax
    from jax.sharding import AbstractMesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
    from deepspeed_tpu.runtime.zero.stages import (
        build_zero_train_step, opt_state_shardings, plan_zero_shardings,
    )

    mesh = AbstractMesh((("data", 8),))
    _cfg, loss_fn, params, batch = _tiny_lm_pieces()
    plan = plan_zero_shardings(params, mesh, DeepSpeedZeroConfig(stage=stage))
    opt = optax.adamw(1e-3)
    opt_abs = jax.eval_shape(opt.init, params)
    opt_sh = opt_state_shardings(opt_abs, params, plan, mesh)
    opt_specs = jax.tree_util.tree_map(
        lambda s: s.spec, opt_sh,
        is_leaf=lambda x: isinstance(x, NamedSharding))
    # stage >= 2 runs the reduction boundary at the configured
    # communication dtype (the quantized-collective arm of ROADMAP item
    # 3 will drop this to int8; the spmd-collective-dtype rule pins it)
    comm = "bfloat16" if stage >= 2 else None
    # stats ON is the engine's dsttrain default; the budget gate plus
    # the with/without-stats inventory pin (tests/unit/test_dsttrain.py)
    # prove the health pytree adds ZERO new collective keys
    step = build_zero_train_step(loss_fn, opt, plan, mesh,
                                 communication_data_type=comm,
                                 with_stats=with_stats)
    batch_specs = {"input_ids": P("data"), "labels": P("data")}
    out_specs = [P(), plan.param_specs, opt_specs]
    if with_stats:
        stats_abs = jax.eval_shape(step, params, opt_abs, batch)[3]
        out_specs.append(jax.tree_util.tree_map(lambda _: P(), stats_abs))
    return {
        "fn": step,
        "avals": (params, opt_abs, batch),
        "in_specs": (plan.param_specs, opt_specs, batch_specs),
        "out_specs": tuple(out_specs),
        "mesh": mesh,
        "meta": {"reduction_dtype": comm,
                 # the scalar loss is replicated by design
                 "allow_replicated": [0]},
    }


def _pipeline_entry():
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.runtime.pipe.interpreter import make_1f1b_lm_loss

    cfg, _loss, params, _b = _tiny_lm_pieces()
    mesh = AbstractMesh((("pipe", 2), ("data", 2), ("tensor", 2)))
    loss_fn = make_1f1b_lm_loss(cfg, mesh, num_micro=2)
    sds = jax.ShapeDtypeStruct
    batch = {"input_ids": sds((4, 8), jnp.int32),
             "labels": sds((4, 8), jnp.int32)}

    def fn(p, b):
        return jax.value_and_grad(lambda pp: loss_fn(pp, b))(p)

    blocks_spec = jax.tree_util.tree_map(lambda _: P("pipe"),
                                         params["blocks"])
    rest_spec = {k: jax.tree_util.tree_map(lambda _: P(), v)
                 for k, v in params.items() if k != "blocks"}
    param_specs = dict(rest_spec, blocks=blocks_spec)
    return {
        "fn": fn,
        "avals": (params, batch),
        "in_specs": (param_specs, {"input_ids": P("data"),
                                   "labels": P("data")}),
        # loss replicated by design; grads come back in the parameter
        # layout (stage-sharded blocks, replicated embeddings)
        "out_specs": (P(), param_specs),
        "mesh": mesh,
        "meta": {"allow_replicated": "all"},
    }


def _moe_entry():
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.moe.sharded_moe import moe_dispatch_combine
    from deepspeed_tpu.utils.jax_compat import abstract_mesh_context

    mesh = AbstractMesh((("data", 4), ("expert", 2)))
    sds = jax.ShapeDtypeStruct
    x = sds((32, 16), jnp.float32)
    gl = sds((32, 8), jnp.float32)
    w = sds((8, 16, 32), jnp.float32)

    def fn(x, gate_logits, w):
        def expert_fn(inp):
            h = jnp.einsum("ecd,edf->ecf", inp, w)
            return jnp.einsum("ecf,edf->ecd", jax.nn.relu(h), w)

        return moe_dispatch_combine(x, gate_logits, expert_fn, k=2)

    return {
        "fn": fn,
        "avals": (x, gl, w),
        "in_specs": (P("data"), P("data"), P("expert")),
        "out_specs": (P("data"), P()),
        "mesh": mesh,
        "meta": {"allow_replicated": [1],    # aux loss scalar
                 "trace_ctx": lambda: abstract_mesh_context(mesh)},
    }


def _sequence_entry(which: str):
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = AbstractMesh((("sequence", 4),))
    sds = jax.ShapeDtypeStruct
    q = sds((2, 32, 4, 8), jnp.float32)

    if which == "ring":
        from deepspeed_tpu.ops.ring_attention import ring_attention as attn
    else:
        from deepspeed_tpu.ops.ulysses import ulysses_attention as attn

    fn = shard_map(lambda a, b, c: attn(a, b, c, causal=True), mesh=mesh,
                   in_specs=(P(None, "sequence"),) * 3,
                   out_specs=P(None, "sequence"))
    spec = P(None, "sequence")
    return {
        "fn": fn,
        "avals": (q, q, q),
        "in_specs": (spec, spec, spec),
        "out_specs": spec,
        "mesh": mesh,
        "meta": {},
    }


def _serve_entry(which: str):
    import jax
    from jax.sharding import AbstractMesh
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.tools.dstlint.jaxprpass import (
        _abstract_serving_pieces,
    )

    if which in ("ragged", "ragged_verify"):
        from deepspeed_tpu.tools.dstlint.jaxprpass import (
            _ragged_serving_pieces,
        )

        fn, avals = _ragged_serving_pieces(
            "reference", verify=which == "ragged_verify")
    else:
        (decode_jit, decode_avals, prefill_jit, prefill_avals,
         _c, _ca) = _abstract_serving_pieces("reference")
        fn, avals = ((decode_jit, decode_avals) if which == "decode"
                     else (prefill_jit, prefill_avals))
    reps = jax.tree_util.tree_map(lambda _: P(), avals)
    return {
        "fn": fn,
        "avals": avals,
        "in_specs": reps,
        "out_specs": None,     # single-replica: everything replicated
        "mesh": AbstractMesh((("tensor", 2),)),
        # the SINGLE-replica serving executors: ANY collective is an
        # implicit insertion, and the decode while_loop body keeps a
        # per-step allowance of zero — the TP serve arm has its own
        # entries (serve_decode_tp2/*) carrying the real budget
        "meta": {"allow_replicated": "all", "while_allowance": {}},
    }


def _serve_tp_entry(collective: str):
    """The tensor-parallel decode step (TP=2, fused scan-Llama wrapped
    in ``tp_shard.make_tp_paged_apply``) — the entry that graduates
    ``spmd-decode-collective`` from "zero allowed" to a real per-step
    budget: two residual-boundary all-reduces per layer inside the layer
    scan, so the fp32 arm budgets ``2·L`` psums per decode step and the
    int8 EQuARX arm budgets the quantized ring's ``ppermute`` hops
    (per all-reduce: ``2·(n-1)`` int8 payload hops + ``2·(n-1)`` fp32
    scale hops). The int8 entry also pins the wire DTYPE via
    ``reduction_dtype`` — a decode all-reduce regressing to a plain
    fp32 psum fires ``spmd-collective-dtype``, with the fp32 *scale*
    hops (metadata, ~1.6% of the payload) explicitly allow-listed by
    exact key rather than exempted wholesale."""
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.models.llama import LlamaConfig
    from deepspeed_tpu.tools.dstlint.jaxprpass import _tp_serving_pieces
    from deepspeed_tpu.utils.jax_compat import abstract_mesh_context

    tp = 2
    fn, avals, mesh, param_specs, pspec = _tp_serving_pieces(
        collective, tp=tp)
    L = LlamaConfig.tiny().num_layers
    rest = tuple(P() for _ in range(len(avals) - 3))
    if collective == "int8":
        # 2 all-reduces/layer × 2 phases × (n-1) hops, per wire dtype
        hops = 2 * 2 * (tp - 1) * L
        allowance = {"ppermute@tensor:int8": hops,
                     "ppermute@tensor:float32": hops}
        dtype_meta = {"reduction_dtype": "int8",
                      "collective_dtype_allow":
                          ["ppermute@tensor:float32"]}
    else:
        allowance = {"psum@tensor:float32": 2 * L}
        dtype_meta = {}
    return {
        "fn": fn,
        "avals": avals,
        "in_specs": (param_specs, P(), pspec) + rest,
        "out_specs": None,   # logits replicated by construction (parity
        # tests pin it); pools come back head-sharded via out_names
        "mesh": mesh,
        "meta": {"allow_replicated": "all",
                 "while_allowance": allowance,
                 "trace_ctx": lambda: abstract_mesh_context(mesh),
                 **dtype_meta},
    }


def spmd_entry_points() -> List[SpmdEntry]:
    return [
        SpmdEntry("zero_step/stage1", lambda: _zero_entry(1)),
        SpmdEntry("zero_step/stage2", lambda: _zero_entry(2)),
        SpmdEntry("zero_step/stage3", lambda: _zero_entry(3)),
        SpmdEntry("pipeline_1f1b/pp2dp2tp2", _pipeline_entry),
        SpmdEntry("moe_dispatch/top2_ep2dp4", _moe_entry),
        SpmdEntry("ring_attention/seq4", lambda: _sequence_entry("ring")),
        SpmdEntry("ulysses_attention/seq4",
                  lambda: _sequence_entry("ulysses")),
        SpmdEntry("serve_decode/reference",
                  lambda: _serve_entry("decode")),
        SpmdEntry("serve_prefill/reference",
                  lambda: _serve_entry("prefill")),
        SpmdEntry("serve_ragged/reference",
                  lambda: _serve_entry("ragged")),
        SpmdEntry("serve_ragged_verify/reference",
                  lambda: _serve_entry("ragged_verify")),
        SpmdEntry("serve_decode_tp2/fp32",
                  lambda: _serve_tp_entry("fp32")),
        SpmdEntry("serve_decode_tp2/int8",
                  lambda: _serve_tp_entry("int8")),
    ]


# ---------------------------------------------------------------------------
# tracing + rule evaluation
# ---------------------------------------------------------------------------

def _flatten_specs(tree, avals, mesh) -> List:
    """Pytree of PartitionSpecs (aligned with ``avals``) → flat internal
    specs in jaxpr invar order."""
    import jax
    from jax.sharding import PartitionSpec

    flat_avals, _ = jax.tree_util.tree_flatten(avals)
    if tree is None:
        return [UNKNOWN] * len(flat_avals)
    flat_specs, _ = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, PartitionSpec))
    if len(flat_specs) != len(flat_avals):
        # spec tree does not align leaf-for-leaf with the avals; treat
        # every input as UNKNOWN rather than misattribute shardings
        return [UNKNOWN] * len(flat_avals)
    out = []
    for spec, aval in zip(flat_specs, flat_avals):
        rank = len(getattr(aval, "shape", ()))
        if isinstance(spec, PartitionSpec):
            out.append(_pspec_to_spec(spec, rank))
        else:
            out.append(UNKNOWN)
    return out


def _broadcast_spec_tree(spec_tree, aval_tree):
    """Expand a spec tree whose leaves are PartitionSpecs covering whole
    sub-trees of avals (e.g. one P('data') for a dict batch)."""
    import jax
    from jax.sharding import PartitionSpec

    def expand(spec, avals):
        if isinstance(spec, PartitionSpec):
            return jax.tree_util.tree_map(lambda _: spec, avals)
        if isinstance(spec, dict):
            return {k: expand(spec[k], avals[k]) for k in avals}
        if isinstance(spec, tuple) and hasattr(spec, "_fields"):
            # NamedTuple (optax states): positional fields, not one
            # iterable argument
            return type(spec)(*(expand(s, a)
                                for s, a in zip(spec, avals)))
        if isinstance(spec, (list, tuple)):
            return type(spec)(expand(s, a) for s, a in zip(spec, avals))
        return jax.tree_util.tree_map(lambda _: PartitionSpec(), avals)

    return expand(spec_tree, aval_tree)


def trace_spmd_entry_points(entries: Optional[List[SpmdEntry]] = None
                            ) -> Dict[str, SpmdReport]:
    import contextlib

    import jax

    reports: Dict[str, SpmdReport] = {}
    for entry in (entries if entries is not None else spmd_entry_points()):
        report = SpmdReport(entry.name)
        reports[entry.name] = report
        try:
            built = entry.build()
            report.meta = dict(built.get("meta") or {})
            mesh = built["mesh"]
            mesh_shape = dict(getattr(mesh, "shape", {}) or {})
            ctx_factory = report.meta.pop("trace_ctx", None)
            tctx = ctx_factory() if ctx_factory else contextlib.nullcontext()
            with tctx:
                closed = jax.make_jaxpr(built["fn"])(*built["avals"])
            in_specs = _broadcast_spec_tree(built["in_specs"],
                                            built["avals"])
            flat_in = _flatten_specs(in_specs, built["avals"], mesh)
            analyzer = ProgramAnalyzer(mesh_shape, report)
            out_specs_flat = analyzer.analyze(closed, flat_in)
            _check_outputs(report, built, closed, out_specs_flat,
                           flat_in, analyzer)
        except Exception as e:
            report.error = f"{type(e).__name__}: {e}"
    return reports


def _check_outputs(report: SpmdReport, built, closed, out_specs_flat,
                   in_specs_flat, analyzer: ProgramAnalyzer):
    """Compare propagated output shardings against declared ones:
    inferred epilogue collectives (the ZeRO-1 param all-gather) and the
    spmd-replication rule."""
    import jax
    from jax.sharding import PartitionSpec

    declared = built.get("out_specs")
    if declared is None:
        return
    out_avals = [v.aval for v in closed.jaxpr.outvars]
    # expand declared tree against the output STRUCTURE via eval-shape
    # of nothing: we already have flat avals; expand coarse specs
    flat_declared, _ = jax.tree_util.tree_flatten(
        declared, is_leaf=lambda x: isinstance(x, PartitionSpec))
    if len(flat_declared) != len(out_avals):
        # coarse spec tree; conservatively skip output-boundary checks
        report.notes.append(
            f"declared out_specs arity {len(flat_declared)} != "
            f"{len(out_avals)} outputs; output boundary unchecked")
        return
    allow = report.meta.get("allow_replicated", [])
    any_sharded_input = any(
        s is not UNKNOWN and not _is_replicated(s) for s in in_specs_flat)
    ctx = _Ctx(mesh_shape=analyzer.mesh)
    for i, (aval, got, want) in enumerate(
            zip(out_avals, out_specs_flat, flat_declared)):
        rank = len(getattr(aval, "shape", ()))
        want_spec = _pspec_to_spec(want, rank) \
            if isinstance(want, PartitionSpec) else _replicated(rank)
        if got is UNKNOWN:
            continue
        analyzer._boundary_events(got, want_spec, aval, None, ctx,
                                  where="output")
        if allow == "all" or i in (allow or []):
            continue
        if any_sharded_input and _spec_axes(want_spec) and \
                _is_replicated(got):
            report.replication.append(
                f"output #{i} ({aval.dtype}{list(aval.shape)}) is "
                f"declared {want} but the traced program provably "
                f"computes it fully REPLICATED with no "
                f"with_sharding_constraint re-sharding it — the whole "
                f"buffer materializes on every device")


# ---------------------------------------------------------------------------
# budgets
# ---------------------------------------------------------------------------

def load_budgets(path) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def budgets_from_reports(reports: Dict[str, SpmdReport],
                         tolerance_pct: int = DEFAULT_TOLERANCE_PCT
                         ) -> dict:
    import jax

    entries = {}
    for name, rep in sorted(reports.items()):
        if rep.error is None:
            entries[name] = {
                "tolerance_pct": tolerance_pct,
                "collectives": {k: dict(v) for k, v in
                                sorted(rep.inventory().items())},
            }
    return {"version": 1, "jax_version": jax.__version__,
            "entries": entries}


def check_reports(reports: Dict[str, SpmdReport],
                  budgets: Optional[dict]) -> List[Finding]:
    findings: List[Finding] = []
    entries = (budgets or {}).get("entries", {})

    def emit(rule, name, msg):
        findings.append(Finding(rule, f"<spmd:{name}>", 1, 0, msg))

    for name, rep in reports.items():
        if rep.error is not None:
            emit("spmd-comms-budget", name,
                 f"entry point failed to trace: {rep.error}")
            continue
        for msg in rep.replication:
            emit("spmd-replication", name, msg)
        for msg in rep.wrong_axis:
            emit("spmd-wrong-axis", name, msg)

        # decode/while allowance
        allowance = rep.meta.get("while_allowance")
        if allowance is not None:
            counts = Counter()
            for ev in rep.events:
                if ev.context == "while_loop":
                    counts[ev.key()] += ev.count
            for key, n in sorted(counts.items()):
                if n > allowance.get(key, 0):
                    emit("spmd-decode-collective", name,
                         f"collective '{key}' x{n} inside the decode "
                         f"while_loop body exceeds the per-step "
                         f"allowance ({allowance.get(key, 0)}) — a "
                         f"per-decode-step collective is the TP serving "
                         f"hot path; budget it explicitly")

        # reduction dtype (EQuARX guardrail)
        expect = rep.meta.get("reduction_dtype")
        if expect:
            want_bits = _FLOAT_BITS.get(expect, 8)
            allow_keys = set(rep.meta.get("collective_dtype_allow") or ())
            wide: Dict[str, int] = Counter()
            for ev in rep.events:
                # two audited surfaces: reduction BOUNDARIES (the ZeRO
                # gradient path), and explicit decode-loop collectives
                # (the TP serving path — the quantized ring's wire dtype
                # is the int8 payload; its fp32 scale hops are allow-
                # listed by exact key, never by dropping the audit)
                audited = (ev.boundary
                           and ev.kind in _BOUNDARY_DTYPE_KINDS) or (
                    ev.context == "while_loop" and ev.origin == "explicit"
                    and ev.kind in _WHILE_DTYPE_KINDS)
                if not audited or ev.key() in allow_keys:
                    continue
                got_bits = _FLOAT_BITS.get(ev.dtype)
                if got_bits is not None and got_bits > want_bits:
                    wide[ev.key()] += ev.count
            for key, n in sorted(wide.items()):
                got_bits = _FLOAT_BITS.get(key.rsplit(":", 1)[-1], 32)
                emit("spmd-collective-dtype", name,
                     f"reduction boundary '{key}' (x{n}) moves a wider "
                     f"float than the entry's communication dtype "
                     f"{expect} — the collective will run {got_bits}-bit "
                     f"on the wire (quantized-collective guardrail)")

        budget = entries.get(name)
        inv = rep.inventory()
        if budget is None:
            if inv:
                emit("spmd-comms-budget", name,
                     f"no checked-in comms budget for this entry point "
                     f"({len(inv)} collective keys measured) — run "
                     f"`bin/dst lint --update-budgets`")
            continue
        tol = budget.get("tolerance_pct", DEFAULT_TOLERANCE_PCT)
        ref = budget.get("collectives", {})
        for key, rec in sorted(inv.items()):
            if key not in ref:
                emit("spmd-implicit-collective", name,
                     f"collective '{key}' (x{rec['count']}, "
                     f"{rec['bytes']} wire B) appears in the traced "
                     f"program but NOT in the checked-in comms budget — "
                     f"an implicit all-gather/reshard crept in; if "
                     f"intentional run `bin/dst lint --update-budgets`")
                continue
            for field in ("count", "bytes"):
                want = ref[key].get(field, 0)
                got = rec[field]
                if want and abs(got - want) * 100 > tol * want:
                    emit("spmd-comms-budget", name,
                         f"collective '{key}' {field} drifted: {got} vs "
                         f"budget {want} (±{tol}%) — regen with "
                         f"`bin/dst lint --update-budgets` if "
                         f"intentional")
                elif not want and got:
                    emit("spmd-comms-budget", name,
                         f"collective '{key}' {field} now {got} vs "
                         f"budgeted 0 — regen with "
                         f"`bin/dst lint --update-budgets` if "
                         f"intentional")
        for key in sorted(ref):
            if key not in inv:
                emit("spmd-comms-budget", name,
                     f"budgeted collective '{key}' disappeared from the "
                     f"trace — structure changed; regen with "
                     f"`bin/dst lint --update-budgets` if intentional")
    # budgeted entries that were not traced at all fail loudly, like the
    # jaxpr pass's arm-drop guard
    for name in sorted(entries):
        if name not in reports:
            findings.append(Finding(
                "spmd-comms-budget", f"<spmd:{name}>", 1, 0,
                "budgeted SPMD entry point was NOT traced this run — "
                "fix the entry registry or re-anchor with "
                "`bin/dst lint --update-budgets`"))
    return findings


def run_spmd_pass(budgets_path) -> List[Finding]:
    return check_reports(trace_spmd_entry_points(),
                         load_budgets(budgets_path))


def inventory_summary(reports: Dict[str, SpmdReport]) -> Dict[str, Any]:
    """Per-entry {per_axis: {axes: {count, bytes}}, total_bytes} — the
    compact shape bench.py embeds into MULTICHIP_*.json artifacts."""
    out: Dict[str, Any] = {}
    for name, rep in sorted(reports.items()):
        if rep.error is not None:
            out[name] = {"error": rep.error}
            continue
        per_axis: Dict[str, Dict[str, int]] = {}
        total = 0
        for ev in rep.events:
            axes = "+".join(ev.axes) or "<none>"
            rec = per_axis.setdefault(axes, {"count": 0, "bytes": 0})
            rec["count"] += ev.count
            rec["bytes"] += ev.bytes
            total += ev.bytes
        out[name] = {"per_axis": per_axis, "total_wire_bytes": total,
                     "collectives": rep.inventory()}
    return out
