"""dstlint — the framework's JAX/TPU invariant checker.

Four backends behind one finding stream:

- **AST pass** (:mod:`.astpass`): framework-specific rules over the
  package source — the ``utils/jax_compat`` seam, host syncs inside
  jitted code, recompile hazards, Pallas kernel hygiene, in-place
  argument mutation, buffer-donation checks on the serving entry
  points, and silently-swallowed exceptions in the serving/runtime/comm
  fault paths. Pure ``ast``, no jax import, runs in milliseconds.
- **jaxpr pass** (:mod:`.jaxprpass`): abstractly traces the registered
  serving entry points (paged decode step, prefill bucket,
  ``copy_pool_blocks``) and fails on callback/transfer primitives in
  their jaxprs, on a missing ``pallas_call`` in the Pallas arm (silent
  fallback to the reference gather), and on equation-count drift beyond
  the checked-in budgets (``tools/dstlint/jaxpr_budgets.json``).
- **SPMD pass** (:mod:`.spmdpass`): traces the sharded training and
  serving entry points under abstract multi-device meshes (no TPU
  required), inventories every collective by mesh axis / dtype /
  per-device wire bytes (the shared ``comm/collective_cost.py``
  arithmetic), pins the inventory in
  ``tools/dstlint/comms_budgets.json``, and fires on implicit
  collectives, comms-budget drift, accidental full replication,
  over-wide reduction dtypes, wrong-axis psums inside ``shard_map``
  bodies, and unbudgeted collectives inside decode ``while_loop``s.
- **memory pass** (:mod:`.mempass`): linear-scan liveness over the
  same abstractly-traced entry points, computing deterministic
  peak-live-bytes per program (donation aliasing, scan/while
  carried-buffer reuse, per-shard input sizes) pinned in
  ``tools/dstlint/mem_budgets.json``; a static per-``pallas_call``
  VMEM estimator with dtype-tile alignment checks; a dead-donation
  verifier; and a configurable per-device HBM OOM-risk cap.

CLI: ``bin/dst lint`` (see :mod:`.cli`); library entry:
:func:`run_lint`. Rule catalog: ``docs/LINT.md``.
"""

from deepspeed_tpu.tools.dstlint.core import (  # noqa: F401
    Baseline, Finding, LintConfig, load_baseline, run_lint,
)
from deepspeed_tpu.tools.dstlint.astpass import AST_RULES  # noqa: F401
