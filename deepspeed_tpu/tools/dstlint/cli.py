"""``bin/dst lint`` — CLI for the dstlint analyzer.

Exit codes: 0 clean (baselined findings do not fail the run), 1
non-baselined findings, 2 internal error. ``--format json`` is the
machine interface consumed by the tier-1 pytest wrapper
(tests/unit/test_dstlint.py).
"""

import argparse
import json
import os
import sys
import tempfile
import traceback
from typing import List, Optional, Tuple

from deepspeed_tpu.tools.dstlint import core
from deepspeed_tpu.tools.dstlint.astpass import AST_RULES
from deepspeed_tpu.tools.dstlint.concpass import CONC_RULES
from deepspeed_tpu.tools.dstlint.jaxprpass import JAXPR_RULES
from deepspeed_tpu.tools.dstlint.mempass import MEM_RULES
from deepspeed_tpu.tools.dstlint.spmdpass import SPMD_RULES

ALL_RULES = tuple(AST_RULES) + tuple(CONC_RULES) + tuple(JAXPR_RULES) \
    + tuple(SPMD_RULES) + tuple(MEM_RULES)


def _repo_root() -> str:
    import deepspeed_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(deepspeed_tpu.__file__)))


def _default_targets(root: str) -> List[str]:
    return [os.path.join(root, "deepspeed_tpu")]


def _iter_py_files(targets: List[str], root: str
                   ) -> List[Tuple[str, str]]:
    """(repo-relative posix path, source) for every .py under targets."""
    out = []
    for target in targets:
        target = os.path.abspath(target)
        if os.path.isfile(target):
            paths = [target]
        else:
            paths = []
            for dirpath, dirnames, filenames in os.walk(target):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                paths.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        for p in sorted(paths):
            rel = os.path.relpath(p, root).replace(os.sep, "/")
            try:
                with open(p, encoding="utf-8") as f:
                    out.append((rel, f.read()))
            except (OSError, UnicodeDecodeError) as e:
                print(f"dstlint: skipping unreadable {rel}: {e}",
                      file=sys.stderr)
    return out


def build_parser() -> argparse.ArgumentParser:
    rule_catalog = (
        "rule ids — AST: " + ", ".join(AST_RULES) +
        "; conc: " + ", ".join(CONC_RULES) +
        "; jaxpr: " + ", ".join(JAXPR_RULES) +
        "; spmd: " + ", ".join(SPMD_RULES) +
        "; mem: " + ", ".join(MEM_RULES))
    p = argparse.ArgumentParser(
        prog="dst lint",
        description="static analysis of the framework's JAX/TPU "
                    "invariants (rule catalog: docs/LINT.md)",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog=rule_catalog)
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the "
                        "deepspeed_tpu package)")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to run (default all; "
                        "see the full catalog at the bottom of --help)")
    p.add_argument("--ignore", default="",
                   help="comma-separated rule ids to skip (full catalog "
                        "at the bottom of --help)")
    p.add_argument("--format", choices=("text", "json", "github"),
                   default="text",
                   help="github emits workflow-command annotations "
                        "(::error file=...) for CI")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default "
                        "tools/dstlint/baseline.json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(grandfather everything currently firing)")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr, SPMD AND memory entry-point "
                        "passes (no jax import; milliseconds instead "
                        "of seconds)")
    p.add_argument("--no-spmd", action="store_true",
                   help="skip only the SPMD sharding/collective pass")
    p.add_argument("--no-mem", action="store_true",
                   help="skip only the memory liveness/VMEM pass")
    p.add_argument("--no-conc", action="store_true",
                   help="skip the whole-repo concurrency-safety pass "
                        "(lockset inference, lock-order cycles, "
                        "blocking-under-lock, check-then-act)")
    p.add_argument("--conc-roots", action="store_true",
                   help="print the discovered thread-root table "
                        "(the concurrency pass's thread model) and "
                        "exit")
    p.add_argument("--budgets", default=None,
                   help="jaxpr equation-budget file (default "
                        "tools/dstlint/jaxpr_budgets.json)")
    p.add_argument("--comms-budgets", default=None,
                   help="SPMD collective-inventory budget file (default "
                        "tools/dstlint/comms_budgets.json)")
    p.add_argument("--mem-budgets", default=None,
                   help="peak-memory budget file (default "
                        "tools/dstlint/mem_budgets.json)")
    p.add_argument("--hbm-gb", type=float, default=None,
                   help="per-device HBM cap in GiB for the "
                        "mem-oom-risk rule (overrides the budget "
                        "file's hbm_cap_bytes)")
    p.add_argument("--update-budgets", action="store_true",
                   help="re-trace the entry points and atomically "
                        "rewrite ALL budget files (jaxpr eqn counts + "
                        "spmd comms + peak memory)")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings covered by the baseline")
    return p


def main(argv=None) -> int:
    try:
        return _main(argv)
    except SystemExit:
        raise
    except Exception:
        traceback.print_exc()
        print("dstlint: internal error (this is a dstlint bug, not a "
              "finding)", file=sys.stderr)
        return 2


def _write_budget_file(path: str, payload: dict, root: str) -> None:
    """Atomic per-file rewrite (tmp + os.replace) with a
    changed/unchanged summary line — an interrupted regen can never
    leave the budget files mutually skewed, and the summary shows which
    files a PR actually has to commit."""
    text = json.dumps(payload, indent=1, sort_keys=True) + "\n"
    old: Optional[str] = None
    try:
        with open(path) as f:
            old = f.read()
    except OSError:
        pass
    rel = os.path.relpath(path, root)
    if old == text:
        print(f"dstlint: {rel}: unchanged "
              f"({len(payload.get('entries', {}))} entries)")
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               prefix=os.path.basename(path) + ".")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError as e:
            print(f"dstlint: leaked tmp file {tmp}: {e}",
                  file=sys.stderr)
        raise
    state = "rewritten" if old is not None else "created"
    print(f"dstlint: {rel}: {state} "
          f"({len(payload.get('entries', {}))} entries)")


def _update_budgets(budgets_path, comms_budgets_path, mem_budgets_path,
                    root) -> int:
    from deepspeed_tpu.tools.dstlint import jaxprpass, mempass, spmdpass

    rc = 0
    # trace ALL THREE backends first, write only when every trace ran —
    # a crash mid-regen then leaves all files at their previous
    # (mutually consistent) state instead of a skewed mix
    reports = jaxprpass.trace_entry_points()
    sreports = spmdpass.trace_spmd_entry_points()
    mreports = mempass.trace_mem_entry_points()

    budgets = jaxprpass.budgets_from_reports(reports)
    _write_budget_file(budgets_path, budgets, root)
    for name, rep in sorted(reports.items()):
        status = rep.error or f"{rep.eqns} eqns, " \
                              f"{rep.pallas_calls} pallas_call"
        print(f"  {name}: {status}")
    if any(r.error for r in reports.values()):
        rc = 2

    sbudgets = spmdpass.budgets_from_reports(sreports)
    _write_budget_file(comms_budgets_path, sbudgets, root)
    for name, rep in sorted(sreports.items()):
        if rep.error:
            status = rep.error
        else:
            inv = rep.inventory()
            wire = sum(r["bytes"] for r in inv.values())
            status = f"{len(inv)} collective keys, {wire} wire B"
        print(f"  {name}: {status}")
    if any(r.error for r in sreports.values()):
        rc = 2

    # preserve operator-configured caps across regens (the HBM cap and
    # a per-chip VMEM override are fleet facts, not trace outputs)
    old_mem = mempass.load_budgets(mem_budgets_path) or {}
    mbudgets = mempass.budgets_from_reports(mreports)
    if old_mem.get("hbm_cap_bytes"):
        mbudgets["hbm_cap_bytes"] = old_mem["hbm_cap_bytes"]
    if old_mem.get("vmem_limit_bytes"):
        mbudgets["vmem_limit_bytes"] = old_mem["vmem_limit_bytes"]
    _write_budget_file(mem_budgets_path, mbudgets, root)
    for name, rep in sorted(mreports.items()):
        status = rep.error or f"peak {rep.peak_bytes} B, " \
                              f"{len(rep.pallas)} pallas kernel(s)"
        print(f"  {name}: {status}")
    if any(r.error for r in mreports.values()):
        rc = 2
    return rc


def _main(argv) -> int:
    args = build_parser().parse_args(argv)
    root = _repo_root()
    baseline_path = args.baseline or os.path.join(
        root, "tools", "dstlint", "baseline.json")
    budgets_path = args.budgets or os.path.join(
        root, "tools", "dstlint", "jaxpr_budgets.json")
    comms_budgets_path = args.comms_budgets or os.path.join(
        root, "tools", "dstlint", "comms_budgets.json")
    mem_budgets_path = args.mem_budgets or os.path.join(
        root, "tools", "dstlint", "mem_budgets.json")

    config = core.LintConfig(
        select={r.strip() for r in args.select.split(",") if r.strip()}
        or None,
        ignore={r.strip() for r in args.ignore.split(",") if r.strip()})

    if args.update_budgets:
        return _update_budgets(budgets_path, comms_budgets_path,
                               mem_budgets_path, root)

    files = _iter_py_files(args.paths or _default_targets(root), root)

    if args.conc_roots:
        from deepspeed_tpu.tools.dstlint import concpass

        roots = concpass.thread_roots(files)
        for relpath, qual, kind, line in roots:
            print(f"{relpath}:{line}: {qual} [{kind}]")
        print(f"dstlint: {len(roots)} thread root(s) in "
              f"{len(files)} files")
        return 0

    findings = core.run_lint(files, config)
    backends = ["ast"]

    if not args.no_conc:
        from deepspeed_tpu.tools.dstlint import concpass

        findings.extend(concpass.run_conc_pass(files, config))
        backends.append("conc")

    if not args.no_jaxpr:
        from deepspeed_tpu.tools.dstlint import jaxprpass

        jf = [f for f in jaxprpass.run_jaxpr_pass(budgets_path)
              if config.rule_enabled(f.rule)]
        findings.extend(jf)
        backends.append("jaxpr")

    if not (args.no_jaxpr or args.no_spmd):
        from deepspeed_tpu.tools.dstlint import spmdpass

        sf = [f for f in spmdpass.run_spmd_pass(comms_budgets_path)
              if config.rule_enabled(f.rule)]
        findings.extend(sf)
        backends.append("spmd")

    if not (args.no_jaxpr or args.no_mem):
        from deepspeed_tpu.tools.dstlint import mempass

        cap = int(args.hbm_gb * (1 << 30)) if args.hbm_gb else None
        mf = [f for f in mempass.run_mem_pass(mem_budgets_path,
                                              hbm_cap_bytes=cap)
              if config.rule_enabled(f.rule)]
        findings.extend(mf)
        backends.append("mem")

    line_texts = core.collect_line_texts(files, findings)
    if args.update_baseline:
        baseline = core.Baseline.from_findings(findings, line_texts)
        os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
        core.save_baseline(baseline_path, baseline)
        print(f"dstlint: baselined {len(findings)} finding(s) into "
              f"{os.path.relpath(baseline_path, root)}")
        return 0

    baseline = core.load_baseline(baseline_path)
    findings = baseline.filter(findings, line_texts)
    active = [f for f in findings if not f.baselined]
    shown = findings if args.show_baselined else active

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "files_checked": len(files),
            "backends": backends,
            "findings": [f.to_json() for f in findings],
            "counts": {"active": len(active),
                       "baselined": len(findings) - len(active)},
        }, indent=1))
    elif args.format == "github":
        # GitHub Actions workflow commands: one ::error annotation per
        # active finding (baselined → ::notice so they surface without
        # failing annotations); messages are %-escaped per the spec
        def esc(s: str) -> str:
            return (s.replace("%", "%25").replace("\r", "%0D")
                     .replace("\n", "%0A"))

        for f in shown:
            level = "notice" if f.baselined else "error"
            print(f"::{level} file={esc(f.path)},line={f.line},"
                  f"col={max(f.col, 1)},title=dstlint {esc(f.rule)}"
                  f"::{esc(f.message)}")
        print(f"dstlint: {len(files)} files, {len(active)} finding(s)"
              f" ({len(findings) - len(active)} baselined)")
    else:
        for f in shown:
            print(f.render())
        print(f"dstlint: {len(files)} files, {len(active)} finding(s)"
              f" ({len(findings) - len(active)} baselined)")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
