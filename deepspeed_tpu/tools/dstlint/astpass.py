"""dstlint AST rules — the framework's source-level invariants.

Seven rules (catalog with bad/good examples: ``docs/LINT.md``):

- ``jax-compat-seam``   moved/renamed JAX symbols must route through
  ``utils/jax_compat`` (the seam that revived the engines on jax
  0.4.37) — both imports and attribute uses, plus the retired
  ``with mesh:`` context spelling.
- ``no-host-sync-in-jit``   ``.item()`` / ``float()`` / ``int()`` /
  ``np.asarray`` / ``jax.device_get`` / ``.block_until_ready()`` on
  traced values inside jit/scan/while_loop bodies.
- ``recompile-hazard``   Python ``if``/``assert``/f-strings over traced
  values (concretization → silent retrace per shape), and
  array-building expressions passed in ``static_argnums`` positions.
- ``pallas-kernel-hygiene``   no ``jnp.repeat``, no ``print``, no
  data-dependent Python control flow inside Pallas kernel bodies.
- ``no-arg-mutation``   helpers under ``ops/``/``inference/`` must not
  mutate their inputs in place (the ``retile_gateup_for_fused_mlp``
  purity bug class). Pallas kernels and ``*_ref``/``*_scr`` parameters
  (the Ref mutation protocol) are exempt.
- ``donation-check``   jitted entry points in ``inference/engine.py`` /
  ``runtime/engine.py`` taking pool/cache-sized buffers must donate
  them (``donate_argnums``) or double peak HBM for the workspace.
- ``no-silent-except``   bare/``Exception``-broad handlers in the
  serving/training/comm/monitoring paths (``inference/``, ``runtime/``,
  ``comm/``, ``monitor/``, ``profiling/``, ``observability/``) must
  handle the exception EXPLICITLY
  (bind it and use it — convert to a terminal status, log it — or
  re-raise); a swallowed exception in the fault-tolerance layer turns
  an isolatable failure into silent KV/bookkeeping corruption.

Everything here is a best-effort, zero-false-positive-biased *static*
approximation: function references are resolved lexically (a function
object stored in a dict and jitted later is out of scope), and taint is
a single forward pass per function (parameters of traced functions are
tainted; ``.shape``/``.dtype``/``len()`` launder taint because shapes
are static under tracing).
"""

import ast
from typing import Dict, List, Optional, Set

from deepspeed_tpu.tools.dstlint.core import Finding

# --- rule ids ---------------------------------------------------------------
SEAM = "jax-compat-seam"
HOST_SYNC = "no-host-sync-in-jit"
RECOMPILE = "recompile-hazard"
PALLAS = "pallas-kernel-hygiene"
ARG_MUT = "no-arg-mutation"
DONATION = "donation-check"
SILENT_EXCEPT = "no-silent-except"

AST_RULES = (SEAM, HOST_SYNC, RECOMPILE, PALLAS, ARG_MUT, DONATION,
             SILENT_EXCEPT)

# the one module allowed to touch the moved symbols directly
SEAM_MODULE = "deepspeed_tpu/utils/jax_compat.py"

#: symbols the jax_compat seam owns — exact dotted paths. Prefixes of
#: jax.experimental.{shard_map,pallas} are matched separately so both
#: the module import and any attribute under it are caught.
SEAM_SYMBOLS = {
    "jax.set_mesh": "set_mesh",
    "jax.shard_map": "shard_map",
    "jax.lax.pvary": "varying_cast",
    "jax.lax.pcast": "varying_cast",
    "jax.lax.axis_size": "axis_size",
    "jax.typeof": "vma_of",
    "jax.sharding.get_abstract_mesh": "get_abstract_mesh",
}
SEAM_PREFIXES = {
    "jax.experimental.shard_map": "shard_map",
    "jax.experimental.pallas": "pallas_tpu()",
}

JIT_WRAPPERS = {"jax.jit", "jax.pmap"}
#: traced-callable positions in control-flow combinators
TRACED_ARG_POS = {
    "jax.lax.while_loop": (0, 1),
    "jax.lax.scan": (0,),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.map": (0,),
    "jax.lax.associative_scan": (0,),
    "jax.vmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.eval_shape": (0,),
    "jax.make_jaxpr": (0,),
}

HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
HOST_SYNC_CASTS = {"float", "int", "bool", "complex"}
NUMPY_MATERIALIZERS = {"numpy.asarray", "numpy.array", "numpy.copy"}

#: attribute reads that launder taint — static under tracing
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "aval",
                "itemsize", "weak_type"}
STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr",
                "callable", "id", "range", "enumerate", "zip"}

#: parameter names that identify session-sized device buffers at the
#: serving/training entry points (donation-check)
BUFFER_PARAM_NAMES = {"pools", "pool", "caches", "kv_caches", "kv_pools",
                      "opt_state"}
DONATION_FILES = ("inference/engine.py", "runtime/engine.py")

MUTATING_METHODS = {"append", "extend", "insert", "remove", "clear",
                    "pop", "popitem", "update", "setdefault", "sort",
                    "reverse", "add", "discard"}
#: Pallas Ref / VMEM-scratch naming convention — mutation is the protocol
REF_PARAM_SUFFIXES = ("_ref", "_scr", "refs", "_vmem", "_smem")


def _func_name_parts(node: ast.AST) -> Optional[List[str]]:
    """['jax', 'lax', 'pvary'] for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


class _Scope:
    """One lexical function (or module) scope."""

    def __init__(self, node, parent: Optional["_Scope"]):
        self.node = node
        self.parent = parent
        self.local_funcs: Dict[str, "_FuncInfo"] = {}

    def resolve(self, name: str) -> Optional["_FuncInfo"]:
        scope = self
        while scope is not None:
            info = scope.local_funcs.get(name)
            if info is not None:
                return info
            scope = scope.parent
        return None


class _FuncInfo:
    def __init__(self, node, scope: _Scope, parent: Optional["_FuncInfo"]):
        self.node = node
        self.scope = scope            # scope of the function's BODY
        self.parent = parent
        self.traced = False
        self.kernel = False
        self.jit_calls: List[ast.Call] = []   # jax.jit(...) wrapping this def

    def in_traced_context(self) -> bool:
        info = self
        while info is not None:
            if info.traced or info.kernel:
                return True
            info = info.parent
        return False

    def in_kernel_context(self) -> bool:
        info = self
        while info is not None:
            if info.kernel:
                return True
            info = info.parent
        return False


class ModuleAnalyzer:
    def __init__(self, tree: ast.Module, relpath: str):
        self.tree = tree
        self.relpath = relpath
        self.findings: List[Finding] = []
        self.aliases: Dict[str, str] = {}
        self.module_scope = _Scope(tree, None)
        self.funcs: List[_FuncInfo] = []
        self._scope_of_body: Dict[ast.AST, _Scope] = {tree: self.module_scope}

    # --- shared resolution ---------------------------------------------------
    def _collect_aliases(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def dotted(self, node: ast.AST) -> Optional[str]:
        parts = _func_name_parts(node)
        if not parts:
            return None
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    def emit(self, rule: str, node: ast.AST, message: str):
        self.findings.append(Finding(
            rule, self.relpath, getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0), message))

    # --- pass 1: scopes + function table ------------------------------------
    def _build_scopes(self):
        def visit(node, scope: _Scope, parent_func: Optional[_FuncInfo]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    body_scope = _Scope(child, scope)
                    info = _FuncInfo(child, body_scope, parent_func)
                    self.funcs.append(info)
                    self._scope_of_body[child] = body_scope
                    if not isinstance(child, ast.Lambda):
                        scope.local_funcs[child.name] = info
                    visit(child, body_scope, info)
                elif isinstance(child, ast.ClassDef):
                    # methods live in the class "scope"; resolution-wise a
                    # plain nested scope is close enough for this pass
                    class_scope = _Scope(child, scope)
                    self._scope_of_body[child] = class_scope
                    visit(child, class_scope, parent_func)
                else:
                    visit(child, scope, parent_func)

        visit(self.tree, self.module_scope, None)

    # --- pass 2: mark traced / kernel functions ------------------------------
    def _callable_arg_to_info(self, arg: ast.AST,
                              scope: _Scope) -> Optional[_FuncInfo]:
        """Resolve a callable argument: a local name, a lambda, or
        functools.partial(name, ...)."""
        if isinstance(arg, ast.Lambda):
            return next((f for f in self.funcs if f.node is arg), None)
        if isinstance(arg, ast.Name):
            return scope.resolve(arg.id)
        if isinstance(arg, ast.Call):
            d = self.dotted(arg.func)
            if d in ("functools.partial", "partial") and arg.args:
                return self._callable_arg_to_info(arg.args[0], scope)
        return None

    def _is_partial_jit(self, node: ast.AST) -> bool:
        """functools.partial(jax.jit, ...) — a curried jit wrapper."""
        return (isinstance(node, ast.Call)
                and self.dotted(node.func) in ("functools.partial",
                                               "partial")
                and bool(node.args)
                and self.dotted(node.args[0]) in JIT_WRAPPERS)

    def _mark_functions(self):
        # decorators
        for info in self.funcs:
            if isinstance(info.node, ast.Lambda):
                continue
            for dec in info.node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                d = self.dotted(target)
                if d in JIT_WRAPPERS:
                    info.traced = True
                    # record BARE @jax.jit too: donation-check reads a
                    # non-Call entry as "jit with no kwargs" (nothing
                    # donated) — the most idiomatic way to miss donation
                    info.jit_calls.append(dec)
                elif d in ("functools.partial", "partial") \
                        and isinstance(dec, ast.Call) and dec.args \
                        and self.dotted(dec.args[0]) in JIT_WRAPPERS:
                    info.traced = True
                    info.jit_calls.append(dec)

        # call sites: jax.jit(f), lax.while_loop(cond, body, ...),
        # pl.pallas_call(kernel | functools.partial(kernel, ...), ...),
        # functools.partial(jax.jit, donate_argnums=...)(f) inline or
        # through a local alias — the partial call carries the jit
        # kwargs donation-check must read
        partial_jit_aliases: Dict[str, ast.Call] = {}
        for node, scope in self._walk_with_scopes():
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and self._is_partial_jit(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        partial_jit_aliases[t.id] = node.value
                continue
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Call) \
                    and self._is_partial_jit(node.func) and node.args:
                info = self._callable_arg_to_info(node.args[0], scope)
                if info is not None:
                    info.traced = True
                    info.jit_calls.append(node.func)
                continue
            if isinstance(node.func, ast.Name) \
                    and node.func.id in partial_jit_aliases and node.args:
                info = self._callable_arg_to_info(node.args[0], scope)
                if info is not None:
                    info.traced = True
                    info.jit_calls.append(
                        partial_jit_aliases[node.func.id])
                continue
            d = self.dotted(node.func)
            if d is None:
                continue
            if d in JIT_WRAPPERS and node.args:
                info = self._callable_arg_to_info(node.args[0], scope)
                if info is not None:
                    info.traced = True
                    info.jit_calls.append(node)
            elif d in TRACED_ARG_POS:
                for pos in TRACED_ARG_POS[d]:
                    if pos < len(node.args):
                        info = self._callable_arg_to_info(
                            node.args[pos], scope)
                        if info is not None:
                            info.traced = True
            elif d.endswith(".pallas_call") or d == "pallas_call":
                if node.args:
                    info = self._callable_arg_to_info(node.args[0], scope)
                    if info is not None:
                        info.kernel = True

    def _walk_with_scopes(self):
        """(node, enclosing_scope) for every node — scope meaning the
        innermost function/module body the node sits in."""
        def visit(node, scope):
            for child in ast.iter_child_nodes(node):
                child_scope = self._scope_of_body.get(child, scope)
                yield child, child_scope
                yield from visit(child, child_scope)

        yield from visit(self.tree, self.module_scope)

    # --- rules ---------------------------------------------------------------
    def run(self) -> List[Finding]:
        self._collect_aliases()
        self._build_scopes()
        self._mark_functions()
        if self.relpath != SEAM_MODULE:
            self._rule_seam()
        self._rule_traced_bodies()
        if self.relpath.startswith(("deepspeed_tpu/ops/",
                                    "deepspeed_tpu/inference/")):
            self._rule_arg_mutation()
        if self.relpath.startswith(("deepspeed_tpu/inference/",
                                    "deepspeed_tpu/runtime/",
                                    "deepspeed_tpu/comm/",
                                    "deepspeed_tpu/monitor/",
                                    "deepspeed_tpu/profiling/",
                                    "deepspeed_tpu/observability/")):
            self._rule_silent_except()
        if self.relpath.endswith(DONATION_FILES):
            self._rule_donation()
        self.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return self.findings

    # jax-compat-seam ---------------------------------------------------------
    def _seam_hit(self, dotted: str) -> Optional[str]:
        if dotted in SEAM_SYMBOLS:
            return SEAM_SYMBOLS[dotted]
        for prefix, repl in SEAM_PREFIXES.items():
            if dotted == prefix or dotted.startswith(prefix + "."):
                return repl
        return None

    def _rule_seam(self):
        seen_lines: Set[int] = set()

        def hit(node, dotted):
            repl = self._seam_hit(dotted)
            if repl is not None and node.lineno not in seen_lines:
                seen_lines.add(node.lineno)
                self.emit(SEAM, node,
                          f"direct use of seam-covered symbol "
                          f"'{dotted}' — import "
                          f"'{repl}' from deepspeed_tpu.utils.jax_compat "
                          f"instead (one-file jax version bumps)")

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    hit(node, a.name)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    hit(node, f"{node.module}.{a.name}")
            elif isinstance(node, ast.Attribute):
                d = self.dotted(node)
                if d is None:
                    continue
                parts = _func_name_parts(node)
                if d in SEAM_SYMBOLS:
                    # exact moved symbols (lax.pvary, jax.set_mesh, ...)
                    # flag through any alias
                    hit(node, d)
                elif parts and parts[0] == "jax":
                    # prefix families (pallas, experimental.shard_map):
                    # alias USES are consequences of an already-flagged
                    # import — only literal jax.experimental... chains
                    # flag here
                    hit(node, d)
            elif isinstance(node, ast.With):
                # retired `with mesh:` context spelling — a bare Mesh as
                # context manager deprecates; route through set_mesh()
                for item in node.items:
                    ctx = item.context_expr
                    parts = _func_name_parts(ctx)
                    if parts and parts[-1] in ("mesh", "_mesh") \
                            and not isinstance(ctx, ast.Call):
                        self.emit(
                            SEAM, ctx,
                            "'with mesh:' is the retired context "
                            "spelling — use 'with set_mesh(mesh):' from "
                            "deepspeed_tpu.utils.jax_compat")

    # traced-body rules: host syncs, recompile hazards, kernel hygiene -------
    def _rule_traced_bodies(self):
        roots = [f for f in self.funcs
                 if (f.traced or f.kernel)
                 and (f.parent is None or not f.parent.in_traced_context())]
        for info in roots:
            # taint seeds ONLY from params of functions the tracer calls
            # directly (jit roots, while_loop/scan/cond bodies, kernels) —
            # a nested helper invoked manually may take static values
            # (dict keys, config) and tainting its params would flag
            # legitimate host math; its closure over traced values is
            # still tracked via the inherited environment.
            self._check_traced_function(info, self._initial_taint(info))
        # static_argnums hazards live at the jit CALL, not inside a body
        self._rule_static_argnums()

    @staticmethod
    def _initial_taint(info: _FuncInfo) -> Set[str]:
        """Positional/vararg params are traced values; keyword-only
        params are the functools.partial static-config idiom."""
        node = info.node
        args = node.args
        names = [a.arg for a in args.args]
        names += [a.arg for a in getattr(args, "posonlyargs", [])]
        if args.vararg:
            names.append(args.vararg.arg)
        return {n for n in names if n not in ("self", "cls")}

    def _check_traced_function(self, info: _FuncInfo, taint: Set[str]):
        kernel = info.in_kernel_context()
        walker = _TracedBodyWalker(self, info, set(taint), kernel)
        body = info.node.body
        if isinstance(info.node, ast.Lambda):
            walker.visit(info.node.body)
        else:
            for stmt in body:
                walker.visit(stmt)
        # nested defs inherit the enclosing taint environment (closures);
        # their OWN params seed taint only when the tracer calls them
        # directly (marked traced/kernel — combinator bodies, jit roots)
        for child in self.funcs:
            if child.parent is info:
                child_taint = set(walker.taint)
                if child.traced or child.kernel:
                    child_taint |= self._initial_taint(child)
                self._check_traced_function(child, child_taint)

    def _rule_static_argnums(self):
        """Array-building expressions passed in static positions: a
        jnp/np-array static arg is unhashable → TypeError at best, a
        per-call recompile with weird cache keys at worst."""
        for info in self.funcs:
            for call in info.jit_calls:
                keywords = call.keywords if isinstance(call, ast.Call) \
                    else []
                static_kw = next((k for k in keywords
                                  if k.arg == "static_argnums"), None)
                if static_kw is None:
                    continue
                positions = _const_int_tuple(static_kw.value)
                if positions is None:
                    continue
                # check call sites of the jitted value is out of scope;
                # instead flag static positions whose PARAM has an
                # array-ish buffer name — those are traced by contract
                params = [a.arg for a in info.node.args.args]
                for pos in positions:
                    # multi-character buffer names only: single-letter
                    # params (k, x, ...) are idiomatic STATIC scalars in
                    # jit signatures and must not collide
                    if pos < len(params) and (
                            params[pos] in BUFFER_PARAM_NAMES
                            or params[pos] in ("tokens", "ids", "logits")):
                        self.emit(
                            RECOMPILE, call,
                            f"static_argnums includes "
                            f"'{params[pos]}' which names a traced "
                            f"array — unhashable at call time or a "
                            f"recompile per distinct buffer")

    # no-arg-mutation ---------------------------------------------------------
    def _rule_arg_mutation(self):
        for info in self.funcs:
            if isinstance(info.node, ast.Lambda) or info.in_kernel_context():
                continue
            params = self._initial_taint(info)
            params = {p for p in params
                      if not p.endswith(REF_PARAM_SUFFIXES)}
            if not params:
                continue
            walker = _ArgMutationWalker(self, params)
            for stmt in info.node.body:
                walker.visit(stmt)

    # no-silent-except --------------------------------------------------------
    _BROAD_EXC = {"Exception", "BaseException", "builtins.Exception",
                  "builtins.BaseException"}

    def _is_broad_handler(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:                  # bare `except:`
            return True
        types = t.elts if isinstance(t, ast.Tuple) else [t]
        for node in types:
            d = self.dotted(node)
            if d in self._BROAD_EXC:
                return True
        return False

    def _rule_silent_except(self):
        """Broad handlers (`except:`, `except Exception`) in the serving
        hot paths must be EXPLICIT about the fault: either re-raise
        somewhere in the handler, or bind the exception and actually use
        it (converting to a terminal status / report). A handler that
        does neither swallows executor/bookkeeping failures the
        fault-tolerance layer exists to surface."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not self._is_broad_handler(handler):
                    continue
                reraises = any(isinstance(n, ast.Raise)
                               for stmt in handler.body
                               for n in ast.walk(stmt))
                uses_exc = handler.name is not None and any(
                    isinstance(n, ast.Name) and n.id == handler.name
                    for stmt in handler.body
                    for n in ast.walk(stmt))
                if reraises or uses_exc:
                    continue
                what = "bare `except:`" if handler.type is None else \
                    "`except Exception`"
                self.emit(
                    SILENT_EXCEPT, handler,
                    f"{what} swallows the exception silently in a "
                    f"serving/training/comm path — bind it (`except "
                    f"Exception as e:`) and convert it to an explicit "
                    f"outcome (terminal status, log, report), or "
                    f"re-raise")

    # donation-check ----------------------------------------------------------
    def _rule_donation(self):
        for info in self.funcs:
            if isinstance(info.node, ast.Lambda):
                continue
            params = [a.arg for a in info.node.args.args]
            buffer_pos = [i for i, p in enumerate(params)
                          if p in BUFFER_PARAM_NAMES]
            if not buffer_pos:
                continue
            for call in info.jit_calls:
                donated = set()
                keywords = call.keywords if isinstance(call, ast.Call) \
                    else []
                for k in keywords:
                    if k.arg == "donate_argnums":
                        vals = _const_int_tuple(k.value)
                        if vals is None:     # dynamic spec: trust it
                            donated = set(buffer_pos)
                        else:
                            donated |= set(vals)
                    elif k.arg == "donate_argnames":
                        names = _const_str_tuple(k.value)
                        if names is None:    # dynamic spec: trust it
                            donated = set(buffer_pos)
                        else:
                            donated |= {i for i, p in enumerate(params)
                                        if p in names}
                missing = [params[i] for i in buffer_pos
                           if i not in donated]
                if missing:
                    self.emit(
                        DONATION, call,
                        f"jit of '{info.node.name}' does not donate "
                        f"buffer argument(s) {missing} — without "
                        f"donate_argnums the pool/cache is copied, "
                        f"doubling its HBM footprint per step")


def _const_str_tuple(node: ast.AST) -> Optional[tuple]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _const_int_tuple(node: ast.AST) -> Optional[tuple]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


class _TracedBodyWalker(ast.NodeVisitor):
    """Host-sync / recompile-hazard / kernel-hygiene checks over ONE
    function body, with a single-pass forward taint approximation.
    Does not descend into nested function defs (the analyzer re-enters
    them with the inherited taint environment)."""

    def __init__(self, mod: ModuleAnalyzer, info: _FuncInfo,
                 taint: Set[str], kernel: bool):
        self.mod = mod
        self.info = info
        self.taint = taint
        self.kernel = kernel

    # --- taint ---------------------------------------------------------------
    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.taint
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            base = node.value
            # x.shape[0] is static even though x is traced
            if isinstance(base, ast.Attribute) and base.attr in STATIC_ATTRS:
                return False
            return self.is_tainted(base) or self.is_tainted(node.slice)
        if isinstance(node, ast.Call):
            d = self.mod.dotted(node.func)
            if d in STATIC_CALLS or (d or "").split(".")[-1] in STATIC_CALLS:
                return False
            if isinstance(node.func, ast.Attribute) \
                    and self.is_tainted(node.func.value):
                return True
            return any(self.is_tainted(a) for a in node.args) or \
                any(self.is_tainted(k.value) for k in node.keywords)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return self.is_tainted(node.left) or \
                any(self.is_tainted(c) for c in node.comparators)
        if isinstance(node, (ast.BoolOp,)):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        return False

    def _assign_names(self, target: ast.AST, tainted: bool):
        if isinstance(target, ast.Name):
            if tainted:
                self.taint.add(target.id)
            else:
                self.taint.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_names(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._assign_names(target.value, tainted)

    # --- traversal -----------------------------------------------------------
    def visit_FunctionDef(self, node):      # noqa: N802 - handled separately
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Assign(self, node):
        self.generic_visit(node)
        tainted = self.is_tainted(node.value)
        for t in node.targets:
            self._assign_names(t, tainted)

    def visit_AugAssign(self, node):
        self.generic_visit(node)
        if self.is_tainted(node.value):
            self._assign_names(node.target, True)

    def visit_AnnAssign(self, node):
        self.generic_visit(node)
        if node.value is not None:
            self._assign_names(node.target, self.is_tainted(node.value))

    def visit_If(self, node):
        if self.is_tainted(node.test):
            rule = PALLAS if self.kernel else RECOMPILE
            what = "data-dependent Python `if` in a Pallas kernel body " \
                   "(use pl.when / jnp.where)" if self.kernel else \
                   "Python `if` on a traced value concretizes at trace " \
                   "time (TracerBoolConversionError or a recompile per " \
                   "value) — use jnp.where / lax.cond"
            self.mod.emit(rule, node, what)
        self.generic_visit(node)

    def visit_While(self, node):
        if self.is_tainted(node.test):
            rule = PALLAS if self.kernel else RECOMPILE
            self.mod.emit(rule, node,
                          "Python `while` over a traced value — use "
                          "lax.while_loop" if not self.kernel else
                          "data-dependent Python `while` in a Pallas "
                          "kernel body — use lax.fori_loop/pl.when")
        self.generic_visit(node)

    def visit_For(self, node):
        if self.kernel and self.is_tainted(node.iter):
            self.mod.emit(PALLAS, node,
                          "data-dependent Python `for` in a Pallas "
                          "kernel body — iteration counts must be static")
        self.generic_visit(node)

    def visit_Assert(self, node):
        if self.is_tainted(node.test):
            self.mod.emit(RECOMPILE, node,
                          "`assert` on a traced value concretizes at "
                          "trace time — use checkify or move the check "
                          "outside the jitted function")
        self.generic_visit(node)

    def visit_JoinedStr(self, node):
        for v in node.values:
            if isinstance(v, ast.FormattedValue) and \
                    self.is_tainted(v.value):
                self.mod.emit(RECOMPILE, node,
                              "f-string over a traced value (e.g. a "
                              "shape-derived cache key built at trace "
                              "time) concretizes the tracer")
                break
        self.generic_visit(node)

    def visit_Call(self, node):
        d = self.mod.dotted(node.func)
        # host syncs -----------------------------------------------------
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in HOST_SYNC_METHODS \
                and not node.args \
                and self.is_tainted(node.func.value):
            self.mod.emit(HOST_SYNC, node,
                          f".{node.func.attr}() inside a jitted/traced "
                          f"body is a device->host sync (or a trace "
                          f"error) — keep the value on device")
        elif d is not None and d in ("jax.device_get",):
            self.mod.emit(HOST_SYNC, node,
                          "jax.device_get inside a jitted/traced body "
                          "is a device->host sync — keep the value on "
                          "device")
        elif d in NUMPY_MATERIALIZERS \
                and any(self.is_tainted(a) for a in node.args):
            self.mod.emit(HOST_SYNC, node,
                          f"{d.replace('numpy', 'np')} on a traced "
                          f"value materializes on host — use jnp")
        elif d in HOST_SYNC_CASTS and len(node.args) == 1 \
                and self.is_tainted(node.args[0]):
            self.mod.emit(HOST_SYNC, node,
                          f"{d}() on a traced value forces a host sync "
                          f"(ConcretizationTypeError under jit) — keep "
                          f"math in jnp")
        # kernel hygiene --------------------------------------------------
        if self.kernel:
            if d is not None and (d == "jax.numpy.repeat"
                                  or d == "numpy.repeat"):
                self.mod.emit(PALLAS, node,
                              "jnp.repeat inside a Pallas kernel "
                              "materializes the broadcast — index a "
                              "reshaped view instead (GQA: [n_kv, rep, "
                              "hd])")
            elif d == "print":
                self.mod.emit(PALLAS, node,
                              "print() in a Pallas kernel body — use "
                              "pl.debug_print")
        self.generic_visit(node)


class _ArgMutationWalker(ast.NodeVisitor):
    """In-place mutation of function parameters (helpers must be pure)."""

    def __init__(self, mod: ModuleAnalyzer, params: Set[str]):
        self.mod = mod
        self.params = set(params)

    def _param_base(self, node: ast.AST) -> Optional[str]:
        """The parameter name if ``node`` is (a subscript chain over) a
        bare parameter; attribute access (obj.field) is NOT flagged —
        mutating self/attr state is a different contract."""
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Name) and node.id in self.params:
            return node.id
        return None

    def visit_FunctionDef(self, node):      # nested defs: own parameters
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Assign(self, node):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                p = self._param_base(t)
                if p is not None:
                    self.mod.emit(
                        ARG_MUT, node,
                        f"in-place write into parameter '{p}' — helpers "
                        f"must not mutate their inputs (return a new "
                        f"value; copy-on-write if cheap)")
            elif isinstance(t, ast.Name) and t.id in self.params:
                # rebinding shadows the param: later subscript writes hit
                # the local, which is fine
                self.params.discard(t.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Subscript):
            p = self._param_base(node.target)
            if p is not None:
                self.mod.emit(ARG_MUT, node,
                              f"in-place augmented write into parameter "
                              f"'{p}' — helpers must not mutate inputs")
        elif isinstance(node.target, ast.Name):
            self.params.discard(node.target.id)
        self.generic_visit(node)

    def visit_Delete(self, node):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                p = self._param_base(t)
                if p is not None:
                    self.mod.emit(ARG_MUT, node,
                                  f"del on parameter '{p}' contents — "
                                  f"helpers must not mutate inputs")
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS:
            p = self._param_base(f.value)
            if p is not None:
                self.mod.emit(ARG_MUT, node,
                              f"'{p}.{f.attr}(...)' mutates parameter "
                              f"'{p}' in place — helpers must not "
                              f"mutate inputs")
        self.generic_visit(node)


def analyze_module(tree: ast.Module, relpath: str) -> List[Finding]:
    return ModuleAnalyzer(tree, relpath).run()
