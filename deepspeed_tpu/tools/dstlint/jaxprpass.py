"""dstlint jaxpr pass — trace the serving entry points, check what XLA
will actually see.

The AST rules catch what the *source* says; this pass catches what the
*trace* contains. It abstractly traces (``jax.make_jaxpr`` — no device
execution, no real weights) the registered serving entry points over a
tiny Llama config:

- the paged DECODE step (``PagedServeExecutor._build_decode_fn``), on
  both attention arms,
- a PREFILL bucket (``_build_prefill_fn(PROMPT_BUCKET)``),
- the unified RAGGED STEP (``_build_ragged_fn`` — chunked-prefill
  serving: mixed prefill-chunk + decode batches in one program), on
  both arms and over BOTH pool layouts (dense and int8),
- the prefix-cache ``copy_pool_blocks`` program,
- the tiered-KV spill/restore programs (``gather_pool_blocks`` /
  ``scatter_pool_blocks``) over BOTH pool layouts (dense 2-tuple and
  int8 4-tuple) — the async restore path in particular must stay free
  of host-sync/callback primitives (the device_put happens OUTSIDE the
  jit, at begin_restore; a device_put inside the scatter would
  serialize the transfer the tier exists to overlap),

and fails on:

- ``jaxpr-forbidden-primitive``: callback/host-transfer primitives in a
  hot serving jaxpr (a ``pure_callback`` or ``device_put`` smuggled into
  the decode loop is a per-step host round-trip — the regression class
  DeepSpeed-Inference calls out as dominating serving latency);
- ``jaxpr-kernel-arm``: the Pallas arm tracing WITHOUT a
  ``pallas_call`` equation — i.e. the kernel silently fell back to the
  reference gather (wrapper dispatch drift, version-gated imports).
  Applies to decode, prefill-bucket AND ragged-step programs: since
  the unified ragged kernel landed there is no "prefill T>1 falls
  back by design" exemption anymore;
- ``jaxpr-budget``: total equation count drifting beyond the
  checked-in budget (``tools/dstlint/jaxpr_budgets.json``) — catches
  accidental de-dup regressions (e.g. a loop-invariant dequant
  re-materialized per decode step) and silent fallback in either
  direction. Regenerate after intentional changes:
  ``bin/dst lint --update-budgets``.

These entry points are the OBSERVABILITY gate too (docs/
OBSERVABILITY.md): the dstrace tracer/metrics instrumentation drives
exactly these builders from the scheduler's host side, so the budgets
above prove tracing adds ZERO traced equations — and
``tests/unit/test_observability.py`` pins the fresh trace equal to the
checked-in numbers exactly (no tolerance), so even a one-equation leak
of instrumentation into a compiled program fails tier-1.
"""

import contextlib
import dataclasses
import json
from collections import Counter
from typing import Dict, List, Optional

from deepspeed_tpu.tools.dstlint.core import Finding

JAXPR_RULES = ("jaxpr-forbidden-primitive", "jaxpr-kernel-arm",
               "jaxpr-budget")

#: primitive names that must never appear in a serving jaxpr — host
#: callbacks and explicit transfers are per-step host round-trips
FORBIDDEN_SUBSTRINGS = ("callback",)
FORBIDDEN_EXACT = {"outside_call", "host_local_array_to_global_array",
                   "device_put", "infeed", "outfeed"}

DEFAULT_TOLERANCE_PCT = 25

# tiny serving shape — big enough to exercise GQA + multi-block tables
_SLOTS = 2
_WIDTH = 4
_BLOCK = 8
_NUM_BLOCKS = 9
_CHUNK = 4
# ragged-step query capacity (chunked prefill): > 1 so the traced
# program exercises the mixed prefill-chunk + decode shape
_RAGGED_T = 8


@dataclasses.dataclass
class EntryReport:
    name: str
    eqns: int
    primitives: Dict[str, int]
    pallas_calls: int
    error: Optional[str] = None


def _count_jaxpr(jaxpr, counter: Counter) -> int:
    """Total equation count, recursing into call/control-flow/pallas
    sub-jaxprs; fills ``counter`` with primitive names."""
    total = 0
    for eqn in jaxpr.eqns:
        counter[eqn.primitive.name] += 1
        total += 1
        for v in eqn.params.values():
            total += _count_sub(v, counter)
    return total


def _count_sub(v, counter: Counter) -> int:
    import jax

    core = jax.core if hasattr(jax, "core") else None
    if core is not None and isinstance(v, core.ClosedJaxpr):
        return _count_jaxpr(v.jaxpr, counter)
    if core is not None and isinstance(v, core.Jaxpr):
        return _count_jaxpr(v, counter)
    if isinstance(v, (list, tuple)):
        return sum(_count_sub(x, counter) for x in v)
    return 0


def _abstract_serving_pieces(arm: str):
    """(decode_jit, decode_avals, prefill_jit, prefill_avals, copy_jit,
    copy_avals) for a tiny Llama over the given attention arm — all
    arguments are ShapeDtypeStructs, nothing touches a device."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.engine import (
        PROMPT_BUCKET, PagedServeExecutor, resolve_paged_decoder,
    )
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel
    from deepspeed_tpu.ops.paged_attention import copy_pool_blocks

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaModel(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jnp.zeros((1, 8), jnp.int32)
    raw_params = jax.eval_shape(
        lambda r, x: model.init(r, x)["params"], rng, ids)
    paged_apply, init_pools, transform, _ = resolve_paged_decoder(
        cfg, attn_kernel=arm)
    params = raw_params if transform is None else \
        jax.eval_shape(transform, raw_params)
    pools = jax.eval_shape(
        lambda: init_pools(cfg, _NUM_BLOCKS, _BLOCK, jnp.float32))

    ex = PagedServeExecutor(paged_apply, None, None, cfg,
                            contextlib.nullcontext, num_slots=_SLOTS,
                            decode_chunk=_CHUNK)
    decode_jit = ex._build_decode_fn(_CHUNK)
    prefill_jit = ex._build_prefill_fn(PROMPT_BUCKET)

    sds = jax.ShapeDtypeStruct
    B, W = _SLOTS, _WIDTH
    i32, f32, u32 = jnp.int32, jnp.float32, jnp.uint32
    decode_avals = (
        params, sds((B,), i32), pools, sds((B, W), i32), sds((B,), i32),
        sds((B,), i32), sds((), i32), sds((B, 2), u32), sds((B,), f32),
        sds((B,), i32), sds((B,), f32), sds((B,), i32))
    prefill_avals = (
        params, sds((1, PROMPT_BUCKET), i32), pools, sds((1, W), i32),
        sds((), i32), sds((), i32), sds((2,), u32), sds((), f32),
        sds((), i32), sds((), f32))
    copy_jit = jax.jit(copy_pool_blocks, donate_argnums=(0,))
    copy_avals = (pools, sds((1,), i32), sds((1,), i32))
    return (decode_jit, decode_avals, prefill_jit, prefill_avals,
            copy_jit, copy_avals)


def _ragged_serving_pieces(arm: str, int8: bool = False,
                           verify: bool = False):
    """(ragged_jit, avals) for the unified RAGGED-STEP program
    (``PagedServeExecutor._build_ragged_fn`` — chunked-prefill
    serving): ONE ``[B, T_cap]`` shape packs prefill chunks of any
    prompt length plus every decode slot, so this entry point is the
    whole chunked session's hot program. ``int8`` traces it over the
    quant.kv_cache pool layout through the fused Llama path (the only
    int8-KV-eligible decoder). ``verify`` traces the SPECULATIVE
    variant instead (``_build_ragged_verify_fn`` — same attention body
    plus in-device draft verification; one extra ``spec_lens`` [B]
    operand), the hot program of a speculation-enabled session."""
    import contextlib as _ctx

    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.engine import (
        PagedServeExecutor, resolve_paged_decoder,
    )
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=int8)
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    raw_params = jax.eval_shape(
        lambda r, x: model.init(r, x)["params"], jax.random.PRNGKey(0),
        ids)
    paged_apply, init_pools, transform, _ = resolve_paged_decoder(
        cfg, attn_kernel=arm)
    params = raw_params if transform is None else \
        jax.eval_shape(transform, raw_params)
    pools = jax.eval_shape(
        lambda: init_pools(cfg, _NUM_BLOCKS, _BLOCK, jnp.float32,
                           int8=int8))
    ex = PagedServeExecutor(paged_apply, None, None, cfg,
                            _ctx.nullcontext, num_slots=_SLOTS,
                            decode_chunk=_CHUNK)
    ragged_jit = (ex._build_ragged_verify_fn if verify
                  else ex._build_ragged_fn)(_RAGGED_T)
    sds = jax.ShapeDtypeStruct
    B, W = _SLOTS, _WIDTH
    i32, f32, u32 = jnp.int32, jnp.float32, jnp.uint32
    spec = (sds((B,), i32),) if verify else ()     # spec_lens operand
    avals = (
        params, sds((B, _RAGGED_T), i32), pools, sds((B, W), i32),
        sds((B,), i32), sds((B,), i32), sds((B,), jnp.bool_),
        sds((B,), jnp.bool_), *spec, sds((B, 2), u32), sds((B,), f32),
        sds((B,), i32), sds((B,), f32))
    return ragged_jit, avals


def _tp_serving_pieces(collective: str = "fp32", tp: int = 2):
    """(decode_jit, avals, mesh, param_specs, pool_specs) for the
    TENSOR-PARALLEL paged decode step: the fused scan-Llama decoder
    wrapped by ``inference.tp_shard.make_tp_paged_apply`` over an
    abstract ``tensor``-axis mesh, on the chosen residual-boundary
    collective arm (``fp32`` psum or the ``int8`` EQuARX quantized
    ring). This is the multi-chip serving hot program — the SPMD pass
    budgets exactly the per-decode-step collectives it is allowed."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh

    from deepspeed_tpu.inference import tp_shard
    from deepspeed_tpu.inference.engine import (
        PagedServeExecutor, resolve_paged_decoder,
    )
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.tiny(dtype=jnp.float32, scan_layers=True)
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    raw_params = jax.eval_shape(
        lambda r, x: model.init(r, x)["params"], jax.random.PRNGKey(0),
        ids)
    _apply, init_pools, transform, decoder = resolve_paged_decoder(
        cfg, attn_kernel="reference")
    permuted = jax.eval_shape(
        lambda p: tp_shard.permute_fused_params_for_tp(
            transform(p), cfg, tp), raw_params)
    param_specs = tp_shard.fused_param_specs(permuted)
    mesh = AbstractMesh((("tensor", tp),))
    tp_apply = tp_shard.make_tp_paged_apply(
        decoder, mesh, tp, collective=collective, param_specs=param_specs)
    pools = jax.eval_shape(
        lambda: init_pools(cfg, _NUM_BLOCKS, _BLOCK, jnp.float32))
    ex = PagedServeExecutor(tp_apply, None, None, cfg,
                            contextlib.nullcontext, num_slots=_SLOTS,
                            decode_chunk=_CHUNK)
    decode_jit = ex._build_decode_fn(_CHUNK)
    sds = jax.ShapeDtypeStruct
    B, W = _SLOTS, _WIDTH
    i32, f32, u32 = jnp.int32, jnp.float32, jnp.uint32
    avals = (
        permuted, sds((B,), i32), pools, sds((B, W), i32), sds((B,), i32),
        sds((B,), i32), sds((), i32), sds((B, 2), u32), sds((B,), f32),
        sds((B,), i32), sds((B,), f32), sds((B,), i32))
    return (decode_jit, avals, mesh, param_specs,
            tp_shard.pool_specs(pools))


def _tiering_pieces():
    """[(name, jit_fn, avals)] for the tiered-KV spill/restore entry
    points over dense and int8 pool layouts — arm-independent (no
    attention in them), traced once alongside the reference arm like
    copy_pool_blocks. Mirrors the engine's jit wrappers: spill is a
    pure gather (nothing donated — the pool survives), restore donates
    the pools exactly like decode/copy."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.paged_attention import (
        gather_pool_blocks, init_paged_pool, scatter_pool_blocks,
    )

    sds = jax.ShapeDtypeStruct
    i32 = jnp.int32
    out = []
    for tag, int8 in (("dense", False), ("int8", True)):
        pools = jax.eval_shape(
            lambda int8=int8: init_paged_pool(
                2, _NUM_BLOCKS, _BLOCK, 2, 8, jnp.float32, int8=int8))
        frames = jax.eval_shape(gather_pool_blocks, pools, sds((2,), i32))
        spill_jit = jax.jit(gather_pool_blocks)
        restore_jit = jax.jit(scatter_pool_blocks, donate_argnums=(0,))
        out.append((f"spill_blocks/{tag}", spill_jit,
                    (pools, sds((2,), i32))))
        out.append((f"restore_blocks/{tag}", restore_jit,
                    (pools, sds((2,), i32), frames)))
    return out


def _train_step_pieces():
    """[(name, fn, avals)] for the ZeRO train-step entry points (dsttrain
    stats pytree ON — the engine's telemetry default), traced over an
    abstract data-8 mesh like the SPMD pass. Budgeting their equation
    counts catches telemetry leaking compute into the compiled step in
    either direction (a stats regression that re-materializes the grad
    tree, or stats silently dropping out of the program)."""
    import jax
    import optax
    from jax.sharding import AbstractMesh

    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
    from deepspeed_tpu.runtime.zero.stages import (
        build_zero_train_step, plan_zero_shardings,
    )
    from deepspeed_tpu.tools.dstlint.spmdpass import _tiny_lm_pieces

    _cfg, loss_fn, params, batch = _tiny_lm_pieces()
    opt = optax.adamw(1e-3)
    opt_abs = jax.eval_shape(opt.init, params)
    out = []
    for stage in (1, 2, 3):
        mesh = AbstractMesh((("data", 8),))
        plan = plan_zero_shardings(params, mesh,
                                   DeepSpeedZeroConfig(stage=stage))
        step = build_zero_train_step(
            loss_fn, opt, plan, mesh,
            communication_data_type="bfloat16" if stage >= 2 else None,
            with_stats=True)
        out.append((f"train_step/stage{stage}", step,
                    (params, opt_abs, batch)))
    return out


def _report(name: str, fn, avals) -> EntryReport:
    import jax

    try:
        jaxpr = jax.make_jaxpr(fn)(*avals)
    except Exception as e:   # report, don't crash the linter (exit 2 is
        # reserved for dstlint's own bugs; a broken entry point is a finding)
        return EntryReport(name, 0, {}, 0, error=f"{type(e).__name__}: {e}")
    counter: Counter = Counter()
    total = _count_jaxpr(jaxpr.jaxpr, counter)
    return EntryReport(name, total, dict(counter),
                       counter.get("pallas_call", 0))


def available_arms() -> List[str]:
    """'reference' always; 'pallas' when the kernel actually runs on
    this toolchain (the same probe the serving tests gate on)."""
    arms = ["reference"]
    try:
        from deepspeed_tpu.ops.paged_attention_kernel import (
            pallas_paged_available,
        )

        if pallas_paged_available():
            arms.append("pallas")
    except Exception:
        pass
    return arms


def trace_entry_points(arms: Optional[List[str]] = None
                       ) -> Dict[str, EntryReport]:
    reports: Dict[str, EntryReport] = {}
    for arm in (arms if arms is not None else available_arms()):
        try:
            (decode_jit, decode_avals, prefill_jit, prefill_avals,
             copy_jit, copy_avals) = _abstract_serving_pieces(arm)
        except Exception as e:
            reports[f"decode_step/{arm}"] = EntryReport(
                f"decode_step/{arm}", 0, {}, 0,
                error=f"{type(e).__name__}: {e}")
            continue
        reports[f"decode_step/{arm}"] = _report(
            f"decode_step/{arm}", decode_jit, decode_avals)
        reports[f"prefill_bucket/{arm}"] = _report(
            f"prefill_bucket/{arm}", prefill_jit, prefill_avals)
        # the unified ragged-step program (chunked prefill), dense AND
        # int8 pool layouts — the chunked session's only hot program,
        # so a silent reference fallback here would cost every step
        for tag, int8 in (("", False), ("_int8", True)):
            name = f"ragged_step{tag}/{arm}"
            try:
                ragged_jit, ragged_avals = _ragged_serving_pieces(
                    arm, int8=int8)
            except Exception as e:
                reports[name] = EntryReport(
                    name, 0, {}, 0, error=f"{type(e).__name__}: {e}")
                continue
            reports[name] = _report(name, ragged_jit, ragged_avals)
        # the speculative ragged-verify variant (serve.speculative):
        # same attention body plus in-device greedy draft verification
        # — a speculation-enabled session's only hot program, budgeted
        # over both pool layouts just like ragged_step
        for tag, int8 in (("", False), ("_int8", True)):
            name = f"ragged_verify{tag}/{arm}"
            try:
                verify_jit, verify_avals = _ragged_serving_pieces(
                    arm, int8=int8, verify=True)
            except Exception as e:
                reports[name] = EntryReport(
                    name, 0, {}, 0, error=f"{type(e).__name__}: {e}")
                continue
            reports[name] = _report(name, verify_jit, verify_avals)
        if arm == "reference":
            reports["copy_pool_blocks"] = _report(
                "copy_pool_blocks", copy_jit, copy_avals)
            for name, fn, avals in _tiering_pieces():
                reports[name] = _report(name, fn, avals)
            for name, fn, avals in _train_step_pieces():
                reports[name] = _report(name, fn, avals)
    return reports


def check_reports(reports: Dict[str, EntryReport],
                  budgets: Optional[dict]) -> List[Finding]:
    """Findings from traced entry reports + the checked-in budget file.
    The pseudo-path ``<jaxpr:NAME>`` keeps jaxpr findings addressable by
    ``--select/--ignore`` and the baseline machinery."""
    findings: List[Finding] = []
    entries = (budgets or {}).get("entries", {})

    def emit(rule, name, msg):
        findings.append(Finding(rule, f"<jaxpr:{name}>", 1, 0, msg))

    for name, rep in reports.items():
        if rep.error is not None:
            emit("jaxpr-budget", name,
                 f"entry point failed to trace: {rep.error}")
            continue
        for prim, n in sorted(rep.primitives.items()):
            if prim in FORBIDDEN_EXACT or any(
                    s in prim for s in FORBIDDEN_SUBSTRINGS):
                emit("jaxpr-forbidden-primitive", name,
                     f"forbidden primitive '{prim}' x{n} in the "
                     f"serving jaxpr — host round-trip per step")
        # EVERY serving entry point on the pallas arm must contain the
        # kernel: the unified ragged kernel serves decode steps,
        # prefill buckets (T > 1 — the old "fallback by design"
        # carve-out is retired) and the ragged mixed-batch step alike,
        # so a missing pallas_call anywhere is a silent reference
        # fallback
        if name.endswith("/pallas") and rep.pallas_calls == 0 \
                and name.split("/")[0] in ("decode_step",
                                           "prefill_bucket",
                                           "ragged_step",
                                           "ragged_step_int8",
                                           "ragged_verify",
                                           "ragged_verify_int8"):
            emit("jaxpr-kernel-arm", name,
                 "Pallas arm traced WITHOUT any pallas_call equation — "
                 "the kernel silently fell back to the reference "
                 "gather (dispatch or version-gate drift)")
        budget = entries.get(name)
        if budget is None:
            emit("jaxpr-budget", name,
                 f"no checked-in equation budget for this entry point "
                 f"(measured {rep.eqns} eqns) — run "
                 f"`bin/dst lint --update-budgets`")
            continue
        ref = budget.get("eqns", 0)
        tol = budget.get("tolerance_pct", DEFAULT_TOLERANCE_PCT)
        if ref and abs(rep.eqns - ref) * 100 > tol * ref:
            emit("jaxpr-budget", name,
                 f"equation count drifted: {rep.eqns} vs budget {ref} "
                 f"(±{tol}%) — a de-dup/fallback regression, or an "
                 f"intentional change (then run "
                 f"`bin/dst lint --update-budgets`)")
    # a budgeted entry point that did not trace at all must fail loudly
    # too: the usual cause is the Pallas arm dropping out on a skewed
    # toolchain — exactly the silent reference fallback this pass exists
    # to catch
    for name in sorted(entries):
        if name not in reports:
            emit("jaxpr-budget", name,
                 "budgeted entry point was NOT traced this run (its "
                 "attention arm is unavailable on this toolchain?) — "
                 "serving would silently fall back to the reference "
                 "arm; fix the toolchain or re-anchor with "
                 "`bin/dst lint --update-budgets`")
    return findings


def load_budgets(path) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def budgets_from_reports(reports: Dict[str, EntryReport],
                         tolerance_pct: int = DEFAULT_TOLERANCE_PCT
                         ) -> dict:
    import jax

    entries = {}
    for name, rep in sorted(reports.items()):
        if rep.error is None:
            entries[name] = {"eqns": rep.eqns,
                             "tolerance_pct": tolerance_pct,
                             "pallas_calls": rep.pallas_calls}
    return {"version": 1, "jax_version": jax.__version__,
            "entries": entries}


def run_jaxpr_pass(budgets_path) -> List[Finding]:
    return check_reports(trace_entry_points(), load_budgets(budgets_path))
